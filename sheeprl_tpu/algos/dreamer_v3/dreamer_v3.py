"""Dreamer-V3 (reference: sheeprl/algos/dreamer_v3/dreamer_v3.py:48-776) —
TPU-native.

The redesign (SURVEY.md §7 hard parts, all addressed here):

- **RSSM + imagination as ``lax.scan``** inside ONE jitted train step per
  gradient step — the reference runs two Python loops over GRU cells
  (dreamer_v3.py:134-145, :235-241).
- **All three optimizations fused**: world model, actor, critic updates (plus
  the Moments percentile sync) execute in a single XLA program; the
  reference dispatches dozens of kernels per phase.
- **DP via shard_map**: the batch axis of the ``[T, B, ...]`` sequence batch
  is split across the mesh's data axis; per-minibatch gradient ``pmean`` and
  the Moments ``all_gather`` (reference ``fabric.all_gather``,
  utils.py:57) are mesh collectives over ICI.
- **Variable replay ratio stays on host**: ``Ratio`` yields G gradient steps
  per policy step; the host loops G times over the jitted step (fixed
  shapes), exactly the reference's structure (dreamer_v3.py:657-693).
- Pixels stay uint8 through the buffer and PCIe; normalization happens
  in-graph (encoder) and in the loss targets.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Sequence

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from sheeprl_tpu.parallel.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.dreamer_v3.agent import (
    WorldModel,
    actor_logprob_entropy,
    build_agent,
    rssm_scan,
    sample_actor_actions,
)
from sheeprl_tpu.algos.dreamer_v3.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v3.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.ops.optim import build_tx
from sheeprl_tpu.data.device_buffer import (
    DeviceReplayBuffer,
    adapt_restored_buffer,
    draw_sequence_batch,
    make_sequential_replay,
)
from sheeprl_tpu.data.prefetch import sampled_batches
from sheeprl_tpu.ops.superstep import (
    fold_sample_key,
    fused_fallback,
    make_superstep_fn,
    periodic_target_ema,
    pregathered,
    reset_fused_fallback_warnings,
)
from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.ops.distributions import (
    Bernoulli,
    Independent,
    MSEDistribution,
    OneHotCategorical,
    SymlogDistribution,
    TwoHotEncodingDistribution,
)
from sheeprl_tpu.obs import (
    log_sps_and_heartbeat,
    telemetry_advance,
    telemetry_register_flops,
    telemetry_run_metrics,
    telemetry_train_window,
)
from sheeprl_tpu.ops.math import MomentsState, compute_lambda_values, init_moments, update_moments
from sheeprl_tpu.resilience import RunResilience
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs

METRIC_ORDER = (
    "Loss/world_model_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Loss/policy_loss",
    "Loss/value_loss",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
)


def make_train_step(
    fabric,
    wm: WorldModel,
    actor,
    critic,
    world_tx,
    actor_tx,
    critic_tx,
    cfg: Dict[str, Any],
    is_continuous: bool,
    actions_dim: Sequence[int],
):
    """The raw (un-jitted) single-gradient-step body over a ``[T, B_local]``
    sequence batch (replaces reference train(), dreamer_v3.py:48-354).
    Returns ``(local_train, use_shard_map)`` — :func:`make_train_fn` wraps it
    in shard_map/jit for the per-step path, :func:`make_fused_train_fn`
    scans it inside one fused superstep dispatch."""
    algo = cfg.algo
    wmc = algo.world_model
    cnn_keys = tuple(algo.cnn_keys.encoder)
    mlp_keys = tuple(algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(algo.mlp_keys.decoder)
    horizon = int(algo.horizon)
    gamma = float(algo.gamma)
    lmbda = float(algo.lmbda)
    ent_coef = float(algo.actor.ent_coef)
    kl_dynamic, kl_representation = float(wmc.kl_dynamic), float(wmc.kl_representation)
    kl_free_nats, kl_regularizer = float(wmc.kl_free_nats), float(wmc.kl_regularizer)
    continue_scale = float(wmc.continue_scale_factor)
    moments_cfg = algo.actor.moments
    data_axis = fabric.data_axis
    multi_device = fabric.world_size > 1
    # Two multi-device modes: pure DP uses shard_map + explicit collectives;
    # a mesh with a `model` axis instead jits the GLOBAL computation and lets
    # GSPMD partition it from the committed input shardings (params placed by
    # fabric.shard_params, batch on the data axis) — explicit pmean/all_gather
    # would be wrong there because the jitted program already has global
    # semantics.
    use_shard_map = multi_device and fabric.model_axis is None

    def pmean(x):
        return lax.pmean(x, data_axis) if use_shard_map else x

    def local_train(
        wm_params,
        actor_params,
        critic_params,
        target_params,
        world_opt,
        actor_opt,
        critic_opt,
        moments_state,
        data,
        key,
    ):
        if use_shard_map:
            key = jax.random.fold_in(key, lax.axis_index(data_axis))
        k_scan, k_img = jax.random.split(key)
        sg = lax.stop_gradient

        T = data["rewards"].shape[0]
        B = data["rewards"].shape[1]
        is_first = data["is_first"].at[0].set(1.0)
        # shift actions right: a_t in the RSSM input is the action LEADING to o_t
        batch_actions = jnp.concatenate(
            [jnp.zeros_like(data["actions"][:1]), data["actions"][:-1]], axis=0
        )
        batch_obs = {k: data[k] for k in cnn_keys + mlp_keys}
        # loss targets (decoder outputs are normalized pixels)
        obs_targets = {k: data[k].astype(jnp.float32) / 255.0 - 0.5 for k in cnn_dec_keys}
        obs_targets.update({k: data[k].astype(jnp.float32) for k in mlp_dec_keys})

        # ---------------- world model step (Eq. 4/5) ---------------- #
        def world_loss_fn(p):
            embedded = wm.apply(p, batch_obs, method=WorldModel.encode)
            hs, zs, post_logits, prior_logits = rssm_scan(wm, p, embedded, batch_actions, is_first, k_scan)
            latents = jnp.concatenate([zs, hs], axis=-1)
            recon = wm.apply(p, latents, method=WorldModel.decode)
            po = {k: MSEDistribution(recon[k], dims=3) for k in cnn_dec_keys}
            po.update({k: SymlogDistribution(recon[k], dims=1) for k in mlp_dec_keys})
            pr = TwoHotEncodingDistribution(wm.apply(p, latents, method=WorldModel.reward_logits), dims=1)
            pc = Independent(Bernoulli(logits=wm.apply(p, latents, method=WorldModel.continue_logits)), 1)
            loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
                po,
                obs_targets,
                pr,
                data["rewards"],
                prior_logits,
                post_logits,
                kl_dynamic,
                kl_representation,
                kl_free_nats,
                kl_regularizer,
                pc,
                1 - data["terminated"],
                continue_scale,
            )
            aux = (hs, zs, post_logits, prior_logits, kl, state_loss, reward_loss, observation_loss, continue_loss)
            return loss, aux

        (rec_loss, aux), wm_grads = jax.value_and_grad(world_loss_fn, has_aux=True)(wm_params)
        hs, zs, post_logits, prior_logits = aux[:4]
        kl, state_loss, reward_loss, observation_loss, continue_loss = aux[4:]
        wm_grads = pmean(wm_grads)
        wm_gnorm = optax.global_norm(wm_grads)
        wm_updates, world_opt = world_tx.update(wm_grads, world_opt, wm_params)
        wm_params = optax.apply_updates(wm_params, wm_updates)

        # ---------------- behaviour learning ---------------- #
        # imagination starts from every (t, b) posterior, flattened
        start_z = sg(zs).reshape(T * B, -1)
        start_h = sg(hs).reshape(T * B, -1)
        true_continue = (1 - data["terminated"]).reshape(T * B, 1)

        def imagine(actor_params, key):
            """Imagination rollout (reference dreamer_v3.py:203-241):
            ``lats[i]`` is the i-th latent, ``acts[i]`` the action sampled at
            it; the scan body advances to ``lats[i+1]`` — H+1 entries."""
            lat0 = jnp.concatenate([start_z, start_h], axis=-1)

            def step(carry, _):
                z, h, lat, key = carry
                key, k_act, k_state = jax.random.split(key, 3)
                action = sample_actor_actions(actor, actor_params, sg(lat), k_act)
                z, h = wm.apply(wm_params, z, h, action, k_state, method=WorldModel.imagination)
                new_lat = jnp.concatenate([z, h], axis=-1)
                return (z, h, new_lat, key), (lat, action)

            _, (lats, acts) = lax.scan(step, (start_z, start_h, lat0, key), None, length=horizon + 1)
            return lats, acts

        def actor_loss_fn(p):
            trajectories, imagined_actions = imagine(p, k_img)  # [H+1, N, L] / [H+1, N, A]

            values = TwoHotEncodingDistribution(critic.apply(critic_params, trajectories), dims=1).mean
            rewards = TwoHotEncodingDistribution(
                wm.apply(wm_params, trajectories, method=WorldModel.reward_logits), dims=1
            ).mean
            continues = Independent(
                Bernoulli(logits=wm.apply(wm_params, trajectories, method=WorldModel.continue_logits)), 1
            ).mode
            continues = jnp.concatenate([true_continue[None], continues[1:]], axis=0)

            lambda_values = compute_lambda_values(rewards[1:], values[1:], continues[1:] * gamma, lmbda)
            discount = sg(jnp.cumprod(continues * gamma, axis=0) / gamma)

            new_moments, (offset, invscale) = update_moments(
                moments_state,
                lambda_values,
                decay=float(moments_cfg.decay),
                max_=float(moments_cfg.max),
                percentile_low=float(moments_cfg.percentile.low),
                percentile_high=float(moments_cfg.percentile.high),
                axis_name=data_axis if use_shard_map else None,
            )
            baseline = values[:-1]
            normed_lambda = (lambda_values - offset) / invscale
            normed_baseline = (baseline - offset) / invscale
            advantage = normed_lambda - normed_baseline
            logp, entropy = actor_logprob_entropy(actor, p, sg(trajectories), sg(imagined_actions))
            if is_continuous:
                objective = advantage
            else:
                objective = logp[..., None][:-1] * sg(advantage)
            policy_loss = -jnp.mean(
                sg(discount[:-1]) * (objective + ent_coef * entropy[..., None][:-1])
            )
            return policy_loss, (trajectories, lambda_values, discount, new_moments)

        (policy_loss, (trajectories, lambda_values, discount, moments_state)), actor_grads = jax.value_and_grad(
            actor_loss_fn, has_aux=True
        )(actor_params)
        actor_grads = pmean(actor_grads)
        actor_gnorm = optax.global_norm(actor_grads)
        actor_updates, actor_opt = actor_tx.update(actor_grads, actor_opt, actor_params)
        actor_params = optax.apply_updates(actor_params, actor_updates)

        # ---------------- critic step (Eq. 10) ---------------- #
        traj_in = sg(trajectories[:-1])
        target_values = TwoHotEncodingDistribution(critic.apply(target_params, traj_in), dims=1).mean

        def critic_loss_fn(p):
            qv = TwoHotEncodingDistribution(critic.apply(p, traj_in), dims=1)
            value_loss = -qv.log_prob(sg(lambda_values)) - qv.log_prob(sg(target_values))
            return jnp.mean(value_loss * sg(discount[:-1]).squeeze(-1))

        value_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(critic_params)
        critic_grads = pmean(critic_grads)
        critic_gnorm = optax.global_norm(critic_grads)
        critic_updates, critic_opt = critic_tx.update(critic_grads, critic_opt, critic_params)
        critic_params = optax.apply_updates(critic_params, critic_updates)

        post_ent = Independent(OneHotCategorical(logits=sg(post_logits)), 1).entropy().mean()
        prior_ent = Independent(OneHotCategorical(logits=sg(prior_logits)), 1).entropy().mean()
        metrics = pmean(
            jnp.stack(
                [
                    rec_loss,
                    observation_loss,
                    reward_loss,
                    state_loss,
                    continue_loss,
                    kl,
                    post_ent,
                    prior_ent,
                    policy_loss,
                    value_loss,
                    wm_gnorm,
                    actor_gnorm,
                    critic_gnorm,
                ]
            )
        )
        return (
            wm_params,
            actor_params,
            critic_params,
            world_opt,
            actor_opt,
            critic_opt,
            moments_state,
            metrics,
        )

    return local_train, use_shard_map


def make_train_fn(
    fabric,
    wm: WorldModel,
    actor,
    critic,
    world_tx,
    actor_tx,
    critic_tx,
    cfg: Dict[str, Any],
    is_continuous: bool,
    actions_dim: Sequence[int],
):
    """One fused gradient step over a ``[T, B_local]`` sequence batch
    (replaces reference train(), dreamer_v3.py:48-354)."""
    local_train, use_shard_map = make_train_step(
        fabric, wm, actor, critic, world_tx, actor_tx, critic_tx, cfg, is_continuous, actions_dim
    )
    if use_shard_map:
        data_axis = fabric.data_axis
        train_fn = shard_map(
            local_train,
            mesh=fabric.mesh,
            in_specs=(P(), P(), P(), P(), P(), P(), P(), P(), P(None, data_axis), P()),
            out_specs=(P(), P(), P(), P(), P(), P(), P(), P()),
        )
    else:
        # single device, or a model-axis mesh: GSPMD partitions the global
        # program from the inputs' committed shardings
        train_fn = local_train
    # donate only optimizer/aux state: param buffers stay un-donated because
    # concurrent readers (async param streaming to the host player, the ema /
    # hard-copy target refresh) may still be in flight when the next train
    # dispatch would otherwise alias over them (observed on the remote chip
    # as spurious INVALID_ARGUMENT errors surfacing at unrelated fetches)
    return jax.jit(train_fn, donate_argnums=(4, 5, 6, 7))


def make_fused_train_fn(
    fabric,
    wm: WorldModel,
    actor,
    critic,
    world_tx,
    actor_tx,
    critic_tx,
    cfg: Dict[str, Any],
    is_continuous: bool,
    actions_dim: Sequence[int],
    gather,
    num_steps: int,
    ctx_spec=None,
    carry_specs=None,
    check_finite: bool = False,
):
    """``num_steps`` gradient steps — replay gather, EMA target refresh and
    train body — fused into ONE donated dispatch (``algo.fused_gradient_steps``;
    see :mod:`sheeprl_tpu.ops.superstep`). On a pure data-parallel mesh the
    whole scan runs under shard_map over ``fabric.data_axis``: the body is
    the same ``local_train`` the per-step sharded path uses (it pmeans
    gradients and metrics), ``gather`` must draw shard-locally
    (``fold_sample_key(..., axis_name=fabric.data_axis)``), and ``ctx_spec``
    gives the sample context's partition spec.

    On a 2-D ``(data, model)`` mesh the scan is one GSPMD program instead:
    pass ``carry_specs=(param_specs, aux_specs)`` (PartitionSpec trees from
    ``fabric.match_partition_rules`` over the exact ``params``/``aux``
    tuples) so the jitted superstep commits params AND their optimizer/EMA
    twins to the model-axis layout and keeps each W2 shard device-resident
    across the window; the body is the same GSPMD ``local_train`` the
    per-step model-axis path uses (no pmean), and ``gather`` must be the
    :func:`~sheeprl_tpu.ops.superstep.pregathered` host stack (the device
    replay ring is pure-DP only).

    The jitted fn's signature is ``(params, aux, counter, sample_ctx, key) ->
    (params, aux, key, metrics[num_steps, len(METRIC_ORDER)])`` with
    ``params = (wm, actor, critic, target_critic)`` (un-donated) and ``aux =
    (world_opt, actor_opt, critic_opt, moments_state)`` (donated).
    ``check_finite=True`` appends the superstep's ``[num_steps]`` finite
    vector (resilience NaN sentinel) as a fifth output."""
    local_train, use_shard_map = make_train_step(
        fabric, wm, actor, critic, world_tx, actor_tx, critic_tx, cfg, is_continuous, actions_dim
    )
    freq = max(1, int(cfg.algo.critic.per_rank_target_network_update_freq))
    tau = float(cfg.algo.critic.tau)

    def train_body(params, aux, batch, key):
        wm_p, a_p, c_p, t_p = params
        wm_p, a_p, c_p, w_o, a_o, c_o, m_s, metrics = local_train(
            wm_p, a_p, c_p, t_p, *aux, batch, key
        )
        return (wm_p, a_p, c_p, t_p), (w_o, a_o, c_o, m_s), metrics

    def pre_step(params, aux, counter):
        # the host loop refreshes the target BEFORE the step on the same
        # schedule (cumulative % freq == 0, hard copy at step 0)
        wm_p, a_p, c_p, t_p = params
        t_p = periodic_target_ema(counter, c_p, t_p, freq, tau)
        return (wm_p, a_p, c_p, t_p), aux

    model_axis = fabric.model_axis if carry_specs is not None else None
    # fabric.aot_cache_dir persists the fused-window executable: the
    # fingerprint digests the algo node + precision (every constant baked
    # into the train graph — lr, tau, horizon, loss scales), so a resume
    # with identical config deserializes in seconds while ANY algo tweak
    # misses cleanly and recompiles
    aot_cache = getattr(fabric, "aot_cache", None)
    cache_fingerprint = None
    if aot_cache is not None:
        from sheeprl_tpu.ops.aotcache import config_fingerprint

        cache_fingerprint = config_fingerprint(
            {"algo": cfg.algo, "precision": str(getattr(fabric, "precision", ""))}
        )
    return make_superstep_fn(
        train_body,
        gather,
        num_steps,
        pre_step=pre_step,
        mesh=fabric.mesh if (use_shard_map or model_axis is not None) else None,
        data_axis=fabric.data_axis if use_shard_map else None,
        ctx_spec=ctx_spec,
        model_axis=model_axis,
        carry_specs=carry_specs,
        check_finite=check_finite,
        aot_cache=aot_cache,
        cache_tag="superstep.dreamer_v3",
        cache_fingerprint=cache_fingerprint,
    )


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)

    # these arguments cannot be changed (reference dreamer_v3.py:366-369)
    cfg.env.frame_stack = 1
    if 2 ** int(np.log2(cfg.env.screen_size)) != cfg.env.screen_size:
        raise ValueError(f"The screen size must be a power of 2, got: {cfg.env.screen_size}")

    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")
    resil = RunResilience(fabric, cfg, log_dir)

    rank = fabric.process_index
    num_envs = int(cfg.env.num_envs)
    # batch split width = the DATA axis only (on a [data, model] mesh the
    # model peers co-own each batch shard rather than adding to it)
    world_size = fabric.data_parallel_size
    num_processes = fabric.num_processes  # hosts: sets the env-step accounting

    envs = build_vector_env(cfg, rank, log_dir if rank == 0 else None, "train", restart_on_exception=True)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape if is_continuous else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)

    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    if (
        len(set(cnn_keys).intersection(cfg.algo.cnn_keys.decoder)) == 0
        and len(set(mlp_keys).intersection(cfg.algo.mlp_keys.decoder)) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    if set(cfg.algo.cnn_keys.decoder) - set(cnn_keys):
        raise RuntimeError("The CNN keys of the decoder must be contained in the encoder ones.")
    if set(cfg.algo.mlp_keys.decoder) - set(mlp_keys):
        raise RuntimeError("The MLP keys of the decoder must be contained in the encoder ones.")
    obs_keys = cnn_keys + mlp_keys

    wm, wm_params, actor, actor_params, critic, critic_params, target_critic_params, player = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"] if cfg.checkpoint.resume_from else None,
        state["actor"] if cfg.checkpoint.resume_from else None,
        state["critic"] if cfg.checkpoint.resume_from else None,
        state["target_critic"] if cfg.checkpoint.resume_from else None,
    )

    world_tx = build_tx(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_tx = build_tx(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_tx = build_tx(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    # shard_params co-shards Adam moments with their params on a model-axis
    # mesh and replicates on a pure-DP one
    world_opt = fabric.shard_params(world_tx.init(jax.device_get(wm_params)))
    actor_opt = fabric.shard_params(actor_tx.init(jax.device_get(actor_params)))
    critic_opt = fabric.shard_params(critic_tx.init(jax.device_get(critic_params)))
    moments_state: MomentsState = init_moments()
    if cfg.checkpoint.resume_from:
        world_opt = fabric.shard_params(jax.tree.map(jnp.asarray, state["world_optimizer"]))
        actor_opt = fabric.shard_params(jax.tree.map(jnp.asarray, state["actor_optimizer"]))
        critic_opt = fabric.shard_params(jax.tree.map(jnp.asarray, state["critic_optimizer"]))
        moments_state = MomentsState(
            low=jnp.asarray(state["moments"]["low"]), high=jnp.asarray(state["moments"]["high"])
        )

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in AGGREGATOR_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    buffer_size = cfg.buffer.size // int(num_envs * num_processes) if not cfg.dry_run else 2
    rb = make_sequential_replay(
        cfg,
        fabric,
        observation_space,
        actions_dim,
        buffer_size,
        num_envs,
        obs_keys,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        seed=cfg.seed,
    )
    use_device_rb = isinstance(rb, DeviceReplayBuffer)
    if cfg.checkpoint.resume_from and cfg.buffer.checkpoint:
        from sheeprl_tpu.utils.checkpoint import select_buffer

        # checkpoints from either buffer mode resume into this run's mode
        rb = adapt_restored_buffer(
            select_buffer(state["rb"], rank, num_processes),
            use_device_rb,
            seed=cfg.seed,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        )

    # EMA update for the target critic (reference dreamer_v3.py:670-675)
    @jax.jit
    def ema(cp, tcp, tau):
        return jax.tree.map(lambda c, t: tau * c + (1 - tau) * t, cp, tcp)

    train_fn = make_train_fn(
        fabric, wm, actor, critic, world_tx, actor_tx, critic_tx, cfg, is_continuous, actions_dim
    )

    # counters (reference dreamer_v3.py:491-516)
    train_step = 0
    last_train = 0
    start_step = state["update"] + 1 if cfg.checkpoint.resume_from else 1
    policy_step = state["update"] * num_envs * num_processes if cfg.checkpoint.resume_from else 0
    last_log = state["last_log"] if cfg.checkpoint.resume_from else 0
    last_checkpoint = state["last_checkpoint"] if cfg.checkpoint.resume_from else 0
    policy_steps_per_update = int(num_envs * num_processes)
    num_updates = int(cfg.algo.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    per_rank_batch_size = int(cfg.algo.per_rank_batch_size)
    sequence_length = int(cfg.algo.per_rank_sequence_length)
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import elastic_per_rank_batch_size

        per_rank_batch_size = elastic_per_rank_batch_size(state["batch_size"], world_size)
        if not cfg.buffer.checkpoint:
            learning_starts += start_step

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if cfg.checkpoint.resume_from:
        ratio.load_state_dict(state["ratio"])

    # ---- fused training supersteps (algo.fused_gradient_steps) ----
    # K > 0 chunks each train window into ceil(G / K) superstep dispatches:
    # replay gather, EMA target refresh and K gradient steps in ONE donated
    # XLA program (ops.superstep). 0 keeps the per-step path above.
    fused_k = int(cfg.algo.get("fused_gradient_steps", 0) or 0)
    if fused_k > 0:
        reset_fused_fallback_warnings()
        if fabric.num_processes > 1:
            fused_fallback(
                "multi_process",
                "algo.fused_gradient_steps cannot span processes "
                f"(num_processes={fabric.num_processes}); falling back to the "
                "per-step train path",
            )
            fused_k = 0
    # model-axis meshes fuse via GSPMD (the scan's carry shardings pin each
    # W2 / Adam / EMA shard device-resident — no shard_map, no pmean);
    # pure-DP meshes keep the explicit-collective shard_map scan
    fused_gspmd = fused_k > 0 and fabric.model_axis is not None
    fused_sharded = fused_k > 0 and fabric.world_size > 1 and not fused_gspmd
    fused_fns: Dict[int, Any] = {}  # one compiled superstep per distinct scan length
    fused_batch_size = per_rank_batch_size * fabric.local_data_parallel_size
    fused_draw_size = fused_batch_size // (fabric.data_parallel_size if fused_sharded else 1)
    fused_axis = fabric.data_axis if fused_sharded else None

    if use_device_rb:

        def fused_gather(ctx, gather_key, i):
            del i  # fresh indices come from the folded per-step key
            bufs, pos, full = ctx
            return draw_sequence_batch(
                bufs,
                pos,
                full,
                fold_sample_key(gather_key, axis_name=fused_axis),
                fused_draw_size,
                sequence_length,
            )

    else:
        fused_gather = pregathered

    fused_ctx_spec = None
    if fused_sharded:
        # ring: (bufs, pos, full) all env-axis sharded; pregathered stack:
        # [n, T, B, ...] sharded along the batch axis
        fused_ctx_spec = (
            (P(fused_axis), P(fused_axis), P(fused_axis))
            if use_device_rb
            else P(None, None, fused_axis)
        )
    elif fused_gspmd:
        # GSPMD scan: the pre-gathered [n, T, B, ...] stack is batch-sharded
        # over the data axis (the model peers co-own each shard)
        fused_ctx_spec = P(None, None, fabric.data_axis)

    # (data, model) superstep carries: one spec per leaf of the exact
    # params/aux tuples the superstep scans over, so optimizer and EMA
    # twins ride model-sharded instead of silently replicated
    fused_carry_specs = None
    if fused_gspmd:
        fused_carry_specs = (
            fabric.match_partition_rules(
                (wm_params, actor_params, critic_params, target_critic_params)
            ),
            fabric.match_partition_rules((world_opt, actor_opt, critic_opt, moments_state)),
        )
        # commit the only still-host carry leaves (the moments scalars) to the
        # mesh now: an uncommitted input in window 1 vs the committed superstep
        # output in window 2 keys a SECOND executable — breaking the
        # zero-recompile-after-window-1 invariant the dryrun asserts
        moments_state = fabric.replicate(moments_state)

    def get_fused_fn(n: int):
        fn = fused_fns.get(n)
        if fn is None:
            fn = fused_fns[n] = make_fused_train_fn(
                fabric,
                wm,
                actor,
                critic,
                world_tx,
                actor_tx,
                critic_tx,
                cfg,
                is_continuous,
                actions_dim,
                fused_gather,
                n,
                ctx_spec=fused_ctx_spec,
                carry_specs=fused_carry_specs,
                check_finite=resil.finite_checks,
            )
        return fn

    def fused_pregather_ctx(n: int):
        # host-buffer fallback: draw the chunk's n batches with the buffer's
        # own RNG (the unfused sampling distribution and stream) and ship
        # them once as a stacked [n, T, B, ...] pytree — batch-axis sharded
        # on a mesh so the shard_map'd superstep slices it without a copy
        from sheeprl_tpu.data.buffers import to_device

        sample = rb.sample(fused_batch_size, sequence_length=sequence_length, n_samples=n)
        batch_axis = fabric.data_axis if (fused_sharded or fused_gspmd) else None
        return to_device(
            {k: (v if k in cnn_keys else v.astype(np.float32)) for k, v in sample.items()},
            sharding=fabric.sharding(None, None, batch_axis) if batch_axis else None,
        )

    key = jax.random.PRNGKey(int(cfg.seed))
    if cfg.checkpoint.resume_from and "rng_key" in state:
        key = jnp.asarray(state["rng_key"])
    if fused_gspmd:
        # same zero-recompile reasoning as the moments above: the superstep
        # returns the key mesh-committed, so it must enter window 1 that way
        key = fabric.replicate(key)
    # action sampling draws from its own stream committed to the player's
    # device, so a host-pinned player (agent.PlayerDV3 device) never waits on
    # a chip round trip for a key
    from sheeprl_tpu.parallel.fabric import put_tree

    player_key = put_tree(jax.random.fold_in(key, 1), player.device)
    if cfg.checkpoint.resume_from and "player_rng_key" in state:
        # continue the pre-resume action-sampling stream
        player_key = put_tree(jnp.asarray(state["player_rng_key"]), player.device)

    # first observation (reference dreamer_v3.py:534-543)
    step_data: Dict[str, np.ndarray] = {}
    obs, _ = envs.reset(seed=cfg.seed)
    prepared = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=num_envs)
    for k in obs_keys:
        step_data[k] = prepared[k][np.newaxis]
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    player.init_states()

    def ckpt_state_fn(completed_update: int) -> Dict[str, Any]:
        return {
            "world_model": jax.device_get(wm_params),
            "actor": jax.device_get(actor_params),
            "critic": jax.device_get(critic_params),
            "target_critic": jax.device_get(target_critic_params),
            "world_optimizer": jax.device_get(world_opt),
            "actor_optimizer": jax.device_get(actor_opt),
            "critic_optimizer": jax.device_get(critic_opt),
            "moments": {
                "low": np.asarray(jax.device_get(moments_state.low)),
                "high": np.asarray(jax.device_get(moments_state.high)),
            },
            "ratio": ratio.state_dict(),
            "update": completed_update,
            "batch_size": per_rank_batch_size * world_size,
            "last_log": last_log,
            "last_checkpoint": last_checkpoint,
            "rng_key": jax.device_get(key),
            "player_rng_key": jax.device_get(player_key),
        }

    def ckpt_path_fn(step: int) -> str:
        return os.path.join(log_dir, "checkpoint", f"ckpt_{step}_{rank}.ckpt")

    def nan_rollback(at_update: int) -> None:
        # restore the full train state (params, target, the three optimizers,
        # return-normalizer moments, replay ratio) from the newest committed
        # checkpoint and fork the sample key away from the stream that
        # diverged; the env/replay side is NOT rolled back — the buffer only
        # ever holds observations, which a NaN train step cannot poison
        nonlocal wm_params, actor_params, critic_params, target_critic_params
        nonlocal world_opt, actor_opt, critic_opt, moments_state, key
        restored = resil.rollback(update=at_update)
        wm_params = resil.place_like(restored["world_model"], wm_params)
        actor_params = resil.place_like(restored["actor"], actor_params)
        critic_params = resil.place_like(restored["critic"], critic_params)
        target_critic_params = resil.place_like(restored["target_critic"], target_critic_params)
        world_opt = resil.place_like(restored["world_optimizer"], world_opt)
        actor_opt = resil.place_like(restored["actor_optimizer"], actor_opt)
        critic_opt = resil.place_like(restored["critic_optimizer"], critic_opt)
        moments_state = MomentsState(
            low=resil.place_like(np.asarray(restored["moments"]["low"]), moments_state.low),
            high=resil.place_like(np.asarray(restored["moments"]["high"]), moments_state.high),
        )
        ratio.load_state_dict(restored["ratio"])
        if "rng_key" in restored:
            key = resil.place_like(restored["rng_key"], key)
        key = resil.resalt_key(key)
        pending_metrics.clear()  # the poisoned window must not reach the logger
        player.update_params(wm_params, actor_params)

    # a crash anywhere in the loop gets the preemption treatment too: the
    # lambdas read the loop's CURRENT policy_step/update at crash time
    resil.arm_crash_guard(
        path_fn=lambda: ckpt_path_fn(policy_step),
        state_fn=lambda: ckpt_state_fn(update - 1),
        replay_buffer_fn=lambda: rb if cfg.buffer.checkpoint else None,
    )
    preempted = False
    cumulative_per_rank_gradient_steps = 0
    pending_metrics: list = []  # device-resident metric vectors, fetched at log time
    # the loop never blocks on the accelerator; the fence keeps it at most a
    # few train blocks ahead so the dispatch/transfer queues stay bounded
    from sheeprl_tpu.parallel.fabric import DispatchFence

    fence = DispatchFence(depth=int(cfg.algo.get("dispatch_fence_depth", 4) or 4))
    # steady-state throughput probe (bench.py): measure from shortly after
    # the gradient path has compiled to the final update, in one process
    from sheeprl_tpu.utils.utils import SteadyStateProbe

    probe = SteadyStateProbe()
    bench_batch = None  # one sampled batch kept for the post-run cost analysis
    bench_superstep = None  # fused path: (fn, chunk, arg shapes) for the same
    last_grad_steps = 0  # heartbeat window: train_fn invocations since last log
    for update in range(start_step, num_updates + 1):
        telemetry_advance(policy_step)
        if resil.preempt_requested():
            # drain the dispatch queue before snapshotting: the state fn's
            # device_get would otherwise fetch mid-flight donated buffers
            fence.drain()
            last_checkpoint = policy_step
            resil.emergency_checkpoint(
                ckpt_path_fn(policy_step),
                ckpt_state_fn(update - 1),
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )
            preempted = True
            break
        probe.mark_warm(update, learning_starts, policy_step, work=cumulative_per_rank_gradient_steps)
        policy_step += num_envs * num_processes

        with timer("Time/env_interaction_time"):
            if update <= learning_starts and cfg.checkpoint.resume_from is None:
                real_actions = actions = np.array(envs.action_space.sample())
                if not is_continuous:
                    actions = np.concatenate(
                        [
                            np.eye(act_dim, dtype=np.float32)[act.reshape(-1)]
                            for act, act_dim in zip(actions.reshape(len(actions_dim), -1), actions_dim)
                        ],
                        axis=-1,
                    )
            else:
                player_key, action_key = jax.random.split(player_key)
                prepared = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=num_envs)
                mask = {k: v for k, v in prepared.items() if k.startswith("mask")}
                actions = player.get_actions(prepared, action_key, mask=mask or None)
                if is_continuous:
                    real_actions = actions
                else:
                    splits = np.cumsum(actions_dim)[:-1]
                    real_actions = np.stack(
                        [p.argmax(-1) for p in np.split(actions, splits, axis=-1)], axis=-1
                    )
                    if real_actions.shape[-1] == 1 and not is_multidiscrete:
                        real_actions = real_actions[..., 0]

            step_data["actions"] = np.asarray(actions, np.float32).reshape(1, num_envs, -1)
            rb.add(step_data, validate_args=cfg.buffer.validate_args)

            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        step_data["is_first"] = np.zeros_like(step_data["terminated"])
        if "restart_on_exception" in infos:
            for i, roe in enumerate(np.asarray(infos["restart_on_exception"]).reshape(-1)):
                if roe and not dones[i]:
                    # patch the last stored step to a truncation and restart the
                    # episode (reference dreamer_v3.py:591-604)
                    if use_device_rb:
                        rb.amend_last(i, terminated=0.0, truncated=1.0, is_first=0.0)
                    else:
                        sub = rb.buffer[i]
                        last_idx = (sub._pos - 1) % sub.buffer_size
                        sub["terminated"][last_idx] = 0.0
                        sub["truncated"][last_idx] = 1.0
                        sub["is_first"][last_idx] = 0.0
                    step_data["is_first"][0, i] = 1.0

        if cfg.metric.log_level > 0 and "final_info" in infos:
            ep = infos["final_info"].get("episode")
            if ep is not None:
                for i in np.nonzero(ep.get("_r", []))[0]:
                    aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                    aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                    print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

        # the final obs of finished episodes (SAME_STEP autoreset provides it)
        real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items()}
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    for k, v in final_obs.items():
                        real_next_obs[k][idx] = v

        prepared_next = prepare_obs(next_obs, cnn_keys=cnn_keys, num_envs=num_envs)
        for k in obs_keys:
            step_data[k] = prepared_next[k][np.newaxis]
        obs = next_obs

        rewards = np.asarray(rewards, np.float32).reshape(1, num_envs, 1)
        step_data["terminated"] = np.asarray(terminated, np.float32).reshape(1, num_envs, 1)
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape(1, num_envs, 1)
        step_data["rewards"] = clip_rewards_fn(rewards)

        dones_idxes = dones.nonzero()[0].tolist()
        if dones_idxes:
            # store the terminal transition with the true final obs, zero
            # action, then reset per-env episode state
            # (reference dreamer_v3.py:635-653)
            prepared_final = prepare_obs(
                {k: real_next_obs[k][dones_idxes] for k in obs_keys},
                cnn_keys=cnn_keys,
                num_envs=len(dones_idxes),
            )
            reset_data = {k: prepared_final[k][np.newaxis] for k in obs_keys}
            reset_data["terminated"] = step_data["terminated"][:, dones_idxes]
            reset_data["truncated"] = step_data["truncated"][:, dones_idxes]
            reset_data["actions"] = np.zeros((1, len(dones_idxes), int(np.sum(actions_dim))), np.float32)
            reset_data["rewards"] = step_data["rewards"][:, dones_idxes]
            reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
            rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)

            step_data["rewards"][:, dones_idxes] = 0.0
            step_data["terminated"][:, dones_idxes] = 0.0
            step_data["truncated"][:, dones_idxes] = 0.0
            step_data["is_first"][:, dones_idxes] = 1.0
            player.init_states(dones_idxes)

        # ---------------- training ---------------- #
        if update >= learning_starts:
            per_rank_gradient_steps = ratio(policy_step / num_processes)
            if per_rank_gradient_steps > 0 and fused_k > 0:
                # fused path: the whole window is ceil(G / K) superstep
                # dispatches — gather + EMA + train scanned inside XLA
                window_dispatches = 0
                window_finite: list = []  # [chunk] bool vectors, one per dispatch
                with timer("Time/train_time"):
                    n_left = per_rank_gradient_steps
                    while n_left > 0:
                        chunk = min(fused_k, n_left)
                        n_left -= chunk
                        superstep = get_fused_fn(chunk)
                        ctx = (
                            rb.superstep_inputs(sequence_length)
                            if use_device_rb
                            else fused_pregather_ctx(chunk)
                        )
                        params = (wm_params, actor_params, critic_params, target_critic_params)
                        aux = (world_opt, actor_opt, critic_opt, moments_state)
                        counter = jnp.int32(cumulative_per_rank_gradient_steps)
                        if cumulative_per_rank_gradient_steps == 0:
                            # shapes only; scaled so the heartbeat's MFU stays
                            # per-gradient-step (invocations count steps)
                            telemetry_register_flops(
                                superstep, params, aux, counter, ctx, key, scale=1.0 / chunk
                            )
                        if probe.active and bench_superstep is None:
                            # ShapeDtypeStructs, NOT live refs — aux is about
                            # to be donated and deleted by the dispatch
                            shapes = jax.tree.map(
                                lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)),
                                (params, aux, counter, ctx, key),
                            )
                            bench_superstep = (superstep, chunk, shapes)
                        if resil.finite_checks:
                            # the sentinel rides the same dispatch: a [chunk]
                            # finite vector instead of an extra program
                            params, aux, key, metrics, chunk_finite = superstep(
                                params, aux, counter, ctx, key
                            )
                            window_finite.append(chunk_finite)
                        else:
                            params, aux, key, metrics = superstep(params, aux, counter, ctx, key)
                        wm_params, actor_params, critic_params, target_critic_params = params
                        world_opt, actor_opt, critic_opt, moments_state = aux
                        cumulative_per_rank_gradient_steps += chunk
                        window_dispatches += 1
                        if cfg.metric.log_level > 0:
                            # [chunk, len(METRIC_ORDER)] on device, one fetch
                            # per log interval for the whole window
                            pending_metrics.append(metrics)
                    if not timer.disabled:
                        jax.block_until_ready(wm_params)
                    train_step += num_processes
                telemetry_train_window(window_dispatches, per_rank_gradient_steps)
                player.update_params(wm_params, actor_params)
                fence.push(metrics)
                # one tiny fetch per window: the [chunk] finite vectors the
                # superstep computed in-dispatch, reduced on the host
                if not resil.window_ok(
                    all(bool(np.all(np.asarray(jax.device_get(f)))) for f in window_finite),
                    update,
                ):
                    nan_rollback(update)
                    continue
            elif per_rank_gradient_steps > 0:
                # each process samples its share of the global batch
                # batch i+1's host->HBM transfer overlaps gradient step i
                batches = sampled_batches(
                    rb,
                    per_rank_batch_size * fabric.local_data_parallel_size,
                    sequence_length,
                    per_rank_gradient_steps,
                    cnn_keys,
                    fabric,
                    prefetch=int(cfg.buffer.get("prefetch", 0) or 0),
                )
                window_ema_dispatches = 0
                with timer("Time/train_time"):
                    for i, batch in enumerate(batches):
                        if (
                            cumulative_per_rank_gradient_steps
                            % cfg.algo.critic.per_rank_target_network_update_freq
                            == 0
                        ):
                            tau = 1.0 if cumulative_per_rank_gradient_steps == 0 else float(cfg.algo.critic.tau)
                            target_critic_params = ema(critic_params, target_critic_params, tau)
                            window_ema_dispatches += 1
                        key, train_key = jax.random.split(key)
                        (
                            wm_params,
                            actor_params,
                            critic_params,
                            world_opt,
                            actor_opt,
                            critic_opt,
                            moments_state,
                            metrics,
                        ) = train_fn(
                            wm_params,
                            actor_params,
                            critic_params,
                            target_critic_params,
                            world_opt,
                            actor_opt,
                            critic_opt,
                            moments_state,
                            batch,
                            train_key,
                        )
                        cumulative_per_rank_gradient_steps += 1
                        if probe.active and bench_batch is None:
                            bench_batch = batch
                        if cumulative_per_rank_gradient_steps == 1:
                            # shapes only — the batch itself is not pinned
                            telemetry_register_flops(
                                train_fn,
                                wm_params,
                                actor_params,
                                critic_params,
                                target_critic_params,
                                world_opt,
                                actor_opt,
                                critic_opt,
                                moments_state,
                                batch,
                                train_key,
                            )
                    if not timer.disabled:
                        # only when timing: wait so Time/train_time measures
                        # the chip, not the async dispatch
                        jax.block_until_ready(wm_params)
                    train_step += num_processes
                # per-step dispatch shape: one train call per gradient step,
                # plus the on-device gather per batch and the EMA refreshes
                telemetry_train_window(
                    per_rank_gradient_steps * (2 if use_device_rb else 1) + window_ema_dispatches,
                    per_rank_gradient_steps,
                )
                player.update_params(wm_params, actor_params)
                fence.push(metrics)
                if cfg.metric.log_level > 0:
                    # keep the metric vector ON DEVICE: fetching here would
                    # serialize the async train dispatch against the host
                    # loop (one chip round trip per train block); the queue
                    # drains at log time instead
                    pending_metrics.append(metrics)
                if resil.finite_checks and not resil.check_finite(
                    # the window's LAST metric vector: NaNs in params propagate
                    # to every later loss, so one fetch per window suffices
                    np.asarray(jax.device_get(metrics)),
                    update,
                ):
                    nan_rollback(update)
                    continue

        # ---------------- logging ---------------- #
        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or update == num_updates):
            if pending_metrics:
                # stack ON DEVICE first: one transfer for the whole window
                # instead of one round trip per train block; fused entries
                # are already [chunk, |METRIC_ORDER|] blocks
                stacked = jnp.concatenate(
                    [m if m.ndim == 2 else m[None] for m in pending_metrics], axis=0
                )
                for metrics_np in np.asarray(jax.device_get(stacked)):
                    for name, value in zip(METRIC_ORDER, metrics_np):
                        aggregator.update(name, float(value))
                pending_metrics.clear()
            metrics_dict = aggregator.compute()
            logger.log_metrics(metrics_dict, policy_step)
            telemetry_run_metrics(metrics_dict)
            aggregator.reset()
            if policy_step > 0:
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * num_processes / policy_step},
                    policy_step,
                )
            log_sps_and_heartbeat(
                logger,
                policy_step=policy_step,
                env_steps=(policy_step - last_log) / num_processes * cfg.env.action_repeat,
                train_steps=train_step - last_train,
                train_invocations=cumulative_per_rank_gradient_steps - last_grad_steps,
            )
            last_log = policy_step
            last_train = train_step
            last_grad_steps = cumulative_per_rank_gradient_steps

        # ---------------- checkpoint ---------------- #
        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            update == num_updates and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path_fn(policy_step),
                state=ckpt_state_fn(update),
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )

    # drain materializes the newest fence marker too — an actual device sync
    # on the tunnel (block_until_ready is advisory on the axon client)
    fence.drain()

    def _bench_extra():
        # per-train-step FLOPs for bench.py's MFU: one AOT cost-analysis
        # compile, paid after the clock stopped
        from sheeprl_tpu.utils.profiler import compiled_flops

        if bench_superstep is not None:
            fn, chunk, shapes = bench_superstep
            flops = compiled_flops(fn, *shapes)
            return {"flops_per_train_step": flops / chunk} if flops else {}
        if bench_batch is None:
            return {}

        flops = compiled_flops(
            train_fn,
            wm_params,
            actor_params,
            critic_params,
            target_critic_params,
            world_opt,
            actor_opt,
            critic_opt,
            moments_state,
            bench_batch,
            key,
        )
        return {"flops_per_train_step": flops} if flops else {}

    probe.finish(policy_step, work=cumulative_per_rank_gradient_steps, extra=_bench_extra)
    # land any in-flight async param stream so the final evaluation and
    # model registration use the last update's weights
    player.flush_stream_attrs()
    envs.close()
    if fabric.is_global_zero and cfg.algo.run_test and not preempted:
        test(player, fabric, cfg, log_dir, greedy=False)
    logger.finalize()
    resil.close()
    if preempted:
        resil.exit_preempted()
