"""Dreamer-V3 evaluation entrypoint (reference: sheeprl/algos/dreamer_v3/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
from sheeprl_tpu.algos.dreamer_v3.utils import test
from sheeprl_tpu.utils.evaluation import dreamer_family_evaluate
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms="dreamer_v3")
def evaluate(fabric, cfg: Dict[str, Any], state: Dict[str, Any]) -> None:
    dreamer_family_evaluate(
        fabric, cfg, state, build_agent, test,
        state_keys=("world_model", "actor", "critic", "target_critic"),
    )
