"""DroQ agent (reference: sheeprl/algos/droq/agent.py:20-266).

The DroQ critic is the SAC critic with Dropout + LayerNorm
(https://arxiv.org/abs/2110.02034); the ensemble stays a vmapped stack.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import gymnasium

from sheeprl_tpu.algos.sac.agent import (  # noqa: F401  (re-exported API)
    SACActor,
    SACAgent as DROQAgent,
    SACCritic as DROQCritic,
    SACPlayer,
    actor_action_and_log_prob,
    actor_greedy_action,
    critic_ensemble_apply,
)
from sheeprl_tpu.algos.sac.agent import build_agent as sac_build_agent


def build_agent(
    fabric: Any,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    action_space: gymnasium.spaces.Box,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[DROQAgent, SACPlayer]:
    return sac_build_agent(
        fabric,
        cfg,
        obs_space,
        action_space,
        agent_state,
        critic_kwargs={
            "dropout": float(cfg["algo"]["critic"].get("dropout", 0.0)),
            "layer_norm": True,
        },
    )
