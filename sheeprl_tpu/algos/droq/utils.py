"""DroQ helpers (reference: sheeprl/algos/droq/utils.py — DroQ shares SAC's
observation/test plumbing and registers the same single ``agent`` model)."""

from __future__ import annotations

from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, prepare_obs, test  # noqa: F401

MODELS_TO_REGISTER = {"agent"}

__all__ = ["AGGREGATOR_KEYS", "MODELS_TO_REGISTER", "prepare_obs", "test"]


def log_models_from_checkpoint(fabric, cfg, state, artifacts_dir):
    """Pickle this algorithm's registered sub-models from a checkpoint
    (reference per-algo log_models_from_checkpoint; shared body in
    utils/model_manager.py)."""
    from sheeprl_tpu.utils.model_manager import log_models_from_checkpoint as _log

    return _log(state, sorted(MODELS_TO_REGISTER), artifacts_dir)
