from sheeprl_tpu.algos.droq import droq  # noqa: F401  (registers the algorithm)
from sheeprl_tpu.algos.droq import evaluate  # noqa: F401  (registers the evaluation)
