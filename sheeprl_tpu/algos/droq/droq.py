"""DroQ (reference: sheeprl/algos/droq/droq.py:31-412) — TPU-native.

SAC with Dropout-Q critics and a high replay ratio (20). Per update: G
critic-only gradient steps (shared TD target, per-critic MSE, target EMA
after every step — reference droq.py:96-119), then ONE actor+alpha update on
a separate batch using the ensemble MEAN Q (droq.py:121-139). The whole G
loop is a ``lax.scan`` inside one jitted shard_map step; dropout rngs are
per-critic, per-step.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from sheeprl_tpu.ops.optim import build_tx
from sheeprl_tpu.parallel.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.droq.agent import (
    actor_action_and_log_prob,
    build_agent,
    critic_ensemble_apply,
)
from sheeprl_tpu.algos.sac.loss import entropy_loss, policy_loss
from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.data.device_buffer import draw_transition_batch
from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.obs import telemetry_train_window
from sheeprl_tpu.ops.superstep import fold_sample_key, fused_fallback, reset_fused_fallback_warnings
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, SteadyStateProbe, gradient_step_chunks, save_configs, weighted_chunk_metrics


def _ensemble_apply_dropout(critic, stacked_params, obs, action, key, n_critics):
    keys = jax.random.split(key, n_critics)
    qs = jax.vmap(
        lambda p, k: critic.apply(p, obs, action, deterministic=False, rngs={"dropout": k})
    )(stacked_params, keys)
    return jnp.moveaxis(qs[..., 0], 0, -1)  # [B, n_critics]


def make_train_fn(fabric, agent, actor_tx, critic_tx, alpha_tx, cfg, *, fused_length=None, fused_batch_size=None):
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    target_entropy = agent.target_entropy
    n_critics = agent.num_critics
    actor, critic = agent.actor, agent.critic
    use_dropout = float(cfg.algo.critic.get("dropout", 0.0)) > 0.0
    data_axis = fabric.data_axis
    multi_device = fabric.world_size > 1
    # fused superstep mode (algo.fused_gradient_steps): `critic_data` becomes
    # the device ring's (bufs, pos, full) context and every scanned critic
    # step draws its own batch on device — gather, TD update and target EMA
    # in ONE dispatch per chunk. The actor update stays one dispatch.
    fused = fused_length is not None
    if fused and multi_device:
        # fused + mesh = pure data-parallel shard_map (main() has already
        # fallen back for model_axis / multi-process runs): the ring context
        # arrives env-axis sharded and every device scans its own in-graph
        # draws of a per-shard batch
        if fabric.model_axis is not None or fabric.num_processes != 1:
            raise ValueError(
                "fused in-scan gather supersteps need a single-process pure "
                f"data-parallel run; got model_axis={fabric.model_axis!r}, "
                f"num_processes={fabric.num_processes}"
            )
        if int(fused_batch_size) % fabric.data_parallel_size:
            raise ValueError(
                f"fused_batch_size ({fused_batch_size}) must divide by "
                f"data_parallel_size ({fabric.data_parallel_size})"
            )
    fused_draw_size = (
        int(fused_batch_size) // (fabric.data_parallel_size if multi_device else 1)
        if fused
        else None
    )

    def pmean(x):
        return lax.pmean(x, data_axis) if multi_device else x

    def q_apply(params, obs, action, key):
        if use_dropout:
            return _ensemble_apply_dropout(critic, params, obs, action, key, n_critics)
        return critic_ensemble_apply(critic, params, obs, action)

    def local_critic_scan(
        actor_params, critic_params, target_params, log_alpha,
        critic_opt, critic_data, key,
    ):
        if multi_device:
            key = jax.random.fold_in(key, lax.axis_index(data_axis))
        alpha = jnp.exp(log_alpha)

        def critic_step(carry, batch):
            critic_params, target_params, critic_opt, key = carry
            key, k_next, k_drop_t, k_drop = jax.random.split(key, 4)
            next_actions, next_logpi = actor_action_and_log_prob(
                actor, actor_params, batch["next_observations"], k_next
            )
            q_next = q_apply(target_params, batch["next_observations"], next_actions, k_drop_t)
            min_q_next = jnp.min(q_next, axis=-1, keepdims=True) - alpha * next_logpi
            target = lax.stop_gradient(
                batch["rewards"] + (1 - batch["terminated"]) * gamma * min_q_next
            )

            def loss_fn(p):
                q = q_apply(p, batch["observations"], batch["actions"], k_drop)
                # per-critic MSE against the shared target (Alg. 2 line 8)
                return sum(
                    jnp.mean(jnp.square(q[..., i : i + 1] - target)) for i in range(n_critics)
                )

            qf_loss, grads = jax.value_and_grad(loss_fn)(critic_params)
            grads = pmean(grads)
            updates, critic_opt = critic_tx.update(grads, critic_opt, critic_params)
            critic_params = optax.apply_updates(critic_params, updates)
            # EMA after every critic step (reference droq.py:119)
            target_params = jax.tree.map(
                lambda c, t: tau * c + (1 - tau) * t, critic_params, target_params
            )
            return (critic_params, target_params, critic_opt, key), qf_loss

        if fused:
            bufs, pos, full = critic_data

            def fused_critic_step(carry, _):
                # draw key = carried key folded with the sample salt, so the
                # index noise stays decorrelated from the dropout/gradient
                # noise critic_step derives from the same key via split
                # the carried key was already folded with axis_index on a
                # mesh, so the salted draw is per-shard decorrelated for free
                batch = draw_transition_batch(
                    bufs, pos, full, fold_sample_key(carry[-1]), fused_draw_size
                )
                return critic_step(carry, batch)

            (critic_params, target_params, critic_opt, key), qf_losses = lax.scan(
                fused_critic_step,
                (critic_params, target_params, critic_opt, key),
                None,
                length=int(fused_length),
            )
        else:
            (critic_params, target_params, critic_opt, key), qf_losses = lax.scan(
                critic_step, (critic_params, target_params, critic_opt, key), critic_data
            )
        return critic_params, target_params, critic_opt, pmean(qf_losses.mean())

    def local_actor_update(
        actor_params, critic_params, log_alpha, actor_opt, alpha_opt, actor_batch, key,
    ):
        # one actor + alpha update per env update (reference droq.py:121-139)
        if multi_device:
            key = jax.random.fold_in(key, lax.axis_index(data_axis))
        alpha = jnp.exp(log_alpha)
        key, k_actor, k_drop = jax.random.split(key, 3)

        def actor_loss_fn(p):
            actions, logpi = actor_action_and_log_prob(actor, p, actor_batch["observations"], k_actor)
            q = q_apply(critic_params, actor_batch["observations"], actions, k_drop)
            mean_q = jnp.mean(q, axis=-1, keepdims=True)  # DroQ: mean, not min
            return policy_loss(alpha, logpi, mean_q), logpi

        (a_loss, logpi), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(actor_params)
        actor_grads = pmean(actor_grads)
        updates, actor_opt = actor_tx.update(actor_grads, actor_opt, actor_params)
        actor_params = optax.apply_updates(actor_params, updates)

        alpha_grad = pmean(
            jax.grad(lambda la: entropy_loss(la, lax.stop_gradient(logpi), target_entropy))(log_alpha)
        )
        updates, alpha_opt = alpha_tx.update(alpha_grad, alpha_opt, log_alpha)
        log_alpha = optax.apply_updates(log_alpha, updates)
        alpha_l = entropy_loss(log_alpha, logpi, target_entropy)
        return actor_params, log_alpha, actor_opt, alpha_opt, pmean(jnp.stack([a_loss, alpha_l]))

    critic_fn, actor_fn = local_critic_scan, local_actor_update
    if multi_device:
        # critic_data slot: pre-gathered [G, B, ...] stacks shard along the
        # batch axis; a fused ring context (bufs, pos, full) shards along the
        # env axis, matching the DeviceReplayBuffer's placement
        critic_data_spec = (
            (P(data_axis), P(data_axis), P(data_axis)) if fused else P(None, data_axis)
        )
        critic_fn = shard_map(
            local_critic_scan,
            mesh=fabric.mesh,
            in_specs=(P(), P(), P(), P(), P(), critic_data_spec, P()),
            out_specs=(P(), P(), P(), P()),
        )
        actor_fn = shard_map(
            local_actor_update,
            mesh=fabric.mesh,
            in_specs=(P(), P(), P(), P(), P(), P(data_axis), P()),
            out_specs=(P(), P(), P(), P(), P()),
        )
    # Split into two jits so the critic scan can run in fixed-size chunks
    # (utils.gradient_step_chunks — scan length changes recompile) while the
    # actor update stays exactly once per env update like the reference.
    # Donate only optimizer state: param buffers stay un-donated because
    # concurrent readers (async param streaming to the host player, the EMA)
    # may still be in flight when the next dispatch would alias over them.
    return (
        jax.jit(critic_fn, donate_argnums=(4,)),
        jax.jit(actor_fn, donate_argnums=(3, 4)),
    )


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    rank = fabric.process_index
    world_size = fabric.data_parallel_size  # batch-split width: the data axis (= device count on a 1-D mesh)
    num_processes = fabric.num_processes
    num_envs = int(cfg.env.num_envs)

    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)

    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")

    envs = build_vector_env(cfg, rank, log_dir if rank == 0 else None, "train")
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the DroQ agent")
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    if len(mlp_keys) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")

    agent, player = build_agent(
        fabric, cfg, observation_space, action_space, state["agent"] if cfg.checkpoint.resume_from else None
    )

    critic_tx = build_tx(cfg.algo.critic.optimizer)
    actor_tx = build_tx(cfg.algo.actor.optimizer)
    alpha_tx = build_tx(cfg.algo.alpha.optimizer)
    critic_opt = fabric.replicate(critic_tx.init(jax.device_get(agent.critic_params)))
    actor_opt = fabric.replicate(actor_tx.init(jax.device_get(agent.actor_params)))
    alpha_opt = fabric.replicate(alpha_tx.init(jax.device_get(agent.log_alpha)))
    if cfg.checkpoint.resume_from:
        critic_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["qf_optimizer"]))
        actor_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["actor_optimizer"]))
        alpha_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["alpha_optimizer"]))

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in AGGREGATOR_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    buffer_size = cfg.buffer.size // int(num_envs * num_processes) if not cfg.dry_run else 1
    # high replay ratio is DroQ's defining regime — exactly where re-staging
    # every resampled batch over the link dominates; the HBM ring uploads each
    # transition once and gathers on-chip (buffer.device=auto)
    from sheeprl_tpu.data.device_buffer import (
        DeviceReplayBuffer,
        adapt_restored_buffer,
        make_transition_replay,
    )

    rb = make_transition_replay(
        cfg,
        fabric,
        observation_space,
        stored_keys=mlp_keys,
        actions_dim=action_space.shape,
        buffer_size=buffer_size,
        num_envs=num_envs,
        obs_keys=("observations",),
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        seed=cfg.seed,
        store_next_obs=True,
    )
    use_device_rb = isinstance(rb, DeviceReplayBuffer)
    if cfg.checkpoint.resume_from and cfg.buffer.checkpoint:
        from sheeprl_tpu.utils.checkpoint import select_buffer

        rb = adapt_restored_buffer(
            select_buffer(state["rb"], rank, num_processes),
            use_device_rb,
            seed=cfg.seed,
            mode="transition",
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        )

    # fused supersteps (algo.fused_gradient_steps): K > 0 moves the replay
    # gather INSIDE the scanned critic chunk so one train window of G critic
    # steps issues ceil(G / K) dispatches (the actor update stays one)
    fused_k = int(cfg.algo.get("fused_gradient_steps", 0) or 0)
    if fused_k > 0:
        reset_fused_fallback_warnings()
        if not use_device_rb:
            fused_fallback(
                "host_buffer",
                "algo.fused_gradient_steps needs the device replay buffer (buffer.device) to "
                "draw batches inside the scanned chunk; the host-buffer path already runs each "
                "chunk as one dispatch. Falling back to the per-chunk host gather.",
            )
            fused_k = 0
        elif fabric.num_processes > 1:
            fused_fallback(
                "multi_process",
                "algo.fused_gradient_steps cannot span processes "
                f"(num_processes={fabric.num_processes}); falling back to the per-chunk gather path.",
            )
            fused_k = 0
        elif fabric.world_size > 1 and fabric.model_axis is not None:
            fused_fallback(
                "model_axis",
                "algo.fused_gradient_steps is pure data-parallel, but this run shards params "
                f"over model_axis={fabric.model_axis!r}; falling back to the per-chunk gather path.",
            )
            fused_k = 0

    critic_fn, actor_fn = make_train_fn(fabric, agent, actor_tx, critic_tx, alpha_tx, cfg)

    train_step = 0
    last_train = 0
    start_step = state["update"] + 1 if cfg.checkpoint.resume_from else 1
    policy_step = state["update"] * num_envs * num_processes if cfg.checkpoint.resume_from else 0
    last_log = state["last_log"] if cfg.checkpoint.resume_from else 0
    last_checkpoint = state["last_checkpoint"] if cfg.checkpoint.resume_from else 0
    policy_steps_per_update = int(num_envs * num_processes)
    num_updates = int(cfg.algo.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    per_rank_batch_size = int(cfg.algo.per_rank_batch_size)
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import elastic_per_rank_batch_size

        per_rank_batch_size = elastic_per_rank_batch_size(state["batch_size"], world_size)
        if not cfg.buffer.checkpoint:
            learning_starts += start_step

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if cfg.checkpoint.resume_from:
        ratio.load_state_dict(state["ratio"])

    # per scanned length one compiled critic superstep (chunking keeps the set
    # of lengths at {fused_k} ∪ {possible remainders}); built lazily AFTER the
    # elastic resume may have rewritten per_rank_batch_size
    fused_critic_fns: Dict[int, Any] = {}

    def get_fused_critic_fn(n: int):
        fn = fused_critic_fns.get(n)
        if fn is None:
            fn = make_train_fn(
                fabric,
                agent,
                actor_tx,
                critic_tx,
                alpha_tx,
                cfg,
                fused_length=n,
                fused_batch_size=per_rank_batch_size * fabric.local_data_parallel_size,
            )[0]
            fused_critic_fns[n] = fn
        return fn

    key = jax.random.PRNGKey(int(cfg.seed))
    # action keys live on the player's device so a host-pinned player
    # never blocks on a chip round trip per env step
    from sheeprl_tpu.parallel.fabric import put_tree as _put_tree

    player_key = _put_tree(jax.random.fold_in(key, 1), player.device)
    obs, _ = envs.reset(seed=cfg.seed)
    cumulative_per_rank_gradient_steps = 0
    step_data: Dict[str, np.ndarray] = {}
    # steady-state throughput probe (SHEEPRL_TPU_BENCH_JSON contract)
    probe = SteadyStateProbe()
    for update in range(start_step, num_updates + 1):
        probe.mark_warm(update, learning_starts, policy_step, work=cumulative_per_rank_gradient_steps)
        policy_step += num_envs * num_processes

        with timer("Time/env_interaction_time"):
            if update <= learning_starts:
                actions = envs.action_space.sample()
            else:
                player_key, action_key = jax.random.split(player_key)
                np_obs = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs)
                actions = player.get_actions(np_obs, action_key)
            next_obs, rewards, terminated, truncated, infos = envs.step(
                np.asarray(actions).reshape(envs.action_space.shape)
            )

        if cfg.metric.log_level > 0 and "final_info" in infos:
            ep = infos["final_info"].get("episode")
            if ep is not None:
                for i in np.nonzero(ep.get("_r", []))[0]:
                    aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                    aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                    print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

        real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items()}
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    for k, v in final_obs.items():
                        real_next_obs[k][idx] = v

        step_data["terminated"] = np.asarray(terminated, np.float32).reshape(1, num_envs, 1)
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape(1, num_envs, 1)
        step_data["actions"] = np.asarray(actions, np.float32).reshape(1, num_envs, -1)
        step_data["observations"] = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs)[np.newaxis]
        step_data["next_observations"] = prepare_obs(
            real_next_obs, mlp_keys=mlp_keys, num_envs=num_envs
        )[np.newaxis]
        step_data["rewards"] = np.asarray(rewards, np.float32).reshape(1, num_envs, 1)
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
        obs = next_obs

        if update >= learning_starts:
            per_rank_gradient_steps = ratio(policy_step / num_processes)
            if per_rank_gradient_steps > 0:
                from sheeprl_tpu.data.buffers import to_device

                # G critic steps in fixed-size scan chunks (every distinct
                # scan length is a fresh XLA compile — gradient_step_chunks);
                # sampling/staging stays OUTSIDE the train timer like the
                # other SAC-family loops
                qf_losses = []
                window_dispatches = 0
                chunk_cfg = {"gradient_steps_chunk": fused_k} if fused_k > 0 else cfg.algo
                for chunk_steps in gradient_step_chunks(per_rank_gradient_steps, chunk_cfg):
                    chunk_fn = critic_fn
                    if fused_k > 0:
                        # in-scan gather: the whole chunk is ONE dispatch;
                        # only the [E] pos/full cursors cross the link
                        critic_data = rb.superstep_inputs()
                        chunk_fn = get_fused_critic_fn(chunk_steps)
                        window_dispatches += 1
                    elif use_device_rb:
                        # on-chip gather: only the indices cross the link
                        critic_data = rb.sample_transitions(
                            batch_size=per_rank_batch_size * fabric.local_data_parallel_size,
                            n_samples=chunk_steps,
                        )
                        window_dispatches += 2  # gather program + scanned train program
                    else:
                        window_dispatches += 1
                        critic_sample = rb.sample(
                            batch_size=per_rank_batch_size * fabric.local_data_parallel_size,
                            n_samples=chunk_steps,
                        )
                        critic_data = {k: np.asarray(v, np.float32) for k, v in critic_sample.items()}
                        if num_processes > 1:
                            critic_data = fabric.make_global(critic_data, (None, fabric.data_axis))
                        else:
                            # async HBM staging ahead of the fused replay loop
                            critic_data = to_device(critic_data)
                    with timer("Time/train_time"):
                        key, train_key = jax.random.split(key)
                        (
                            agent.critic_params,
                            agent.target_critic_params,
                            critic_opt,
                            qf_loss,
                        ) = chunk_fn(
                            agent.actor_params,
                            agent.critic_params,
                            agent.target_critic_params,
                            agent.log_alpha,
                            critic_opt,
                            critic_data,
                            train_key,
                        )
                    qf_losses.append((chunk_steps, qf_loss))
                    cumulative_per_rank_gradient_steps += chunk_steps

                # then ONE actor+alpha update (reference droq.py:121-139)
                if use_device_rb:
                    actor_batch = {
                        k: v[0]
                        for k, v in rb.sample_transitions(
                            batch_size=per_rank_batch_size * fabric.local_data_parallel_size
                        ).items()
                    }  # [B, ...]
                    window_dispatches += 2  # actor-batch gather + actor program
                else:
                    window_dispatches += 1
                    actor_sample = rb.sample(batch_size=per_rank_batch_size * fabric.local_data_parallel_size)
                    actor_batch = {
                        k: np.asarray(v, np.float32)[0] for k, v in actor_sample.items()
                    }  # [B, ...]
                    if num_processes > 1:
                        actor_batch = fabric.make_global(actor_batch, (fabric.data_axis,))
                    else:
                        actor_batch = to_device(actor_batch)
                with timer("Time/train_time"):
                    key, train_key = jax.random.split(key)
                    (
                        agent.actor_params,
                        agent.log_alpha,
                        actor_opt,
                        alpha_opt,
                        actor_metrics,
                    ) = actor_fn(
                        agent.actor_params,
                        agent.critic_params,
                        agent.log_alpha,
                        actor_opt,
                        alpha_opt,
                        actor_batch,
                        train_key,
                    )
                    qf_mean = float(weighted_chunk_metrics(qf_losses))
                    actor_metrics = np.asarray(jax.device_get(actor_metrics))
                    train_step += num_processes
                telemetry_train_window(window_dispatches, per_rank_gradient_steps + 1)
                player.update_params(agent.actor_params)
                if cfg.metric.log_level > 0:
                    aggregator.update("Loss/value_loss", float(qf_mean))
                    aggregator.update("Loss/policy_loss", float(actor_metrics[0]))
                    aggregator.update("Loss/alpha_loss", float(actor_metrics[1]))

        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or update == num_updates):
            logger.log_metrics(aggregator.compute(), policy_step)
            aggregator.reset()
            if policy_step > 0:
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * num_processes / policy_step},
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time"):
                    logger.log_metrics(
                        {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                        policy_step,
                    )
                if timer_metrics.get("Time/env_interaction_time"):
                    logger.log_metrics(
                        {
                            "Time/sps_env_interaction": (
                                (policy_step - last_log) / num_processes * cfg.env.action_repeat
                            )
                            / timer_metrics["Time/env_interaction_time"]
                        },
                        policy_step,
                    )
                timer.reset()
            last_log = policy_step
            last_train = train_step

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            update == num_updates and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": {
                    "actor": jax.device_get(agent.actor_params),
                    "critics": jax.device_get(agent.critic_params),
                    "target_critics": jax.device_get(agent.target_critic_params),
                    "log_alpha": jax.device_get(agent.log_alpha),
                },
                "qf_optimizer": jax.device_get(critic_opt),
                "actor_optimizer": jax.device_get(actor_opt),
                "alpha_optimizer": jax.device_get(alpha_opt),
                "ratio": ratio.state_dict(),
                "update": update,
                "batch_size": per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )

    probe.finish(
        policy_step,
        # a materializing fetch is the only real device sync on the tunnel
        sync=lambda: np.asarray(jax.device_get(agent.log_alpha)),
        work=cumulative_per_rank_gradient_steps,
    )
    # land any in-flight async param stream before the final evaluation
    player.flush_stream_attrs()
    envs.close()
    if fabric.is_global_zero and cfg.algo.run_test:
        test(player, fabric, cfg, log_dir)
    logger.finalize()
