"""Shared Plan2Explore constants and helpers.

All three P2E generations log the same exploration metric surface (the
reference repeats the set in ``sheeprl/algos/p2e_dv{1,2,3}/utils.py``; the
names are the metric contract, so they must match). Each version's
``utils.py`` keeps only its deltas: the registered-model set and any extra
finetuning keys.
"""

from __future__ import annotations

from typing import Iterable

# the exploration-phase metric names common to P2E DV1/DV2/DV3
P2E_EXPLORATION_KEYS = frozenset(
    {
        "Rewards/rew_avg",
        "Game/ep_len_avg",
        "Loss/world_model_loss",
        "Loss/value_loss_task",
        "Loss/policy_loss_task",
        "Loss/value_loss_exploration",
        "Loss/policy_loss_exploration",
        "Loss/observation_loss",
        "Loss/reward_loss",
        "Loss/state_loss",
        "Loss/continue_loss",
        "Loss/ensemble_loss",
        "State/kl",
        "State/post_entropy",
        "State/prior_entropy",
        "Params/exploration_amount",
        "Rewards/intrinsic",
        "Values_exploration/predicted_values",
        "Values_exploration/lambda_values",
        "Grads/world_model",
        "Grads/actor_task",
        "Grads/critic_task",
        "Grads/actor_exploration",
        "Grads/critic_exploration",
        "Grads/ensemble",
    }
)

# the plain Dreamer metric names the finetuning phase logs on top
DREAMER_FINETUNING_KEYS = frozenset(
    {"Loss/value_loss", "Loss/policy_loss", "Grads/actor", "Grads/critic"}
)


def make_log_models(models_to_register: Iterable[str]):
    """Per-algo ``log_models_from_checkpoint`` bound to that algo's
    registered-model set (reference per-algo log_models_from_checkpoint;
    shared body in ``utils/model_manager.py``)."""

    def log_models_from_checkpoint(fabric, cfg, state, artifacts_dir):
        from sheeprl_tpu.utils.model_manager import log_models_from_checkpoint as _log

        return _log(state, sorted(models_to_register), artifacts_dir)

    return log_models_from_checkpoint
