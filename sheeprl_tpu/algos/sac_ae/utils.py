"""SAC-AE helpers (reference: sheeprl/algos/sac_ae/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

from sheeprl_tpu.obs.telemetry import telemetry_deliberate_compiles
import jax
import numpy as np

from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS as _SAC_KEYS

AGGREGATOR_KEYS = _SAC_KEYS | {"Loss/reconstruction_loss"}
MODELS_TO_REGISTER = {"agent", "encoder", "decoder"}


def prepare_obs(
    obs: Dict[str, np.ndarray], cnn_keys: Sequence[str] = (), num_envs: int = 1
) -> Dict[str, np.ndarray]:
    """Shape env observations for the agent (reference utils.py:28-40):
    pixels fold a frame-stack axis into channels and are normalized to
    [0, 1]; vectors flatten and stay float32."""
    out: Dict[str, np.ndarray] = {}
    for k, v in obs.items():
        v = np.asarray(v)
        if k in cnn_keys:
            if v.ndim == 3:
                v = v[None]
            if v.ndim == 4 and v.shape[0] != num_envs:
                v = v[None]
            if v.ndim == 5:
                e, s, h, w, c = v.shape
                v = np.moveaxis(v, 1, 3).reshape(e, h, w, s * c)
            out[k] = v.astype(np.float32) / 255.0
        else:
            out[k] = v.reshape(num_envs, -1).astype(np.float32)
    return out


# the eval rollout compiles fresh programs (eval batch shapes) after the
# loop's warm point; that is a deliberate one-time compile, not a retrace
@telemetry_deliberate_compiles("eval_rollout")
def test(player: Any, fabric: Any, cfg: Dict[str, Any], log_dir: str) -> None:
    """Greedy evaluation episode (reference utils.py:43-66)."""
    from sheeprl_tpu.envs import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    done = False
    cumulative_rew = 0.0
    key = jax.random.PRNGKey(cfg.seed)
    obs, _ = env.reset(seed=cfg.seed)
    while not done:
        key, sub = jax.random.split(key)
        np_obs = prepare_obs(obs, cnn_keys=cfg.algo.cnn_keys.encoder)
        action = player.get_actions(np_obs, sub, greedy=True)
        obs, reward, terminated, truncated, _ = env.step(
            np.asarray(action).reshape(env.action_space.shape)
        )
        done = terminated or truncated or cfg.dry_run
        cumulative_rew += float(reward)
    fabric_print = getattr(fabric, "print", print)
    fabric_print(f"Test - Reward: {cumulative_rew}")
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()


def log_models_from_checkpoint(fabric, cfg, state, artifacts_dir):
    """Pickle this algorithm's registered sub-models from a checkpoint
    (reference per-algo log_models_from_checkpoint; shared body in
    utils/model_manager.py)."""
    from sheeprl_tpu.utils.model_manager import log_models_from_checkpoint as _log

    return _log(state, sorted(MODELS_TO_REGISTER), artifacts_dir)
