"""SAC-AE agent (reference: sheeprl/algos/sac_ae/agent.py:26-640).

flax re-design of the pixel-SAC autoencoder (https://arxiv.org/abs/1910.01741):

- the encoder/decoder/actor/Q-functions are separate param trees matched to
  the reference's five optimizers; the Q ensemble is vmapped stacked params
  over a shared encoder feature (reference SACAECritic loop, agent.py:235-238),
- ``detach_encoder_features`` becomes a ``stop_gradient`` on the conv trunk
  output (CNN) / the full MLP output (reference agent.py:77-121) — combined
  with per-tree ``jax.grad`` it reproduces the reference's careful gradient
  routing (actor never trains the encoder, agent.py:74-110 in sac_ae.py),
- the decoder's final transposed conv reproduces torch's ``output_padding=1``
  by right-padding the input one pixel and cropping (NHWC).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.sac.agent import finite_action_bounds
from sheeprl_tpu.models import MLP
from sheeprl_tpu.parallel.fabric import HostPlayerParams, put_tree, resolve_player_device

Array = jax.Array

LOG_STD_MAX = 2.0
LOG_STD_MIN = -10.0


class SACAEEncoder(nn.Module):
    """Multi-encoder: conv trunk (k3 s2 + 3x k3 s1, VALID) -> Dense+LN+tanh
    feature head for pixels (reference CNNEncoder, agent.py:26-87), plus an
    MLP for vector keys (reference MLPEncoder, agent.py:89-120)."""

    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    features_dim: int = 64
    cnn_channels_multiplier: int = 1
    dense_units: int = 64
    mlp_layers: int = 2
    dense_act: str = "relu"
    layer_norm: bool = False
    screen_size: int = 64
    dtype: Any = jnp.float32

    @property
    def conv_hw(self) -> int:
        hw = (self.screen_size - 3) // 2 + 1  # k3 s2
        for _ in range(3):  # 3x k3 s1
            hw = hw - 2
        return hw

    @property
    def conv_channels(self) -> int:
        return 32 * self.cnn_channels_multiplier

    @property
    def output_dim(self) -> int:
        dim = self.features_dim if self.cnn_keys else 0
        dim += self.dense_units if self.mlp_keys else 0
        return dim

    @nn.compact
    def __call__(self, obs: Dict[str, Array], detach_encoder_features: bool = False) -> Array:
        feats = []
        if self.cnn_keys:
            x = jnp.concatenate([obs[k].astype(self.dtype) for k in self.cnn_keys], axis=-1)
            strides = [2, 1, 1, 1]
            for s in strides:
                x = nn.Conv(
                    self.conv_channels,
                    kernel_size=(3, 3),
                    strides=(s, s),
                    padding="VALID",
                    dtype=self.dtype,
                    param_dtype=jnp.float32,
                )(x)
                x = nn.relu(x)
            x = x.reshape(*x.shape[:-3], -1)
            if detach_encoder_features:
                x = jax.lax.stop_gradient(x)
            x = nn.Dense(self.features_dim, dtype=self.dtype, param_dtype=jnp.float32, name="fc")(x)
            x = nn.LayerNorm(dtype=jnp.float32)(x.astype(jnp.float32))
            feats.append(jnp.tanh(x))
        if self.mlp_keys:
            v = jnp.concatenate([obs[k].astype(self.dtype) for k in self.mlp_keys], axis=-1)
            v = MLP(
                hidden_sizes=(self.dense_units,) * self.mlp_layers,
                output_dim=None,
                activation=self.dense_act,
                norm_layer="layer_norm" if self.layer_norm else None,
                dtype=self.dtype,
                name="mlp_encoder",
            )(v).astype(jnp.float32)
            if detach_encoder_features:
                v = jax.lax.stop_gradient(v)
            feats.append(v)
        return feats[0] if len(feats) == 1 else jnp.concatenate(feats, axis=-1)


class SACAEDecoder(nn.Module):
    """Multi-decoder: Dense to the conv seed then 3x ConvTranspose k3 s1 and
    a final k3 s2 (+output-padding) back to pixels (reference CNNDecoder,
    agent.py:153-201), plus an MLP trunk with per-key heads for vectors
    (reference MLPDecoder, agent.py:122-150)."""

    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    cnn_output_channels: Tuple[int, ...]
    mlp_output_dims: Tuple[int, ...]
    conv_hw: int
    conv_channels: int
    features_dim: int = 64
    dense_units: int = 64
    mlp_layers: int = 2
    dense_act: str = "relu"
    layer_norm: bool = False
    screen_size: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features: Array) -> Dict[str, Array]:
        out: Dict[str, Array] = {}
        if self.cnn_keys:
            x = nn.Dense(
                self.conv_hw * self.conv_hw * self.conv_channels,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name="fc",
            )(features.astype(self.dtype))
            x = x.reshape(*x.shape[:-1], self.conv_hw, self.conv_hw, self.conv_channels)
            for _ in range(3):
                x = nn.ConvTranspose(
                    self.conv_channels,
                    kernel_size=(3, 3),
                    strides=(1, 1),
                    padding="VALID",
                    dtype=self.dtype,
                    param_dtype=jnp.float32,
                )(x)
                x = nn.relu(x)
            # torch's output_padding=1: right-pad the input and crop
            x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
            x = nn.ConvTranspose(
                sum(self.cnn_output_channels),
                kernel_size=(3, 3),
                strides=(2, 2),
                padding="VALID",
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name="to_obs",
            )(x)
            x = x[..., : self.screen_size, : self.screen_size, :].astype(jnp.float32)
            splits = np.cumsum(self.cnn_output_channels)[:-1]
            out.update({k: p for k, p in zip(self.cnn_keys, jnp.split(x, splits, axis=-1))})
        if self.mlp_keys:
            v = MLP(
                hidden_sizes=(self.dense_units,) * self.mlp_layers,
                output_dim=None,
                activation=self.dense_act,
                norm_layer="layer_norm" if self.layer_norm else None,
                dtype=self.dtype,
                name="mlp_decoder",
            )(features.astype(self.dtype))
            for k, d in zip(self.mlp_keys, self.mlp_output_dims):
                out[k] = nn.Dense(d, dtype=jnp.float32, param_dtype=jnp.float32, name=f"head_{k}")(v)
        return out


class SACAEQFunction(nn.Module):
    """Q(features, a) MLP (reference agent.py:204-223); ensemble via vmap."""

    hidden_size: int = 1024
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features: Array, action: Array) -> Array:
        x = jnp.concatenate([features, action], axis=-1).astype(self.dtype)
        for _ in range(2):
            x = nn.Dense(self.hidden_size, dtype=self.dtype, param_dtype=jnp.float32)(x)
            x = nn.relu(x)
        return nn.Dense(1, dtype=jnp.float32, param_dtype=jnp.float32)(x)


class SACAEActorTrunk(nn.Module):
    """Actor head on top of encoder features (reference SACAEContinuousActor,
    agent.py:240-318; the tanh-rescaled log-std clamp is :281-284)."""

    action_dim: int
    hidden_size: int = 1024
    action_low: Tuple[float, ...] = (-1.0,)
    action_high: Tuple[float, ...] = (1.0,)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, features: Array) -> Tuple[Array, Array]:
        x = features.astype(self.dtype)
        for _ in range(2):
            x = nn.Dense(self.hidden_size, dtype=self.dtype, param_dtype=jnp.float32)(x)
            x = nn.relu(x)
        mean = nn.Dense(self.action_dim, dtype=jnp.float32, param_dtype=jnp.float32, name="fc_mean")(x)
        log_std = nn.Dense(self.action_dim, dtype=jnp.float32, param_dtype=jnp.float32, name="fc_logstd")(x)
        log_std = jnp.tanh(log_std)
        log_std = LOG_STD_MIN + 0.5 * (LOG_STD_MAX - LOG_STD_MIN) * (log_std + 1)
        return mean, log_std

    @property
    def action_scale(self) -> Array:
        return (jnp.asarray(self.action_high) - jnp.asarray(self.action_low)) / 2.0

    @property
    def action_bias(self) -> Array:
        return (jnp.asarray(self.action_high) + jnp.asarray(self.action_low)) / 2.0


def actor_action_and_log_prob(
    actor: SACAEActorTrunk, params: Any, features: Array, key: Array
) -> Tuple[Array, Array]:
    """rsample a squashed action + log-prob from encoder features
    (reference agent.py:286-318)."""
    mean, log_std = actor.apply(params, features)
    std = jnp.exp(log_std)
    x_t = mean + std * jax.random.normal(key, mean.shape)
    y_t = jnp.tanh(x_t)
    scale, bias = actor.action_scale, actor.action_bias
    action = y_t * scale + bias
    log_prob = -0.5 * (jnp.square((x_t - mean) / std) + 2 * jnp.log(std) + jnp.log(2 * jnp.pi))
    log_prob = log_prob - jnp.log(scale * (1 - jnp.square(y_t)) + 1e-6)
    return action, log_prob.sum(-1, keepdims=True)


def actor_greedy_action(actor: SACAEActorTrunk, params: Any, features: Array) -> Array:
    mean, _ = actor.apply(params, features)
    return jnp.tanh(mean) * actor.action_scale + actor.action_bias


def qf_ensemble_apply(qf: SACAEQFunction, stacked_params: Any, features: Array, action: Array) -> Array:
    """[B, n_critics] Q-values in one vmapped call (reference agent.py:235-238)."""
    qs = jax.vmap(lambda p: qf.apply(p, features, action))(stacked_params)
    return jnp.moveaxis(qs[..., 0], 0, -1)


class SACAEAgent:
    """Host handle holding the five param trees + targets (reference
    SACAEAgent, agent.py:321-520)."""

    def __init__(
        self,
        encoder: SACAEEncoder,
        decoder: SACAEDecoder,
        actor: SACAEActorTrunk,
        qf: SACAEQFunction,
        encoder_params: Any,
        decoder_params: Any,
        actor_params: Any,
        qfs_params: Any,  # stacked [n_critics, ...]
        target_entropy: float,
        alpha: float = 0.1,
        tau: float = 0.01,
        encoder_tau: float = 0.05,
        num_critics: int = 2,
    ) -> None:
        self.encoder = encoder
        self.decoder = decoder
        self.actor = actor
        self.qf = qf
        self.encoder_params = encoder_params
        self.decoder_params = decoder_params
        self.actor_params = actor_params
        self.qfs_params = qfs_params
        self.target_encoder_params = jax.tree.map(jnp.copy, encoder_params)
        self.target_qfs_params = jax.tree.map(jnp.copy, qfs_params)
        self.log_alpha = jnp.log(jnp.asarray([alpha], jnp.float32))
        self.target_entropy = float(target_entropy)
        self.tau = float(tau)
        self.encoder_tau = float(encoder_tau)
        self.num_critics = num_critics


class SACAEPlayer(HostPlayerParams):
    """Rollout/eval policy handle (reference SACAEPlayer, agent.py:523-560).

    ``device`` optionally pins inference to the host CPU backend
    (see ``parallel.fabric.resolve_player_device``)."""

    _placed_attrs = ("encoder_params", "actor_params")

    def __init__(
        self,
        encoder: SACAEEncoder,
        actor: SACAEActorTrunk,
        encoder_params: Any,
        actor_params: Any,
        device: Optional[Any] = None,
    ) -> None:
        self.encoder = encoder
        self.actor = actor
        self.device = device  # must precede the param assignments below
        self.encoder_params = encoder_params
        self.actor_params = actor_params

        def _sample(ep, ap, obs, key):
            feat = encoder.apply(ep, obs)
            return actor_action_and_log_prob(actor, ap, feat, key)[0]

        def _greedy(ep, ap, obs):
            feat = encoder.apply(ep, obs)
            return actor_greedy_action(actor, ap, feat)

        self._sample = jax.jit(_sample)
        self._greedy = jax.jit(_greedy)

    def get_actions(self, obs: Dict[str, Array], key: Optional[Array] = None, greedy: bool = False) -> np.ndarray:
        self.poll_stream_attrs()
        if greedy:
            return np.asarray(self._greedy(self.encoder_params, self.actor_params, obs))
        return np.asarray(self._sample(self.encoder_params, self.actor_params, obs, put_tree(key, self.device)))


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    action_space: gymnasium.spaces.Box,
    agent_state: Optional[Dict[str, Any]] = None,
) -> Tuple[SACAEAgent, SACAEPlayer]:
    """Construct modules + init/replicate params (reference build_agent,
    agent.py:563-640)."""
    if not is_continuous:
        raise ValueError("Only continuous action space is supported for the SAC-AE agent")
    algo = cfg["algo"]
    cnn_keys = tuple(algo["cnn_keys"]["encoder"])
    mlp_keys = tuple(algo["mlp_keys"]["encoder"])
    act_dim = int(np.sum(actions_dim))
    screen = int(cfg["env"]["screen_size"])
    dtype = fabric.precision.compute_dtype

    def _channels(k):
        shape = obs_space[k].shape
        return int(np.prod(shape[:-3]) * shape[-1]) if len(shape) >= 3 else 1

    encoder = SACAEEncoder(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        features_dim=int(algo["encoder"]["features_dim"]),
        cnn_channels_multiplier=int(algo["encoder"]["cnn_channels_multiplier"]),
        dense_units=int(algo["encoder"]["dense_units"]),
        mlp_layers=int(algo["encoder"]["mlp_layers"]),
        dense_act=str(algo["encoder"]["dense_act"]),
        layer_norm=bool(algo["encoder"]["layer_norm"]),
        screen_size=screen,
        dtype=dtype,
    )
    decoder = SACAEDecoder(
        cnn_keys=tuple(algo["cnn_keys"]["decoder"]),
        mlp_keys=tuple(algo["mlp_keys"]["decoder"]),
        cnn_output_channels=tuple(_channels(k) for k in algo["cnn_keys"]["decoder"]),
        mlp_output_dims=tuple(int(obs_space[k].shape[0]) for k in algo["mlp_keys"]["decoder"]),
        conv_hw=encoder.conv_hw,
        conv_channels=encoder.conv_channels,
        features_dim=int(algo["encoder"]["features_dim"]),
        dense_units=int(algo["decoder"]["dense_units"]),
        mlp_layers=int(algo["decoder"]["mlp_layers"]),
        dense_act=str(algo["decoder"]["dense_act"]),
        layer_norm=bool(algo["decoder"]["layer_norm"]),
        screen_size=screen,
        dtype=dtype,
    )
    action_low, action_high = finite_action_bounds(action_space)
    actor = SACAEActorTrunk(
        action_dim=act_dim,
        hidden_size=int(algo["hidden_size"]),
        action_low=action_low,
        action_high=action_high,
        dtype=dtype,
    )
    n_critics = int(algo["critic"]["n"])
    qf = SACAEQFunction(hidden_size=int(algo["hidden_size"]), dtype=dtype)

    key = jax.random.PRNGKey(int(cfg["seed"]))
    k_enc, k_dec, k_actor, *k_qfs = jax.random.split(key, n_critics + 3)

    dummy_obs = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        if len(shape) == 4:
            s, hh, ww, c = shape
            shape = (hh, ww, s * c)
        dummy_obs[k] = jnp.zeros((1, *shape), jnp.float32)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((1, int(np.prod(obs_space[k].shape))), jnp.float32)

    if agent_state is not None:
        encoder_params = jax.tree.map(jnp.asarray, agent_state["encoder"])
        decoder_params = jax.tree.map(jnp.asarray, agent_state["decoder"])
        actor_params = jax.tree.map(jnp.asarray, agent_state["actor"])
        qfs_params = jax.tree.map(jnp.asarray, agent_state["qfs"])
    else:
        encoder_params = encoder.init(k_enc, dummy_obs)
        feat = encoder.apply(encoder_params, dummy_obs)
        decoder_params = decoder.init(k_dec, feat)
        actor_params = actor.init(k_actor, feat)
        dummy_act = jnp.zeros((1, act_dim), jnp.float32)
        qfs_params = jax.vmap(lambda kk: qf.init(kk, feat, dummy_act))(jnp.stack(k_qfs))

    agent = SACAEAgent(
        encoder,
        decoder,
        actor,
        qf,
        fabric.replicate(encoder_params),
        fabric.replicate(decoder_params),
        fabric.replicate(actor_params),
        fabric.replicate(qfs_params),
        target_entropy=-act_dim,
        alpha=float(algo["alpha"]["alpha"]),
        tau=float(algo["tau"]),
        encoder_tau=float(algo["encoder"]["tau"]),
        num_critics=n_critics,
    )
    if agent_state is not None:
        agent.target_encoder_params = fabric.replicate(jax.tree.map(jnp.asarray, agent_state["target_encoder"]))
        agent.target_qfs_params = fabric.replicate(jax.tree.map(jnp.asarray, agent_state["target_qfs"]))
        agent.log_alpha = fabric.replicate(jnp.asarray(agent_state["log_alpha"]))
    else:
        agent.target_encoder_params = fabric.replicate(agent.target_encoder_params)
        agent.target_qfs_params = fabric.replicate(agent.target_qfs_params)

    player = SACAEPlayer(
        encoder,
        actor,
        agent.encoder_params,
        agent.actor_params,
        device=resolve_player_device(cfg["algo"].get("player_device", "auto")),
    )
    return agent, player
