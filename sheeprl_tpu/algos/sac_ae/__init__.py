from sheeprl_tpu.algos.sac_ae import sac_ae, evaluate  # noqa: F401
