"""SAC-AE (reference: sheeprl/algos/sac_ae/sac_ae.py:35-517) — TPU-native.

Pixel SAC with an autoencoder. Redesign highlights:

- **All G gradient steps fused into one jit** (the reference dispatches each
  batch from Python, :390-410): critic (+encoder), EMA targets, actor, alpha,
  and decoder (+encoder) updates run per scanned step.
- Frequency-gated updates (actor every ``actor.per_rank_update_freq`` steps,
  decoder every ``decoder.per_rank_update_freq``, target EMA every
  ``critic.per_rank_target_network_update_freq``, reference :74-118) are
  ``jnp.where``-applied so the graph stays static.
- The gradient routing of the reference's five optimizers maps to per-tree
  ``jax.grad``: the critic loss trains (encoder, qfs); the actor loss trains
  only the actor trunk (conv features stop-gradient'd); the reconstruction
  loss trains (encoder, decoder) with the L2 latent penalty (:100-118).
- Pixels stay uint8 through the buffer; /255 normalization and the 5-bit
  reconstruction target quantization (utils.preprocess_obs) happen in-graph.
"""

from __future__ import annotations

import os
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.ops.optim import build_tx
from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac_ae.agent import (
    SACAEAgent,
    actor_action_and_log_prob,
    build_agent,
    qf_ensemble_apply,
)
from sheeprl_tpu.algos.sac_ae.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.parallel.shard_map import shard_map
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, SteadyStateProbe, gradient_step_chunks, save_configs, weighted_chunk_metrics


def make_train_fn(fabric, agent: SACAEAgent, actor_tx, qf_tx, alpha_tx, encoder_tx, decoder_tx, cfg):
    algo = cfg.algo
    gamma = float(algo.gamma)
    tau = float(algo.tau)
    encoder_tau = float(algo.encoder.tau)
    l2_lambda = float(algo.decoder.l2_lambda)
    target_entropy = agent.target_entropy
    num_critics = agent.num_critics
    encoder, decoder, actor, qf = agent.encoder, agent.decoder, agent.actor, agent.qf
    cnn_keys = tuple(algo.cnn_keys.encoder)
    mlp_keys = tuple(algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(algo.mlp_keys.decoder)
    target_update_freq = max(1, int(algo.critic.per_rank_target_network_update_freq))
    actor_update_freq = max(1, int(algo.actor.per_rank_update_freq))
    decoder_update_freq = max(1, int(algo.decoder.per_rank_update_freq))
    data_axis = fabric.data_axis
    multi_device = fabric.world_size > 1

    def pmean(x):
        return lax.pmean(x, data_axis) if multi_device else x

    def normalized(batch, prefix=""):
        obs = {k: batch[prefix + k] / 255.0 for k in cnn_keys}
        obs.update({k: batch[prefix + k] for k in mlp_keys})
        return obs

    def preprocess_target(x, bits=5):
        """5-bit quantized reconstruction target (reference
        utils.preprocess_obs; the dequantization noise is omitted — a
        deterministic half-bin shift keeps the jitted step noise-free)."""
        bins = 2**bits
        x = jnp.floor(x / 2 ** (8 - bits))
        return x / bins + 0.5 / bins - 0.5

    def local_train(
        encoder_params, decoder_params, actor_params, qfs_params,
        target_encoder_params, target_qfs_params, log_alpha,
        actor_opt, qf_opt, alpha_opt, encoder_opt, decoder_opt,
        grad_counter, data, key,
    ):
        if multi_device:
            key = jax.random.fold_in(key, lax.axis_index(data_axis))

        def one_step(carry, batch):
            (encoder_params, decoder_params, actor_params, qfs_params,
             target_encoder_params, target_qfs_params, log_alpha,
             actor_opt, qf_opt, alpha_opt, encoder_opt, decoder_opt,
             counter, key) = carry
            key, k_next, k_actor = jax.random.split(key, 3)
            alpha = jnp.exp(log_alpha)
            obs = normalized(batch)
            next_obs = normalized(batch, "next_")

            # -------- soft critic (+ encoder) update (reference :62-70) ---- #
            next_feat = encoder.apply(target_encoder_params, next_obs)
            actor_feat_next = encoder.apply(encoder_params, next_obs)
            next_actions, next_logpi = actor_action_and_log_prob(actor, actor_params, actor_feat_next, k_next)
            q_next = qf_ensemble_apply(qf, target_qfs_params, next_feat, next_actions)
            min_q_next = jnp.min(q_next, axis=-1, keepdims=True) - alpha * next_logpi
            target = batch["rewards"] + (1 - batch["terminated"]) * gamma * min_q_next
            target = lax.stop_gradient(target)

            def qf_loss_fn(ep, qp):
                feat = encoder.apply(ep, obs)
                q = qf_ensemble_apply(qf, qp, feat, batch["actions"])
                return critic_loss(q, target, num_critics)

            qf_loss, (enc_grads, qf_grads) = jax.value_and_grad(qf_loss_fn, argnums=(0, 1))(
                encoder_params, qfs_params
            )
            enc_grads, qf_grads = pmean(enc_grads), pmean(qf_grads)
            updates, qf_opt = qf_tx.update(qf_grads, qf_opt, qfs_params)
            qfs_params = optax.apply_updates(qfs_params, updates)
            # the reference's qf optimizer covers the encoder too (its critic
            # module embeds it, sac_ae.py:66-69 + agent.py:226-238)
            updates, encoder_opt = encoder_tx.update(enc_grads, encoder_opt, encoder_params)
            encoder_params = optax.apply_updates(encoder_params, updates)

            # -------- target EMA (reference :73-77) ----------------------- #
            do_ema = (counter % target_update_freq) == 0
            target_qfs_params = jax.tree.map(
                lambda c, t: jnp.where(do_ema, tau * c + (1 - tau) * t, t), qfs_params, target_qfs_params
            )
            target_encoder_params = jax.tree.map(
                lambda c, t: jnp.where(do_ema, encoder_tau * c + (1 - encoder_tau) * t, t),
                encoder_params,
                target_encoder_params,
            )

            # -------- actor + alpha update (reference :79-97) ------------- #
            # the frequency gates are lax.cond so skipped steps skip the whole
            # backward pass; the counter is identical on every replica, so all
            # shards take the same branch and the pmean collectives line up
            do_actor = (counter % actor_update_freq) == 0

            def actor_update(operand):
                actor_params, log_alpha, actor_opt, alpha_opt = operand

                def actor_loss_fn(p):
                    feat = encoder.apply(encoder_params, obs, detach_encoder_features=True)
                    actions, logpi = actor_action_and_log_prob(actor, p, feat, k_actor)
                    q = qf_ensemble_apply(qf, qfs_params, feat, actions)
                    min_q = jnp.min(q, axis=-1, keepdims=True)
                    return policy_loss(alpha, logpi, min_q), logpi

                (a_loss, logpi), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(actor_params)
                actor_grads = pmean(actor_grads)
                updates, actor_opt = actor_tx.update(actor_grads, actor_opt, actor_params)
                actor_params = optax.apply_updates(actor_params, updates)

                alpha_grad = jax.grad(
                    lambda la: entropy_loss(la, lax.stop_gradient(logpi), target_entropy)
                )(log_alpha)
                alpha_grad = pmean(alpha_grad)
                updates, alpha_opt = alpha_tx.update(alpha_grad, alpha_opt, log_alpha)
                log_alpha = optax.apply_updates(log_alpha, updates)
                alpha_l = entropy_loss(log_alpha, logpi, target_entropy)
                return actor_params, log_alpha, actor_opt, alpha_opt, a_loss, alpha_l

            def actor_skip(operand):
                actor_params, log_alpha, actor_opt, alpha_opt = operand
                return actor_params, log_alpha, actor_opt, alpha_opt, jnp.zeros(()), jnp.zeros(())

            actor_params, log_alpha, actor_opt, alpha_opt, a_loss, alpha_l = lax.cond(
                do_actor, actor_update, actor_skip, (actor_params, log_alpha, actor_opt, alpha_opt)
            )

            # -------- decoder (+ encoder) update (reference :99-118) ------ #
            do_decoder = (counter % decoder_update_freq) == 0

            def decoder_update(operand):
                encoder_params, decoder_params, encoder_opt, decoder_opt = operand

                def recon_loss_fn(ep, dp):
                    hidden = encoder.apply(ep, obs)
                    recon = decoder.apply(dp, hidden)
                    loss = l2_lambda * jnp.mean(0.5 * jnp.square(hidden).sum(-1))
                    for k in cnn_dec_keys + mlp_dec_keys:
                        target_k = preprocess_target(batch[k]) if k in cnn_dec_keys else batch[k]
                        loss = loss + jnp.mean(jnp.square(target_k - recon[k]))
                    return loss

                rec_loss, (enc_grads, dec_grads) = jax.value_and_grad(recon_loss_fn, argnums=(0, 1))(
                    encoder_params, decoder_params
                )
                enc_grads, dec_grads = pmean(enc_grads), pmean(dec_grads)
                updates, encoder_opt = encoder_tx.update(enc_grads, encoder_opt, encoder_params)
                encoder_params = optax.apply_updates(encoder_params, updates)
                updates, decoder_opt = decoder_tx.update(dec_grads, decoder_opt, decoder_params)
                decoder_params = optax.apply_updates(decoder_params, updates)
                return encoder_params, decoder_params, encoder_opt, decoder_opt, rec_loss

            def decoder_skip(operand):
                encoder_params, decoder_params, encoder_opt, decoder_opt = operand
                return encoder_params, decoder_params, encoder_opt, decoder_opt, jnp.zeros(())

            encoder_params, decoder_params, encoder_opt, decoder_opt, rec_loss = lax.cond(
                do_decoder,
                decoder_update,
                decoder_skip,
                (encoder_params, decoder_params, encoder_opt, decoder_opt),
            )

            carry = (encoder_params, decoder_params, actor_params, qfs_params,
                     target_encoder_params, target_qfs_params, log_alpha,
                     actor_opt, qf_opt, alpha_opt, encoder_opt, decoder_opt,
                     counter + 1, key)
            return carry, jnp.stack([qf_loss, a_loss, alpha_l, rec_loss])

        carry = (encoder_params, decoder_params, actor_params, qfs_params,
                 target_encoder_params, target_qfs_params, log_alpha,
                 actor_opt, qf_opt, alpha_opt, encoder_opt, decoder_opt,
                 grad_counter, key)
        carry, metrics = lax.scan(one_step, carry, data)
        return (*carry[:13], pmean(metrics.mean(axis=0)))

    if multi_device:
        train_fn = shard_map(
            local_train,
            mesh=fabric.mesh,
            in_specs=(P(),) * 13 + (P(None, data_axis), P()),
            out_specs=(P(),) * 14,
        )
    else:
        train_fn = local_train
    # donate only optimizer/aux state: param buffers stay un-donated because
    # concurrent readers (async param streaming to the host player, the ema /
    # hard-copy target refresh) may still be in flight when the next train
    # dispatch would otherwise alias over them (observed on the remote chip
    # as spurious INVALID_ARGUMENT errors surfacing at unrelated fetches)
    return jax.jit(train_fn, donate_argnums=(7, 8, 9, 10, 11, 12))


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    rank = fabric.process_index
    world_size = fabric.data_parallel_size  # batch-split width: the data axis (= device count on a 1-D mesh)
    num_processes = fabric.num_processes
    num_envs = int(cfg.env.num_envs)

    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)

    # these arguments cannot be changed (reference sac_ae.py:137-138)
    cfg.env.screen_size = 64

    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")

    envs = build_vector_env(cfg, rank, log_dir if rank == 0 else None, "train")
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC-AE agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    if not obs_keys:
        raise RuntimeError(
            "You should specify at least one CNN key or MLP key from the cli: "
            "`algo.cnn_keys.encoder=[rgb]` or `algo.mlp_keys.encoder=[state]`"
        )

    actions_dim = tuple(action_space.shape)

    agent, player = build_agent(
        fabric,
        actions_dim,
        True,
        cfg,
        observation_space,
        action_space,
        state["agent"] if cfg.checkpoint.resume_from else None,
    )

    qf_tx = build_tx(cfg.algo.critic.optimizer)
    actor_tx = build_tx(cfg.algo.actor.optimizer)
    alpha_tx = build_tx(cfg.algo.alpha.optimizer)
    encoder_tx = build_tx(cfg.algo.encoder.optimizer)
    decoder_tx = build_tx(cfg.algo.decoder.optimizer)
    qf_opt = fabric.replicate(qf_tx.init(jax.device_get(agent.qfs_params)))
    actor_opt = fabric.replicate(actor_tx.init(jax.device_get(agent.actor_params)))
    alpha_opt = fabric.replicate(alpha_tx.init(jax.device_get(agent.log_alpha)))
    encoder_opt = fabric.replicate(encoder_tx.init(jax.device_get(agent.encoder_params)))
    decoder_opt = fabric.replicate(decoder_tx.init(jax.device_get(agent.decoder_params)))
    if cfg.checkpoint.resume_from:
        qf_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["qf_optimizer"]))
        actor_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["actor_optimizer"]))
        alpha_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["alpha_optimizer"]))
        encoder_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["encoder_optimizer"]))
        decoder_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["decoder_optimizer"]))

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in AGGREGATOR_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    buffer_size = cfg.buffer.size // int(num_envs * num_processes) if not cfg.dry_run else 1
    # the pixel workload is where the HBM ring pays most: at replay ratio 1.0
    # the host buffer re-uploads every sampled [G, B] pixel batch over the
    # link; the ring uploads each frame once and gathers on-chip
    # (buffer.device=auto)
    from sheeprl_tpu.data.device_buffer import (
        DeviceReplayBuffer,
        adapt_restored_buffer,
        make_transition_replay,
    )

    rb = make_transition_replay(
        cfg,
        fabric,
        observation_space,
        stored_keys=obs_keys,
        actions_dim=action_space.shape,
        buffer_size=buffer_size,
        num_envs=num_envs,
        obs_keys=tuple(obs_keys) + tuple(f"next_{k}" for k in obs_keys),
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        seed=cfg.seed,
        store_next_obs=True,
    )
    use_device_rb = isinstance(rb, DeviceReplayBuffer)
    if cfg.checkpoint.resume_from and cfg.buffer.checkpoint:
        from sheeprl_tpu.utils.checkpoint import select_buffer

        rb = adapt_restored_buffer(
            select_buffer(state["rb"], rank, num_processes),
            use_device_rb,
            seed=cfg.seed,
            mode="transition",
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        )

    train_fn = make_train_fn(fabric, agent, actor_tx, qf_tx, alpha_tx, encoder_tx, decoder_tx, cfg)

    train_step = 0
    last_train = 0
    start_step = state["update"] + 1 if cfg.checkpoint.resume_from else 1
    policy_step = state["update"] * num_envs * num_processes if cfg.checkpoint.resume_from else 0
    last_log = state["last_log"] if cfg.checkpoint.resume_from else 0
    last_checkpoint = state["last_checkpoint"] if cfg.checkpoint.resume_from else 0
    policy_steps_per_update = int(num_envs * num_processes)
    num_updates = int(cfg.algo.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    per_rank_batch_size = int(cfg.algo.per_rank_batch_size)
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import elastic_per_rank_batch_size

        per_rank_batch_size = elastic_per_rank_batch_size(state["batch_size"], world_size)
        if not cfg.buffer.checkpoint:
            learning_starts += start_step

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if cfg.checkpoint.resume_from:
        ratio.load_state_dict(state["ratio"])

    key = jax.random.PRNGKey(int(cfg.seed))
    # action keys live on the player's device so a host-pinned player
    # never blocks on a chip round trip per env step
    from sheeprl_tpu.parallel.fabric import put_tree as _put_tree

    player_key = _put_tree(jax.random.fold_in(key, 1), player.device)
    grad_counter = jnp.zeros((), jnp.int32)

    obs, _ = envs.reset(seed=cfg.seed)
    cumulative_per_rank_gradient_steps = 0
    step_data: Dict[str, np.ndarray] = {}
    # steady-state throughput probe (SHEEPRL_TPU_BENCH_JSON contract)
    probe = SteadyStateProbe()
    for update in range(start_step, num_updates + 1):
        probe.mark_warm(update, learning_starts, policy_step, work=cumulative_per_rank_gradient_steps)
        policy_step += num_envs * num_processes

        with timer("Time/env_interaction_time"):
            if update <= learning_starts:
                actions = envs.action_space.sample()
            else:
                player_key, action_key = jax.random.split(player_key)
                np_obs = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=num_envs)
                actions = player.get_actions(np_obs, action_key)
            next_obs, rewards, terminated, truncated, infos = envs.step(
                np.asarray(actions).reshape(envs.action_space.shape)
            )

        if cfg.metric.log_level > 0 and "final_info" in infos:
            ep = infos["final_info"].get("episode")
            if ep is not None:
                for i in np.nonzero(ep.get("_r", []))[0]:
                    aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                    aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                    print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

        real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items()}
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    for k, v in final_obs.items():
                        real_next_obs[k][idx] = v

        # pixels stored raw uint8; vectors float32 (reference :330-345)
        raw_obs = {
            k: (np.asarray(obs[k]) if k in cnn_keys else np.asarray(obs[k], np.float32)) for k in obs_keys
        }
        raw_next = {
            k: (np.asarray(real_next_obs[k]) if k in cnn_keys else np.asarray(real_next_obs[k], np.float32))
            for k in obs_keys
        }
        for k in obs_keys:
            v = raw_obs[k]
            step_data[k] = v.reshape(1, num_envs, *v.shape[1:])
            nv = raw_next[k]
            step_data[f"next_{k}"] = nv.reshape(1, num_envs, *nv.shape[1:])
        step_data["terminated"] = np.asarray(terminated, np.float32).reshape(1, num_envs, 1)
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape(1, num_envs, 1)
        step_data["actions"] = np.asarray(actions, np.float32).reshape(1, num_envs, -1)
        step_data["rewards"] = np.asarray(rewards, np.float32).reshape(1, num_envs, 1)
        rb.add(step_data, validate_args=cfg.buffer.validate_args)

        obs = next_obs

        if update >= learning_starts:
            per_rank_gradient_steps = ratio(policy_step / num_processes)
            # fixed-size scan chunks (utils.gradient_step_chunks): every
            # distinct scan length is a fresh XLA compile and Ratio's first
            # post-warmup call repays the whole warmup debt in one G
            chunk_metrics = []
            for chunk_steps in gradient_step_chunks(per_rank_gradient_steps, cfg.algo):
                if use_device_rb:
                    # on-chip gather (only indices cross the link); the
                    # frame-stack fold happens on device — storage stays raw
                    # so checkpoints swap between buffer modes
                    data = {}
                    for k, v in rb.sample_transitions(
                        batch_size=per_rank_batch_size * fabric.local_data_parallel_size,
                        n_samples=chunk_steps,
                    ).items():
                        if (k in cnn_keys or (k.startswith("next_") and k[5:] in cnn_keys)) and v.ndim == 6:
                            g, b, s, h, w, c = v.shape
                            v = jnp.moveaxis(v, 2, 4).reshape(g, b, h, w, s * c)
                        data[k] = v
                else:
                    sample = rb.sample(
                        batch_size=per_rank_batch_size * fabric.local_data_parallel_size,
                        n_samples=chunk_steps,
                    )
                    data = {}
                    for k, v in sample.items():
                        if k in cnn_keys or (k.startswith("next_") and k[5:] in cnn_keys):
                            # [G, B, S, H, W, C] or [G, B, H, W, C] -> fold stack;
                            # pixels STAY uint8 across the link (4x fewer bytes —
                            # the in-graph /255 normalization promotes to f32)
                            v = np.asarray(v)
                            if v.ndim == 6:
                                g, b, s, h, w, c = v.shape
                                v = np.moveaxis(v, 2, 4).reshape(g, b, h, w, s * c)
                            data[k] = v if v.dtype == np.uint8 else v.astype(np.float32)
                        else:
                            data[k] = np.asarray(v, np.float32)
                    if num_processes > 1:
                        data = fabric.make_global(data, (None, fabric.data_axis))
                    else:
                        # async HBM staging: overlap the [G, B] transfer with dispatch
                        from sheeprl_tpu.data.buffers import to_device
                        data = to_device(data)
                with timer("Time/train_time"):
                    key, train_key = jax.random.split(key)
                    (
                        agent.encoder_params,
                        agent.decoder_params,
                        agent.actor_params,
                        agent.qfs_params,
                        agent.target_encoder_params,
                        agent.target_qfs_params,
                        agent.log_alpha,
                        actor_opt,
                        qf_opt,
                        alpha_opt,
                        encoder_opt,
                        decoder_opt,
                        grad_counter,
                        metrics,
                    ) = train_fn(
                        agent.encoder_params,
                        agent.decoder_params,
                        agent.actor_params,
                        agent.qfs_params,
                        agent.target_encoder_params,
                        agent.target_qfs_params,
                        agent.log_alpha,
                        actor_opt,
                        qf_opt,
                        alpha_opt,
                        encoder_opt,
                        decoder_opt,
                        grad_counter,
                        data,
                        train_key,
                    )
                    chunk_metrics.append((chunk_steps, metrics))  # device array; fetched once below
                cumulative_per_rank_gradient_steps += chunk_steps
            if per_rank_gradient_steps > 0:
                train_step += num_processes  # one "train event" per update
                # off-policy: non-blocking refresh, params land a block later
                player.stream_attr("encoder_params", agent.encoder_params)
                player.stream_attr("actor_params", agent.actor_params)
                if cfg.metric.log_level > 0:
                    metrics = weighted_chunk_metrics(chunk_metrics)
                    aggregator.update("Loss/value_loss", float(metrics[0]))
                    aggregator.update("Loss/policy_loss", float(metrics[1]))
                    aggregator.update("Loss/alpha_loss", float(metrics[2]))
                    aggregator.update("Loss/reconstruction_loss", float(metrics[3]))

        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or update == num_updates):
            metrics_dict = aggregator.compute()
            logger.log_metrics(metrics_dict, policy_step)
            aggregator.reset()
            if policy_step > 0:
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * num_processes / policy_step},
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time"):
                    logger.log_metrics(
                        {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                        policy_step,
                    )
                if timer_metrics.get("Time/env_interaction_time"):
                    logger.log_metrics(
                        {
                            "Time/sps_env_interaction": (
                                (policy_step - last_log) / num_processes * cfg.env.action_repeat
                            )
                            / timer_metrics["Time/env_interaction_time"]
                        },
                        policy_step,
                    )
                timer.reset()
            last_log = policy_step
            last_train = train_step

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            update == num_updates and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "agent": {
                    "encoder": jax.device_get(agent.encoder_params),
                    "decoder": jax.device_get(agent.decoder_params),
                    "actor": jax.device_get(agent.actor_params),
                    "qfs": jax.device_get(agent.qfs_params),
                    "target_encoder": jax.device_get(agent.target_encoder_params),
                    "target_qfs": jax.device_get(agent.target_qfs_params),
                    "log_alpha": jax.device_get(agent.log_alpha),
                },
                "qf_optimizer": jax.device_get(qf_opt),
                "actor_optimizer": jax.device_get(actor_opt),
                "alpha_optimizer": jax.device_get(alpha_opt),
                "encoder_optimizer": jax.device_get(encoder_opt),
                "decoder_optimizer": jax.device_get(decoder_opt),
                "ratio": ratio.state_dict(),
                "update": update,
                "batch_size": per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )

    probe.finish(
        policy_step,
        # a materializing fetch is the only real device sync on the tunnel
        sync=lambda: np.asarray(jax.device_get(agent.log_alpha)),
        work=cumulative_per_rank_gradient_steps,
    )
    # land any in-flight async param stream before the final evaluation
    player.flush_stream_attrs()
    envs.close()
    if fabric.is_global_zero and cfg.algo.run_test:
        test(player, fabric, cfg, log_dir)
    logger.finalize()
