from sheeprl_tpu.algos.sac import sac  # noqa: F401  (registers the algorithm)
from sheeprl_tpu.algos.sac import sac_decoupled  # noqa: F401
from sheeprl_tpu.algos.sac import evaluate  # noqa: F401  (registers the evaluation)
