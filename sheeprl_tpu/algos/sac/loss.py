"""SAC losses (reference: sheeprl/algos/sac/loss.py:10-27)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def policy_loss(alpha: Array, logprobs: Array, qf_values: Array) -> Array:
    """Eq. 7."""
    return ((alpha * logprobs) - qf_values).mean()


def critic_loss(qf_values: Array, next_qf_value: Array, num_critics: int) -> Array:
    """Eq. 5: sum of per-critic MSE against the shared target."""
    return sum(
        jnp.mean(jnp.square(qf_values[..., i : i + 1] - next_qf_value)) for i in range(num_critics)
    )


def entropy_loss(log_alpha: Array, logprobs: Array, target_entropy: float) -> Array:
    """Eq. 17."""
    return (-log_alpha * (logprobs + target_entropy)).mean()
