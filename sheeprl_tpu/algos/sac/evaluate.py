"""SAC evaluation entrypoint (reference: sheeprl/algos/sac/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.utils import test
from sheeprl_tpu.envs import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["sac", "sac_decoupled"])
def evaluate(fabric, cfg: Dict[str, Any], state: Dict[str, Any]) -> None:
    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    env.close()

    _, player = build_agent(fabric, cfg, observation_space, action_space, state["agent"])
    test(player, fabric, cfg, log_dir)
    logger.finalize()
