"""SAC agent (reference: sheeprl/algos/sac/agent.py:20-373).

flax re-design: the critic ensemble is a single ``SACCritic`` module with
**vmapped stacked params** — the TPU-native replacement for the reference's
per-critic ``nn.ModuleList`` loop (agent.py:248-253); all ensemble members
evaluate in one batched matmul on the MXU. Target critics are a stacked
params copy updated by a jitted EMA.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.parallel.fabric import HostPlayerParams, put_tree

Array = jax.Array

LOG_STD_MAX = 2.0
LOG_STD_MIN = -5.0


class SACCritic(nn.Module):
    """Q(s, a) MLP (reference agent.py:20-54); ensemble via vmapped params."""

    hidden_size: int = 256
    num_critics: int = 1
    dropout: float = 0.0  # used by DroQ
    layer_norm: bool = False  # used by DroQ
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Array, action: Array, deterministic: bool = True) -> Array:
        x = jnp.concatenate([obs, action], axis=-1).astype(self.dtype)
        for _ in range(2):
            x = nn.Dense(self.hidden_size, dtype=self.dtype, param_dtype=jnp.float32)(x)
            if self.dropout > 0.0:
                x = nn.Dropout(rate=self.dropout)(x, deterministic=deterministic)
            if self.layer_norm:
                x = nn.LayerNorm(dtype=jnp.float32)(x.astype(jnp.float32)).astype(self.dtype)
            x = nn.relu(x)
        return nn.Dense(self.num_critics, dtype=jnp.float32, param_dtype=jnp.float32)(x)


class SACActor(nn.Module):
    """Tanh-squashed Gaussian policy (reference agent.py:57-142)."""

    action_dim: int
    hidden_size: int = 256
    action_low: Tuple[float, ...] = (-1.0,)
    action_high: Tuple[float, ...] = (1.0,)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Array) -> Tuple[Array, Array]:
        x = obs.astype(self.dtype)
        for _ in range(2):
            x = nn.Dense(self.hidden_size, dtype=self.dtype, param_dtype=jnp.float32)(x)
            x = nn.relu(x)
        mean = nn.Dense(self.action_dim, dtype=jnp.float32, param_dtype=jnp.float32, name="fc_mean")(x)
        log_std = nn.Dense(self.action_dim, dtype=jnp.float32, param_dtype=jnp.float32, name="fc_logstd")(x)
        return mean, log_std

    @property
    def action_scale(self) -> Array:
        return (jnp.asarray(self.action_high) - jnp.asarray(self.action_low)) / 2.0

    @property
    def action_bias(self) -> Array:
        return (jnp.asarray(self.action_high) + jnp.asarray(self.action_low)) / 2.0


def actor_action_and_log_prob(
    actor: SACActor, params: Any, obs: Array, key: Array
) -> Tuple[Array, Array]:
    """rsample a squashed action and its log-prob (Eq. 26 of the SAC paper;
    reference agent.py:110-142)."""
    mean, log_std = actor.apply(params, obs)
    std = jnp.exp(jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
    x_t = mean + std * jax.random.normal(key, mean.shape)
    y_t = jnp.tanh(x_t)
    scale, bias = actor.action_scale, actor.action_bias
    action = y_t * scale + bias
    # Normal log-prob minus the tanh+scale change of variables
    log_prob = -0.5 * (jnp.square((x_t - mean) / std) + 2 * jnp.log(std) + jnp.log(2 * jnp.pi))
    log_prob = log_prob - jnp.log(scale * (1 - jnp.square(y_t)) + 1e-6)
    return action, log_prob.sum(-1, keepdims=True)


def actor_greedy_action(actor: SACActor, params: Any, obs: Array) -> Array:
    mean, _ = actor.apply(params, obs)
    return jnp.tanh(mean) * actor.action_scale + actor.action_bias


class SACAgent:
    """Host handle holding modules + param trees (reference SACAgent,
    agent.py:145-267). All numeric paths are pure functions over the trees."""

    def __init__(
        self,
        actor: SACActor,
        critic: SACCritic,
        actor_params: Any,
        critic_params: Any,  # stacked [n_critics, ...]
        target_entropy: float,
        alpha: float = 1.0,
        tau: float = 0.005,
        num_critics: int = 2,
    ) -> None:
        self.actor = actor
        self.critic = critic
        self.actor_params = actor_params
        self.critic_params = critic_params
        self.target_critic_params = jax.tree.map(jnp.copy, critic_params)
        self.log_alpha = jnp.log(jnp.asarray([alpha], jnp.float32))
        self.target_entropy = float(target_entropy)
        self.tau = float(tau)
        self.num_critics = num_critics

    @property
    def alpha(self) -> float:
        return float(jnp.exp(self.log_alpha)[0])


def critic_ensemble_apply(critic: SACCritic, stacked_params: Any, obs: Array, action: Array) -> Array:
    """[n_critics, B, 1] -> [B, n_critics] Q-values in one vmapped call."""
    qs = jax.vmap(lambda p: critic.apply(p, obs, action))(stacked_params)
    return jnp.moveaxis(qs[..., 0], 0, -1)


class SACPlayer(HostPlayerParams):
    """Rollout/eval policy handle (reference SACPlayer, agent.py:270-314).

    ``device`` optionally pins inference to the host CPU backend
    (learner-on-chip/actor-on-host for remote-attached chips; see
    ``parallel.fabric.resolve_player_device``)."""

    _placed_attrs = ("params",)

    def __init__(self, actor: SACActor, params: Any, device: Optional[Any] = None) -> None:
        self.actor = actor
        self.device = device  # must precede the params assignment
        self.params = params
        self._sample = jax.jit(lambda p, o, k: actor_action_and_log_prob(actor, p, o, k)[0])
        self._greedy = jax.jit(lambda p, o: actor_greedy_action(actor, p, o))

    def update_params(self, params: Any) -> None:
        """Per-train-block refresh: non-blocking in host-player mode (the
        SAC family is off-policy — a block or two of param staleness is the
        standard actor-learner lag; see ``fabric.HostPlayerParams.stream_attr``)."""
        self.stream_attr("params", params)

    def get_actions(self, obs: Array, key: Optional[Array] = None, greedy: bool = False) -> np.ndarray:
        self.poll_stream_attrs()
        if greedy:
            return np.asarray(self._greedy(self.params, obs))
        return np.asarray(self._sample(self.params, obs, put_tree(key, self.device)))


def finite_action_bounds(action_space: gymnasium.spaces.Box) -> Tuple[Tuple[float, ...], Tuple[float, ...]]:
    """Per-dimension (low, high) with non-finite bounds clamped to ±1: an
    unbounded Box means "no rescale", and a literal ``inf`` scale would turn
    the tanh-squashed action (and every loss downstream) into NaN."""
    low = np.asarray(action_space.low, np.float32).ravel()
    high = np.asarray(action_space.high, np.float32).ravel()
    unbounded = ~(np.isfinite(low) & np.isfinite(high))
    low = np.where(unbounded, -1.0, low).astype(np.float32)
    high = np.where(unbounded, 1.0, high).astype(np.float32)
    return tuple(low.tolist()), tuple(high.tolist())


def build_agent(
    fabric: Any,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    action_space: gymnasium.spaces.Box,
    agent_state: Optional[Dict[str, Any]] = None,
    critic_cls: type = SACCritic,
    critic_kwargs: Optional[Dict[str, Any]] = None,
) -> Tuple[SACAgent, SACPlayer]:
    act_dim = int(np.prod(action_space.shape))
    obs_dim = int(sum(np.prod(obs_space[k].shape) for k in cfg["algo"]["mlp_keys"]["encoder"]))
    dtype = fabric.precision.compute_dtype

    action_low, action_high = finite_action_bounds(action_space)
    actor = SACActor(
        action_dim=act_dim,
        hidden_size=int(cfg["algo"]["actor"]["hidden_size"]),
        action_low=action_low,
        action_high=action_high,
        dtype=dtype,
    )
    n_critics = int(cfg["algo"]["critic"]["n"])
    critic = critic_cls(
        hidden_size=int(cfg["algo"]["critic"]["hidden_size"]),
        num_critics=1,
        dtype=dtype,
        **(critic_kwargs or {}),
    )

    key = jax.random.PRNGKey(int(cfg["seed"]))
    k_actor, *k_critics = jax.random.split(key, n_critics + 1)
    dummy_obs = jnp.zeros((1, obs_dim), jnp.float32)
    dummy_act = jnp.zeros((1, act_dim), jnp.float32)

    if agent_state is not None:
        actor_params = jax.tree.map(jnp.asarray, agent_state["actor"])
        critic_params = jax.tree.map(jnp.asarray, agent_state["critics"])
        agent = SACAgent(
            actor,
            critic,
            fabric.replicate(actor_params),
            fabric.replicate(critic_params),
            target_entropy=-act_dim,
            alpha=float(cfg["algo"]["alpha"]["alpha"]),
            tau=float(cfg["algo"]["tau"]),
            num_critics=n_critics,
        )
        agent.target_critic_params = fabric.replicate(jax.tree.map(jnp.asarray, agent_state["target_critics"]))
        agent.log_alpha = fabric.replicate(jnp.asarray(agent_state["log_alpha"]))
    else:
        actor_params = actor.init(k_actor, dummy_obs)
        critic_params = jax.vmap(lambda k: critic.init(k, dummy_obs, dummy_act))(jnp.stack(k_critics))
        agent = SACAgent(
            actor,
            critic,
            fabric.replicate(actor_params),
            fabric.replicate(critic_params),
            target_entropy=-act_dim,
            alpha=float(cfg["algo"]["alpha"]["alpha"]),
            tau=float(cfg["algo"]["tau"]),
            num_critics=n_critics,
        )
        agent.target_critic_params = fabric.replicate(agent.target_critic_params)
    from sheeprl_tpu.parallel.fabric import resolve_player_device

    player = SACPlayer(
        actor,
        agent.actor_params,
        device=resolve_player_device(cfg["algo"].get("player_device", "auto")),
    )
    return agent, player
