"""SAC, coupled (reference: sheeprl/algos/sac/sac.py:32-424) — TPU-native.

Redesign highlights:

- **All G gradient steps of an update fused into one jit**: the sampled
  ``[G, B, ...]`` batch is scanned on device (critic, EMA, actor, alpha
  updates per step) — the reference dispatches each minibatch from Python
  (sac.py:337-351).
- **Critic ensemble is vmapped**, not looped.
- The reference's per-rank sample → ``fabric.all_gather`` → DistributedSampler
  round-robin (sac.py:303-333) collapses to: host samples the global batch,
  shard_map splits it over the data axis, gradient ``pmean`` restores DDP
  semantics (including the explicit ``log_alpha.grad`` all-reduce,
  sac.py:72).
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from sheeprl_tpu.ops.optim import build_tx
from sheeprl_tpu.parallel.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.sac.agent import (
    SACAgent,
    actor_action_and_log_prob,
    build_agent,
    critic_ensemble_apply,
)
from sheeprl_tpu.algos.sac.loss import critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.data.device_buffer import draw_transition_batch
from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.obs import (
    log_sps_and_heartbeat,
    telemetry_advance,
    telemetry_run_metrics,
    telemetry_train_window,
)
from sheeprl_tpu.ops.superstep import fold_sample_key, fused_fallback, reset_fused_fallback_warnings
from sheeprl_tpu.resilience import RunResilience
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, SteadyStateProbe, gradient_step_chunks, save_configs, weighted_chunk_metrics


def make_train_fn(
    fabric,
    agent: SACAgent,
    actor_tx,
    critic_tx,
    alpha_tx,
    cfg,
    *,
    fused_length=None,
    fused_batch_size=None,
    fused_sample_next_obs=False,
):
    gamma = float(cfg.algo.gamma)
    tau = float(cfg.algo.tau)
    target_entropy = agent.target_entropy
    num_critics = agent.num_critics
    actor, critic = agent.actor, agent.critic
    data_axis = fabric.data_axis
    multi_device = fabric.world_size > 1
    # fused superstep mode (algo.fused_gradient_steps): instead of scanning a
    # pre-gathered [G, B, ...] batch, `data` is the device ring's
    # (bufs, pos, full) context and every scanned step draws its own batch
    # on device — replay gather, critic/actor/alpha updates and the target
    # EMA all land in ONE dispatch per chunk (ops/superstep.py rationale)
    fused = fused_length is not None
    if fused and multi_device:
        # fused + mesh = pure data-parallel shard_map (main() has already
        # fallen back for model_axis / multi-process runs): the ring context
        # arrives env-axis sharded and every device scans its own in-graph
        # draws of a per-shard batch
        if fabric.model_axis is not None or fabric.num_processes != 1:
            raise ValueError(
                "fused in-scan gather supersteps need a single-process pure "
                f"data-parallel run; got model_axis={fabric.model_axis!r}, "
                f"num_processes={fabric.num_processes}"
            )
        if int(fused_batch_size) % fabric.data_parallel_size:
            raise ValueError(
                f"fused_batch_size ({fused_batch_size}) must divide by "
                f"data_parallel_size ({fabric.data_parallel_size})"
            )
    fused_draw_size = (
        int(fused_batch_size) // (fabric.data_parallel_size if multi_device else 1)
        if fused
        else None
    )
    # EMA cadence in gradient steps (reference sac.py:56 ties it to updates)
    ema_every = max(1, int(cfg.algo.critic.target_network_frequency) // max(1, int(cfg.env.num_envs)))

    def pmean(x):
        return lax.pmean(x, data_axis) if multi_device else x

    def local_train(
        actor_params, critic_params, target_params, log_alpha,
        actor_opt, critic_opt, alpha_opt, grad_counter, data, key,
    ):
        if multi_device:
            key = jax.random.fold_in(key, lax.axis_index(data_axis))

        def one_step(carry, batch):
            (actor_params, critic_params, target_params, log_alpha,
             actor_opt, critic_opt, alpha_opt, counter, key) = carry
            key, k_next, k_actor = jax.random.split(key, 3)
            alpha = jnp.exp(log_alpha)

            # soft critic update (Eq. 5)
            next_actions, next_logpi = actor_action_and_log_prob(
                actor, actor_params, batch["next_observations"], k_next
            )
            q_next = critic_ensemble_apply(critic, target_params, batch["next_observations"], next_actions)
            min_q_next = jnp.min(q_next, axis=-1, keepdims=True) - alpha * next_logpi
            target = batch["rewards"] + (1 - batch["terminated"]) * gamma * min_q_next
            target = lax.stop_gradient(target)

            def critic_loss_fn(p):
                q = critic_ensemble_apply(critic, p, batch["observations"], batch["actions"])
                return critic_loss(q, target, num_critics)

            qf_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(critic_params)
            critic_grads = pmean(critic_grads)
            updates, critic_opt = critic_tx.update(critic_grads, critic_opt, critic_params)
            critic_params = optax.apply_updates(critic_params, updates)

            # target EMA (reference agent.py:264-267)
            do_ema = (counter % ema_every) == 0
            target_params = jax.tree.map(
                lambda c, t: jnp.where(do_ema, tau * c + (1 - tau) * t, t), critic_params, target_params
            )

            # actor update (Eq. 7)
            def actor_loss_fn(p):
                actions, logpi = actor_action_and_log_prob(actor, p, batch["observations"], k_actor)
                q = critic_ensemble_apply(critic, critic_params, batch["observations"], actions)
                min_q = jnp.min(q, axis=-1, keepdims=True)
                return policy_loss(alpha, logpi, min_q), logpi

            (a_loss, logpi), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(actor_params)
            actor_grads = pmean(actor_grads)
            updates, actor_opt = actor_tx.update(actor_grads, actor_opt, actor_params)
            actor_params = optax.apply_updates(actor_params, updates)

            # entropy coefficient update (Eq. 17; grad all-reduced like
            # reference sac.py:72)
            alpha_grad = jax.grad(lambda la: entropy_loss(la, lax.stop_gradient(logpi), target_entropy))(
                log_alpha
            )
            alpha_grad = pmean(alpha_grad)
            updates, alpha_opt = alpha_tx.update(alpha_grad, alpha_opt, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, updates)

            alpha_l = entropy_loss(log_alpha, logpi, target_entropy)
            carry = (actor_params, critic_params, target_params, log_alpha,
                     actor_opt, critic_opt, alpha_opt, counter + 1, key)
            return carry, jnp.stack([qf_loss, a_loss, alpha_l])

        carry = (actor_params, critic_params, target_params, log_alpha,
                 actor_opt, critic_opt, alpha_opt, grad_counter, key)
        if fused:
            bufs, pos, full = data

            def fused_step(carry, _):
                # the draw key is the carried key folded with the sample salt,
                # so the index noise never correlates with the gradient noise
                # one_step derives from the same key via split
                # the carried key was already folded with axis_index on a
                # mesh (local_train's first line), so the salted draw is
                # per-shard decorrelated for free
                batch = draw_transition_batch(
                    bufs,
                    pos,
                    full,
                    fold_sample_key(carry[-1]),
                    fused_draw_size,
                    sample_next_obs=fused_sample_next_obs,
                    obs_keys=("observations",),
                )
                return one_step(carry, batch)

            carry, metrics = lax.scan(fused_step, carry, None, length=int(fused_length))
        else:
            carry, metrics = lax.scan(one_step, carry, data)
        (actor_params, critic_params, target_params, log_alpha,
         actor_opt, critic_opt, alpha_opt, grad_counter, _) = carry
        return (
            actor_params, critic_params, target_params, log_alpha,
            actor_opt, critic_opt, alpha_opt, grad_counter,
            pmean(metrics.mean(axis=0)),
        )

    if multi_device:
        # data slot: pre-gathered [G, B, ...] stacks shard along the batch
        # axis; a fused ring context (bufs, pos, full) shards along the env
        # axis, matching the DeviceReplayBuffer's placement
        data_spec = (
            (P(data_axis), P(data_axis), P(data_axis)) if fused else P(None, data_axis)
        )
        train_fn = shard_map(
            local_train,
            mesh=fabric.mesh,
            in_specs=(P(), P(), P(), P(), P(), P(), P(), P(), data_spec, P()),
            out_specs=(P(), P(), P(), P(), P(), P(), P(), P(), P()),
        )
    else:
        train_fn = local_train
    # donate only optimizer/aux state: param buffers stay un-donated because
    # concurrent readers (async param streaming to the host player, the ema /
    # hard-copy target refresh) may still be in flight when the next train
    # dispatch would otherwise alias over them (observed on the remote chip
    # as spurious INVALID_ARGUMENT errors surfacing at unrelated fetches)
    return jax.jit(train_fn, donate_argnums=(4, 5, 6))


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    rank = fabric.process_index
    world_size = fabric.data_parallel_size  # batch-split width: the data axis (= device count on a 1-D mesh)
    num_processes = fabric.num_processes  # hosts: sets the env-step accounting
    num_envs = int(cfg.env.num_envs)

    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)

    if len(cfg.algo.cnn_keys.encoder) > 0:
        warnings.warn("SAC algorithm cannot allow to use images as observations, the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")
    resil = RunResilience(fabric, cfg, log_dir)

    envs = build_vector_env(cfg, rank, log_dir if rank == 0 else None, "train")
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    if len(mlp_keys) == 0:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")
    for k in mlp_keys:
        if len(observation_space[k].shape) > 1:
            raise ValueError(
                "Only environments with vector-only observations are supported by the SAC agent. "
                f"The observation with key '{k}' has shape {observation_space[k].shape}."
            )

    agent, player = build_agent(
        fabric, cfg, observation_space, action_space, state["agent"] if cfg.checkpoint.resume_from else None
    )

    critic_tx = build_tx(cfg.algo.critic.optimizer)
    actor_tx = build_tx(cfg.algo.actor.optimizer)
    alpha_tx = build_tx(cfg.algo.alpha.optimizer)
    critic_opt = fabric.replicate(critic_tx.init(jax.device_get(agent.critic_params)))
    actor_opt = fabric.replicate(actor_tx.init(jax.device_get(agent.actor_params)))
    alpha_opt = fabric.replicate(alpha_tx.init(jax.device_get(agent.log_alpha)))
    if cfg.checkpoint.resume_from:
        critic_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["qf_optimizer"]))
        actor_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["actor_optimizer"]))
        alpha_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["alpha_optimizer"]))

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in AGGREGATOR_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    buffer_size = cfg.buffer.size // int(num_envs * num_processes) if not cfg.dry_run else 1
    # HBM replay ring when the chip allows it (buffer.device=auto): each
    # transition is uploaded once, every high-replay-ratio resample is an
    # on-chip gather — the same trade the Dreamer loops made in round 3
    from sheeprl_tpu.data.device_buffer import (
        DeviceReplayBuffer,
        adapt_restored_buffer,
        make_transition_replay,
    )

    rb = make_transition_replay(
        cfg,
        fabric,
        observation_space,
        stored_keys=mlp_keys,
        actions_dim=action_space.shape,
        buffer_size=buffer_size,
        num_envs=num_envs,
        obs_keys=("observations",),
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        seed=cfg.seed,
        store_next_obs=not cfg.buffer.sample_next_obs,
    )
    use_device_rb = isinstance(rb, DeviceReplayBuffer)
    if cfg.checkpoint.resume_from and cfg.buffer.checkpoint:
        from sheeprl_tpu.utils.checkpoint import select_buffer

        rb = adapt_restored_buffer(
            select_buffer(state["rb"], rank, num_processes),
            use_device_rb,
            seed=cfg.seed,
            mode="transition",
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        )

    # fused supersteps (algo.fused_gradient_steps): K > 0 moves the replay
    # gather INSIDE the scanned chunk so one train window of G steps issues
    # ceil(G / K) dispatches with no host round trip in between
    fused_k = int(cfg.algo.get("fused_gradient_steps", 0) or 0)
    if fused_k > 0:
        reset_fused_fallback_warnings()
        if not use_device_rb:
            fused_fallback(
                "host_buffer",
                "algo.fused_gradient_steps needs the device replay buffer (buffer.device) to draw "
                "batches inside the scanned chunk; the host-buffer path already runs each chunk as "
                "one dispatch. Falling back to the per-chunk host gather.",
            )
            fused_k = 0
        elif fabric.num_processes > 1:
            fused_fallback(
                "multi_process",
                "algo.fused_gradient_steps cannot span processes "
                f"(num_processes={fabric.num_processes}); falling back to the per-chunk gather path.",
            )
            fused_k = 0
        elif fabric.world_size > 1 and fabric.model_axis is not None:
            fused_fallback(
                "model_axis",
                "algo.fused_gradient_steps is pure data-parallel, but this run shards params "
                f"over model_axis={fabric.model_axis!r}; falling back to the per-chunk gather path.",
            )
            fused_k = 0

    train_fn = make_train_fn(fabric, agent, actor_tx, critic_tx, alpha_tx, cfg)

    train_step = 0
    last_train = 0
    start_step = state["update"] + 1 if cfg.checkpoint.resume_from else 1
    policy_step = state["update"] * num_envs * num_processes if cfg.checkpoint.resume_from else 0
    last_log = state["last_log"] if cfg.checkpoint.resume_from else 0
    last_checkpoint = state["last_checkpoint"] if cfg.checkpoint.resume_from else 0
    policy_steps_per_update = int(num_envs * num_processes)
    num_updates = int(cfg.algo.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    per_rank_batch_size = int(cfg.algo.per_rank_batch_size)
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import elastic_per_rank_batch_size

        per_rank_batch_size = elastic_per_rank_batch_size(state["batch_size"], world_size)
        if not cfg.buffer.checkpoint:
            learning_starts += start_step

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if cfg.checkpoint.resume_from:
        ratio.load_state_dict(state["ratio"])

    # per scanned length one compiled superstep (chunking keeps the set of
    # lengths at {fused_k} ∪ {possible remainders}); built lazily AFTER the
    # elastic resume may have rewritten per_rank_batch_size
    fused_train_fns: Dict[int, Any] = {}

    def get_fused_fn(n: int):
        fn = fused_train_fns.get(n)
        if fn is None:
            fn = make_train_fn(
                fabric,
                agent,
                actor_tx,
                critic_tx,
                alpha_tx,
                cfg,
                fused_length=n,
                fused_batch_size=per_rank_batch_size * fabric.local_data_parallel_size,
                fused_sample_next_obs=bool(cfg.buffer.sample_next_obs),
            )
            fused_train_fns[n] = fn
        return fn

    key = jax.random.PRNGKey(int(cfg.seed))
    grad_counter = jnp.zeros((), jnp.int32)
    # action keys stay on the player's device (no chip round trip per step
    # when the player is host-pinned)
    from sheeprl_tpu.parallel.fabric import put_tree

    player_key = put_tree(jax.random.fold_in(key, 1), player.device)

    obs, _ = envs.reset(seed=cfg.seed)
    cumulative_per_rank_gradient_steps = 0
    step_data: Dict[str, np.ndarray] = {}

    def ckpt_state_fn(completed_update: int) -> Dict[str, Any]:
        return {
            "agent": {
                "actor": jax.device_get(agent.actor_params),
                "critics": jax.device_get(agent.critic_params),
                "target_critics": jax.device_get(agent.target_critic_params),
                "log_alpha": jax.device_get(agent.log_alpha),
            },
            "qf_optimizer": jax.device_get(critic_opt),
            "actor_optimizer": jax.device_get(actor_opt),
            "alpha_optimizer": jax.device_get(alpha_opt),
            "ratio": ratio.state_dict(),
            "update": completed_update,
            "batch_size": per_rank_batch_size * world_size,
            "last_log": last_log,
            "last_checkpoint": last_checkpoint,
        }

    def ckpt_path_fn(step: int) -> str:
        return os.path.join(log_dir, "checkpoint", f"ckpt_{step}_{rank}.ckpt")

    # a crash anywhere in the loop gets the preemption treatment too: the
    # lambdas read the loop's CURRENT policy_step/update at crash time
    resil.arm_crash_guard(
        path_fn=lambda: ckpt_path_fn(policy_step),
        state_fn=lambda: ckpt_state_fn(update - 1),
        replay_buffer_fn=lambda: rb if cfg.buffer.checkpoint else None,
    )
    preempted = False
    # steady-state throughput probe (SHEEPRL_TPU_BENCH_JSON contract)
    probe = SteadyStateProbe()
    for update in range(start_step, num_updates + 1):
        telemetry_advance(policy_step)
        if resil.preempt_requested():
            last_checkpoint = policy_step
            resil.emergency_checkpoint(
                ckpt_path_fn(policy_step),
                ckpt_state_fn(update - 1),
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )
            preempted = True
            break
        probe.mark_warm(update, learning_starts, policy_step, work=cumulative_per_rank_gradient_steps)
        policy_step += num_envs * num_processes

        with timer("Time/env_interaction_time"):
            if update <= learning_starts:
                actions = envs.action_space.sample()
            else:
                player_key, action_key = jax.random.split(player_key)
                np_obs = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs)
                actions = player.get_actions(np_obs, action_key)
            next_obs, rewards, terminated, truncated, infos = envs.step(
                np.asarray(actions).reshape(envs.action_space.shape)
            )

        if cfg.metric.log_level > 0 and "final_info" in infos:
            ep = infos["final_info"].get("episode")
            if ep is not None:
                for i in np.nonzero(ep.get("_r", []))[0]:
                    aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                    aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                    print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

        real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items()}
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    for k, v in final_obs.items():
                        real_next_obs[k][idx] = v

        step_data["terminated"] = np.asarray(terminated, np.float32).reshape(1, num_envs, 1)
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape(1, num_envs, 1)
        step_data["actions"] = np.asarray(actions, np.float32).reshape(1, num_envs, -1)
        step_data["observations"] = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs)[np.newaxis]
        if not cfg.buffer.sample_next_obs:
            step_data["next_observations"] = prepare_obs(
                real_next_obs, mlp_keys=mlp_keys, num_envs=num_envs
            )[np.newaxis]
        step_data["rewards"] = np.asarray(rewards, np.float32).reshape(1, num_envs, 1)
        rb.add(step_data, validate_args=cfg.buffer.validate_args)

        obs = next_obs

        if update >= learning_starts:
            per_rank_gradient_steps = ratio(policy_step / num_processes)
            # fixed-size scan chunks: every distinct scan length is a fresh
            # XLA compile, and Ratio's first post-warmup call repays the whole
            # warmup debt in one G (utils.gradient_step_chunks)
            chunk_metrics = []
            window_dispatches = 0
            chunk_cfg = {"gradient_steps_chunk": fused_k} if fused_k > 0 else cfg.algo
            for chunk_steps in gradient_step_chunks(per_rank_gradient_steps, chunk_cfg):
                # [G, B_total, ...] so the chunk's gradient loop runs in one
                # jit; each process samples its share of the global batch and
                # the shards assemble into one global array over the mesh
                chunk_fn = train_fn
                if fused_k > 0:
                    # in-scan gather: the whole chunk is ONE dispatch; only
                    # the [E] pos/full cursors cross the link per chunk
                    data = rb.superstep_inputs(sample_next_obs=cfg.buffer.sample_next_obs)
                    chunk_fn = get_fused_fn(chunk_steps)
                    window_dispatches += 1
                elif use_device_rb:
                    # on-chip gather: only the indices cross the link.
                    # local_data_parallel_size, NOT local_device_count: on a
                    # 2-D (data x model) mesh the batch splits over the data
                    # axis only — model-axis devices see the same batch shard
                    data = rb.sample_transitions(
                        batch_size=per_rank_batch_size * fabric.local_data_parallel_size,
                        n_samples=chunk_steps,
                        sample_next_obs=cfg.buffer.sample_next_obs,
                    )
                    window_dispatches += 2  # gather program + scanned train program
                else:
                    window_dispatches += 1
                    sample = rb.sample(
                        batch_size=per_rank_batch_size * fabric.local_data_parallel_size,
                        n_samples=chunk_steps,
                        sample_next_obs=cfg.buffer.sample_next_obs,
                    )
                    data = {k: np.asarray(v, np.float32) for k, v in sample.items()}
                    if num_processes > 1:
                        data = fabric.make_global(data, (None, fabric.data_axis))
                    else:
                        # async HBM staging: device_put returns immediately and
                        # XLA orders the copy before the fused train step reads
                        # it; on a mesh the stack goes up pre-sharded along the
                        # batch axis (the train fn's in_spec), not replicated
                        from sheeprl_tpu.data.buffers import to_device
                        data = to_device(
                            data,
                            sharding=fabric.sharding(None, fabric.data_axis)
                            if fabric.world_size > 1
                            else None,
                        )
                with timer("Time/train_time"):
                    key, train_key = jax.random.split(key)
                    (
                        agent.actor_params,
                        agent.critic_params,
                        agent.target_critic_params,
                        agent.log_alpha,
                        actor_opt,
                        critic_opt,
                        alpha_opt,
                        grad_counter,
                        metrics,
                    ) = chunk_fn(
                        agent.actor_params,
                        agent.critic_params,
                        agent.target_critic_params,
                        agent.log_alpha,
                        actor_opt,
                        critic_opt,
                        alpha_opt,
                        grad_counter,
                        data,
                        train_key,
                    )
                    chunk_metrics.append((chunk_steps, metrics))  # device array; fetched once below
                cumulative_per_rank_gradient_steps += chunk_steps
            if per_rank_gradient_steps > 0:
                telemetry_train_window(window_dispatches, per_rank_gradient_steps)
                train_step += num_processes  # one "train event" per update
                # one fetch serves both the sentinel and the aggregator
                window_metrics = weighted_chunk_metrics(chunk_metrics)
                if not resil.check_finite(window_metrics, update):
                    # restore the newest committed checkpoint over the whole
                    # train state (params + all three optimizers) and fork
                    # the sample key away from the stream that diverged
                    restored = resil.rollback(update=update)
                    ra = restored["agent"]
                    agent.actor_params = resil.place_like(ra["actor"], agent.actor_params)
                    agent.critic_params = resil.place_like(ra["critics"], agent.critic_params)
                    agent.target_critic_params = resil.place_like(
                        ra["target_critics"], agent.target_critic_params
                    )
                    agent.log_alpha = resil.place_like(ra["log_alpha"], agent.log_alpha)
                    actor_opt = resil.place_like(restored["actor_optimizer"], actor_opt)
                    critic_opt = resil.place_like(restored["qf_optimizer"], critic_opt)
                    alpha_opt = resil.place_like(restored["alpha_optimizer"], alpha_opt)
                    key = resil.resalt_key(key)
                    player.update_params(agent.actor_params)
                    continue
                player.update_params(agent.actor_params)
                if cfg.metric.log_level > 0:
                    metrics = window_metrics
                    aggregator.update("Loss/value_loss", float(metrics[0]))
                    aggregator.update("Loss/policy_loss", float(metrics[1]))
                    aggregator.update("Loss/alpha_loss", float(metrics[2]))

        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or update == num_updates):
            metrics_dict = aggregator.compute()
            logger.log_metrics(metrics_dict, policy_step)
            telemetry_run_metrics(metrics_dict)
            aggregator.reset()
            if policy_step > 0:
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * num_processes / policy_step},
                    policy_step,
                )
            log_sps_and_heartbeat(
                logger,
                policy_step=policy_step,
                env_steps=(policy_step - last_log) / num_processes * cfg.env.action_repeat,
                train_steps=train_step - last_train,
            )
            last_log = policy_step
            last_train = train_step

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            update == num_updates and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path_fn(policy_step),
                state=ckpt_state_fn(update),
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )

    probe.finish(
        policy_step,
        # a materializing fetch is the only real device sync on the tunnel
        sync=lambda: np.asarray(jax.device_get(agent.log_alpha)),
        work=cumulative_per_rank_gradient_steps,
    )
    # land any in-flight async param stream before the final evaluation
    player.flush_stream_attrs()
    envs.close()
    if fabric.is_global_zero and cfg.algo.run_test and not preempted:
        test(player, fabric, cfg, log_dir)
    logger.finalize()
    resil.close()
    if preempted:
        resil.exit_preempted()
