"""SAC helpers (reference: sheeprl/algos/sac/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

from sheeprl_tpu.obs.telemetry import telemetry_deliberate_compiles
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
}
MODELS_TO_REGISTER = {"agent"}


def prepare_obs(
    obs: Dict[str, np.ndarray], mlp_keys: Sequence[str] = (), num_envs: int = 1
) -> np.ndarray:
    """Concatenate vector keys -> [num_envs, obs_dim] float32 (reference
    utils.py:31-34)."""
    return np.concatenate([np.asarray(obs[k], np.float32) for k in mlp_keys], axis=-1).reshape(
        num_envs, -1
    )


# the eval rollout compiles fresh programs (eval batch shapes) after the
# loop's warm point; that is a deliberate one-time compile, not a retrace
@telemetry_deliberate_compiles("eval_rollout")
def test(player: Any, fabric: Any, cfg: Dict[str, Any], log_dir: str) -> None:
    """Greedy evaluation episode (reference utils.py:38-62)."""
    from sheeprl_tpu.envs import make_env

    env = make_env(cfg, None, 0, log_dir, "test", vector_env_idx=0)()
    done = False
    cumulative_rew = 0.0
    obs, _ = env.reset(seed=cfg.seed)
    while not done:
        np_obs = prepare_obs(obs, mlp_keys=cfg.algo.mlp_keys.encoder)
        action = player.get_actions(np_obs, greedy=True)
        obs, reward, terminated, truncated, _ = env.step(action.reshape(env.action_space.shape))
        done = terminated or truncated or cfg.dry_run
        cumulative_rew += float(reward)
    print(f"Test - Reward: {cumulative_rew}")
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()


def log_models_from_checkpoint(fabric, cfg, state, artifacts_dir):
    """Pickle this algorithm's registered sub-models from a checkpoint
    (reference per-algo log_models_from_checkpoint; shared body in
    utils/model_manager.py)."""
    from sheeprl_tpu.utils.model_manager import log_models_from_checkpoint as _log

    return _log(state, sorted(MODELS_TO_REGISTER), artifacts_dir)
