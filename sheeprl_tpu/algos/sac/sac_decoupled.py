"""SAC, decoupled player/trainer topology (reference:
sheeprl/algos/sac/sac_decoupled.py:33-583) — TPU-native.

Same role split as ``ppo_decoupled``: process 0 is the PLAYER — it owns the
environments AND the replay buffer (reference :33-352), samples the training
batches and ships them; processes 1..N-1 are TRAINERS on their own mesh
running the fused SAC update of ``sac.make_train_fn`` with gradient ``pmean``
over the trainer mesh (reference trainer branch :352-542).

Per-update protocol on the host-object plane (both sides always make both
calls, so the collectives stay aligned even on no-train updates):

1. ``broadcast_object(batches | None, src=0)`` — the sampled ``[G, B, ...]``
   chunks (reference buffer-chunk scatter, :303-330),
2. ``broadcast_object(payload | None, src=1)`` — updated actor params for
   the player's policy (+ the full agent/optimizer state on checkpoint
   updates, reference on_checkpoint_player).
"""

from __future__ import annotations

import os
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.ops.optim import build_tx
from sheeprl_tpu.algos.ppo.ppo_decoupled import _ckpt_schedule, _trainer_devices
from sheeprl_tpu.algos.sac.agent import SACPlayer, build_agent
from sheeprl_tpu.algos.sac.sac import make_train_fn
from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.data import ReplayBuffer
from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.parallel.collectives import broadcast_object
from sheeprl_tpu.parallel.submesh import LocalFabric, SubMeshFabric, probe_spaces
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs


@register_algorithm(decoupled=True)
def main(fabric, cfg: Dict[str, Any]):
    if jax.process_count() < 2:
        raise RuntimeError(
            "sac_decoupled requires at least 2 processes: one player and one or more trainers "
            "(reference sac_decoupled.py:552-556)"
        )
    # every process restores from the same checkpoint file (reference
    # sac_decoupled.py resume; see also ppo_decoupled.py:45-46,104-116)
    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    if len(cfg.algo.cnn_keys.encoder) > 0:
        cfg.algo.cnn_keys.encoder = []
    if jax.process_index() == 0:
        _player(fabric, cfg, state)
    else:
        _trainer(fabric, cfg, state)


def _counters(cfg, num_envs):
    policy_steps_per_update = num_envs
    num_updates = int(cfg.algo.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    return policy_steps_per_update, num_updates, learning_starts


def _player(fabric, cfg, state=None):
    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")

    num_envs = int(cfg.env.num_envs)
    trainer_devs = _trainer_devices()
    policy_steps_per_update, num_updates, learning_starts = _counters(cfg, num_envs)
    start_update = state["update"] + 1 if state else 1
    ckpt_updates = _ckpt_schedule(
        cfg,
        num_updates,
        policy_steps_per_update,
        start_update=start_update,
        last_checkpoint=state["last_checkpoint"] if state else 0,
    )
    per_rank_batch_size = int(cfg.algo.per_rank_batch_size)

    envs = build_vector_env(cfg, 0, log_dir, "train")
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("Only continuous action space is supported for the SAC agent")
    mlp_keys = list(cfg.algo.mlp_keys.encoder)

    agent, player = build_agent(
        LocalFabric(fabric), cfg, observation_space, action_space, state["agent"] if state else None
    )

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in AGGREGATOR_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    buffer_size = cfg.buffer.size // num_envs if not cfg.dry_run else 1
    rb = ReplayBuffer(
        buffer_size,
        num_envs,
        obs_keys=("observations",),
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", "rank_0"),
        seed=cfg.seed,
    )
    if state:
        if cfg.buffer.checkpoint and "rb" in state:
            from sheeprl_tpu.utils.checkpoint import select_buffer

            rb = select_buffer(state["rb"], 0, 1)
        else:
            # without the buffer, refill before training resumes
            learning_starts += start_update

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])
    key = jax.random.PRNGKey(int(cfg.seed))
    if state and "rng_key" in state:
        key = jnp.asarray(state["rng_key"])
    # action keys live on the player's device so a host-pinned player
    # never blocks on a chip round trip per env step
    from sheeprl_tpu.parallel.fabric import put_tree as _put_tree

    from sheeprl_tpu.parallel.fabric import _ParamStreamer

    # flat-vector receive lane matching the trainer's actor pack
    actor_lane_player = _ParamStreamer(
        jax.device_get(player.params), player.device or jax.devices()[0]
    )
    player_key = _put_tree(jax.random.fold_in(key, 1), player.device)
    if state and "player_rng_key" in state:
        # continue the pre-resume action-sampling stream
        player_key = _put_tree(jnp.asarray(state["player_rng_key"]), player.device)

    policy_step = (start_update - 1) * num_envs
    last_log = state["last_log"] if state else 0
    obs, _ = envs.reset(seed=cfg.seed)
    step_data: Dict[str, np.ndarray] = {}
    cumulative_per_rank_gradient_steps = 0

    for update in range(start_update, num_updates + 1):
        policy_step += num_envs

        with timer("Time/env_interaction_time"):
            if update <= learning_starts:
                actions = envs.action_space.sample()
            else:
                player_key, action_key = jax.random.split(player_key)
                np_obs = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs)
                actions = player.get_actions(np_obs, action_key)
            next_obs, rewards, terminated, truncated, infos = envs.step(
                np.asarray(actions).reshape(envs.action_space.shape)
            )

        if cfg.metric.log_level > 0 and "final_info" in infos:
            ep = infos["final_info"].get("episode")
            if ep is not None:
                for i in np.nonzero(ep.get("_r", []))[0]:
                    aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                    aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                    print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

        real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items()}
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    for k, v in final_obs.items():
                        real_next_obs[k][idx] = v

        step_data["terminated"] = np.asarray(terminated, np.float32).reshape(1, num_envs, 1)
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape(1, num_envs, 1)
        step_data["actions"] = np.asarray(actions, np.float32).reshape(1, num_envs, -1)
        step_data["observations"] = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs)[np.newaxis]
        if not cfg.buffer.sample_next_obs:
            step_data["next_observations"] = prepare_obs(
                real_next_obs, mlp_keys=mlp_keys, num_envs=num_envs
            )[np.newaxis]
        step_data["rewards"] = np.asarray(rewards, np.float32).reshape(1, num_envs, 1)
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
        obs = next_obs

        # sample the trainers' batches from the player-owned buffer
        # (reference :303-330)
        data = None
        # NOTE (round-4 item): this path still ships per_rank_gradient_steps
        # in ONE [G, B, ...] block — the trainer's fused scan recompiles per
        # distinct G and the first post-warmup G repays the whole warmup debt
        # (see utils.gradient_step_chunks, applied to the coupled loops);
        # chunking here needs a protocol change (multiple data broadcasts
        # per update), so keep learning_starts small on remote chips.
        if update >= learning_starts:
            per_rank_gradient_steps = ratio(policy_step)
            if per_rank_gradient_steps > 0:
                sample = rb.sample(
                    batch_size=per_rank_batch_size * len(trainer_devs),
                    n_samples=per_rank_gradient_steps,
                    sample_next_obs=cfg.buffer.sample_next_obs,
                )
                data = {k: np.asarray(v, np.float32) for k, v in sample.items()}
                cumulative_per_rank_gradient_steps += per_rank_gradient_steps
        broadcast_object(data, src=0)
        payload = broadcast_object(None, src=1)
        if payload is not None:
            player.params = actor_lane_player.finish(payload["actor_flat"])
            if cfg.metric.log_level > 0:
                aggregator.update("Loss/value_loss", float(payload["metrics"][0]))
                aggregator.update("Loss/policy_loss", float(payload["metrics"][1]))
                aggregator.update("Loss/alpha_loss", float(payload["metrics"][2]))

        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or update == num_updates):
            logger.log_metrics(aggregator.compute(), policy_step)
            aggregator.reset()
            timer.reset()
            last_log = policy_step

        # skip scheduled checkpoints that landed on a no-train update — a
        # .ckpt with no model state would crash evaluation on load
        if update in ckpt_updates and payload is not None and payload.get("state") is not None:
            # payload["state"] carries {agent, qf_optimizer, actor_optimizer,
            # alpha_optimizer} — merged flat to match the coupled SAC format
            ckpt_state = {
                **payload["state"],
                "update": update,
                "batch_size": per_rank_batch_size * len(trainer_devs),
                "last_log": last_log,
                "last_checkpoint": policy_step,
                "ratio": ratio.state_dict(),
                "rng_key": jax.device_get(key),
                "player_rng_key": jax.device_get(player_key),
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_0.ckpt")
            fabric.call(
                "on_checkpoint_player",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )

    envs.close()
    if cfg.algo.run_test:
        test(player, fabric, cfg, log_dir)
    logger.finalize()


def _trainer(fabric, cfg, state=None):
    get_log_dir(cfg)  # join the player's log-dir broadcast
    num_envs = int(cfg.env.num_envs)
    trainer_devs = _trainer_devices()
    tfabric = SubMeshFabric(fabric, trainer_devs)
    policy_steps_per_update, num_updates, learning_starts = _counters(cfg, num_envs)
    start_update = state["update"] + 1 if state else 1
    ckpt_updates = _ckpt_schedule(
        cfg,
        num_updates,
        policy_steps_per_update,
        start_update=start_update,
        last_checkpoint=state["last_checkpoint"] if state else 0,
    )
    per_rank_batch_size = int(cfg.algo.per_rank_batch_size)

    observation_space, action_space = probe_spaces(cfg)
    agent, _player_handle = build_agent(
        tfabric, cfg, observation_space, action_space, state["agent"] if state else None
    )

    critic_tx = build_tx(cfg.algo.critic.optimizer)
    actor_tx = build_tx(cfg.algo.actor.optimizer)
    alpha_tx = build_tx(cfg.algo.alpha.optimizer)
    if state:
        critic_opt = tfabric.replicate(jax.tree.map(jnp.asarray, state["qf_optimizer"]))
        actor_opt = tfabric.replicate(jax.tree.map(jnp.asarray, state["actor_optimizer"]))
        alpha_opt = tfabric.replicate(jax.tree.map(jnp.asarray, state["alpha_optimizer"]))
    else:
        critic_opt = tfabric.replicate(critic_tx.init(jax.device_get(agent.critic_params)))
        actor_opt = tfabric.replicate(actor_tx.init(jax.device_get(agent.actor_params)))
        alpha_opt = tfabric.replicate(alpha_tx.init(jax.device_get(agent.log_alpha)))

    # the fused SAC update over the trainer-only mesh (reference trainer DDP
    # over optimization_pg, :352-542)
    train_fn = make_train_fn(tfabric, agent, actor_tx, critic_tx, alpha_tx, cfg)

    key = jax.random.PRNGKey(int(cfg.seed) + jax.process_index())
    if state:
        # the trainer key is not checkpointed; fold in the resume point so the
        # post-resume train_key stream does not replay the pre-checkpoint one
        key = jax.random.fold_in(key, start_update)
    grad_counter = jnp.zeros((), jnp.int32)
    my_dev_idx = [i for i, d in enumerate(trainer_devs) if d.process_index == jax.process_index()]

    from sheeprl_tpu.parallel.fabric import _ParamStreamer

    # flat-vector send lane for the per-update actor refresh
    actor_lane = _ParamStreamer(jax.device_get(agent.actor_params), trainer_devs[0])

    for update in range(start_update, num_updates + 1):
        data = broadcast_object(None, src=0)
        payload = None
        if data is not None:
            # this process's slice of the global batch: the contiguous blocks
            # of the devices it hosts
            cols = np.concatenate(
                [np.arange(i * per_rank_batch_size, (i + 1) * per_rank_batch_size) for i in my_dev_idx]
            )
            local = {k: v[:, cols] for k, v in data.items()}
            gdata = tfabric.make_global(local, (None, tfabric.data_axis))
            key, train_key = jax.random.split(key)
            (
                agent.actor_params,
                agent.critic_params,
                agent.target_critic_params,
                agent.log_alpha,
                actor_opt,
                critic_opt,
                alpha_opt,
                grad_counter,
                metrics,
            ) = train_fn(
                agent.actor_params,
                agent.critic_params,
                agent.target_critic_params,
                agent.log_alpha,
                actor_opt,
                critic_opt,
                alpha_opt,
                grad_counter,
                gdata,
                train_key,
            )
            if jax.process_index() == 1:
                payload = {
                    "actor_flat": np.asarray(actor_lane.begin(agent.actor_params)),
                    "metrics": np.asarray(jax.device_get(metrics)),
                    "state": None,
                }
                if update in ckpt_updates:
                    payload["state"] = {
                        "agent": {
                            "actor": jax.device_get(agent.actor_params),
                            "critics": jax.device_get(agent.critic_params),
                            "target_critics": jax.device_get(agent.target_critic_params),
                            "log_alpha": jax.device_get(agent.log_alpha),
                        },
                        "qf_optimizer": jax.device_get(critic_opt),
                        "actor_optimizer": jax.device_get(actor_opt),
                        "alpha_optimizer": jax.device_get(alpha_opt),
                    }
        broadcast_object(payload, src=1)
