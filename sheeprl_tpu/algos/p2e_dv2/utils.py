"""P2E DV2 helpers (reference: sheeprl/algos/p2e_dv2/utils.py)."""

from __future__ import annotations

from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401
from sheeprl_tpu.algos.p2e_common import (
    DREAMER_FINETUNING_KEYS,
    P2E_EXPLORATION_KEYS,
    make_log_models,
)

# finetuning logs the plain Dreamer-V2 metric set on top
AGGREGATOR_KEYS = set(P2E_EXPLORATION_KEYS | DREAMER_FINETUNING_KEYS)
MODELS_TO_REGISTER = {
    "world_model",
    "ensembles",
    "actor_exploration",
    "critic_exploration",
    "target_critic_exploration",
    "actor_task",
    "critic_task",
    "target_critic_task",
}

__all__ = ["AGGREGATOR_KEYS", "MODELS_TO_REGISTER", "prepare_obs", "test"]

log_models_from_checkpoint = make_log_models(MODELS_TO_REGISTER)
