"""P2E DV2 helpers (reference: sheeprl/algos/p2e_dv2/utils.py)."""

from __future__ import annotations

from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss_task",
    "Loss/policy_loss_task",
    "Loss/value_loss_exploration",
    "Loss/policy_loss_exploration",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "Loss/ensemble_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Params/exploration_amount",
    "Rewards/intrinsic",
    "Values_exploration/predicted_values",
    "Values_exploration/lambda_values",
    "Grads/world_model",
    "Grads/actor_task",
    "Grads/critic_task",
    "Grads/actor_exploration",
    "Grads/critic_exploration",
    "Grads/ensemble",
    # finetuning logs the plain Dreamer-V2 metric set
    "Loss/value_loss",
    "Loss/policy_loss",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {
    "world_model",
    "ensembles",
    "actor_exploration",
    "critic_exploration",
    "target_critic_exploration",
    "actor_task",
    "critic_task",
    "target_critic_task",
}

__all__ = ["AGGREGATOR_KEYS", "MODELS_TO_REGISTER", "prepare_obs", "test"]


def log_models_from_checkpoint(fabric, cfg, state, artifacts_dir):
    """Pickle this algorithm's registered sub-models from a checkpoint
    (reference per-algo log_models_from_checkpoint; shared body in
    utils/model_manager.py)."""
    from sheeprl_tpu.utils.model_manager import log_models_from_checkpoint as _log

    return _log(state, sorted(MODELS_TO_REGISTER), artifacts_dir)
