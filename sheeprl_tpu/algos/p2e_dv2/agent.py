"""Plan2Explore on Dreamer-V2 — agent builders (reference:
sheeprl/algos/p2e_dv2/agent.py:27-230).

The ensemble is ONE vmapped param tree predicting the next flattened
discrete posterior from (z, h, action) (reference agent.py:155-170). One
exploration critic WITH an EMA/hard-copy target (reference agent.py:120-150)
plus an exploration actor sharing the DV2 Actor module."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v2.agent import (
    ActorDV2,
    CriticDV2,
    PlayerDV2,
    WorldModelDV2,
    _dense,
    _MLPBlock,
    build_agent as dv2_build_agent,
)

Array = jax.Array


class EnsembleDV2(nn.Module):
    """One ensemble member: MLP from (z, h, action) to the flattened
    stochastic state (reference agent.py:155-170)."""

    output_dim: int
    mlp_layers: int = 4
    dense_units: int = 400
    act: str = "elu"
    use_layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = _MLPBlock(self.mlp_layers, self.dense_units, self.act, self.use_layer_norm, self.dtype)(
            x.astype(self.dtype)
        )
        return _dense(self.output_dim, jnp.float32)(x)


def ensemble_apply(ens: nn.Module, stacked_params: Any, x: Array) -> Array:
    return jax.vmap(lambda p: ens.apply(p, x))(stacked_params)


def init_ensembles(ens: nn.Module, n: int, key: Array, dummy_in: Array) -> Any:
    keys = jax.random.split(key, n)
    return jax.vmap(lambda k: ens.init(k, dummy_in))(keys)


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Any] = None,
    ensembles_state: Optional[Any] = None,
    actor_task_state: Optional[Any] = None,
    critic_task_state: Optional[Any] = None,
    target_critic_task_state: Optional[Any] = None,
    actor_exploration_state: Optional[Any] = None,
    critic_exploration_state: Optional[Any] = None,
    target_critic_exploration_state: Optional[Any] = None,
) -> Tuple[
    WorldModelDV2, Any, ActorDV2, Any, CriticDV2, Any, Any, Any, Any, Any, Any, Any, PlayerDV2
]:
    """Returns ``(wm, wm_params, actor, actor_task_params, critic,
    critic_task_params, target_critic_task_params, actor_exploration_params,
    critic_exploration_params, target_critic_exploration_params, ensemble,
    ensembles_params, player)``."""
    (
        wm,
        wm_params,
        actor,
        actor_task_params,
        critic,
        critic_task_params,
        target_critic_task_params,
        player,
    ) = dv2_build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        actor_task_state,
        critic_task_state,
        target_critic_task_state,
    )

    key = jax.random.PRNGKey(int(cfg["seed"]) + 1)
    k_actor, k_ens, k_crit = jax.random.split(key, 3)
    latent = jnp.zeros((1, wm.latent_state_size), jnp.float32)

    actor_exploration_params = (
        jax.tree.map(jnp.asarray, actor_exploration_state)
        if actor_exploration_state is not None
        else actor.init(k_actor, latent)
    )
    critic_exploration_params = (
        jax.tree.map(jnp.asarray, critic_exploration_state)
        if critic_exploration_state is not None
        else critic.init(k_crit, latent)
    )
    target_critic_exploration_params = (
        jax.tree.map(jnp.asarray, target_critic_exploration_state)
        if target_critic_exploration_state is not None
        else jax.tree.map(jnp.copy, critic_exploration_params)
    )
    actor_exploration_params = fabric.replicate(actor_exploration_params)
    critic_exploration_params = fabric.replicate(critic_exploration_params)
    target_critic_exploration_params = fabric.replicate(target_critic_exploration_params)

    ens_cfg = cfg["algo"]["ensembles"]
    ensemble = EnsembleDV2(
        output_dim=wm.stoch_state_size,
        mlp_layers=int(ens_cfg["mlp_layers"]),
        dense_units=int(ens_cfg["dense_units"]),
        act=str(ens_cfg.get("dense_act", "elu")),
        use_layer_norm=bool(ens_cfg.get("layer_norm", False)),
        dtype=fabric.precision.compute_dtype,
    )
    dummy_in = jnp.zeros((1, wm.latent_state_size + int(np.sum(actions_dim))), jnp.float32)
    if ensembles_state is not None:
        ensembles_params = jax.tree.map(jnp.asarray, ensembles_state)
    else:
        ensembles_params = init_ensembles(ensemble, int(ens_cfg["n"]), k_ens, dummy_in)
    ensembles_params = fabric.replicate(ensembles_params)

    if str(cfg["algo"]["player"].get("actor_type", "task")) == "exploration":
        player.actor_params = actor_exploration_params

    return (
        wm,
        wm_params,
        actor,
        actor_task_params,
        critic,
        critic_task_params,
        target_critic_task_params,
        actor_exploration_params,
        critic_exploration_params,
        target_critic_exploration_params,
        ensemble,
        ensembles_params,
        player,
    )
