"""Plan2Explore on Dreamer-V2 — exploration phase (reference:
sheeprl/algos/p2e_dv2/p2e_dv2_exploration.py:39-880) — TPU-native.

ONE jitted train step fuses: DV2 world model (KL balancing; reward/continue
heads on detached latents, :150-154), ensemble learning in posterior space as
a vmapped batched MLP (:192-216), exploration behaviour with the
ensemble-disagreement intrinsic reward and a TARGET exploration critic
(:218-330), and zero-shot task behaviour (:332-420)."""

from __future__ import annotations

import os
from typing import Any, Dict, Sequence

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.ops.optim import build_tx
from sheeprl_tpu.algos.dreamer_v2.agent import (
    WorldModelDV2,
    actor_logprob_entropy,
    rssm_scan,
    sample_actor_actions,
)
from sheeprl_tpu.algos.dreamer_v2.loss import reconstruction_loss
from sheeprl_tpu.algos.p2e_dv2.agent import build_agent, ensemble_apply
from sheeprl_tpu.algos.p2e_dv2.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.data.device_buffer import (
    DeviceReplayBuffer,
    adapt_restored_buffer,
    make_sequential_replay,
)
from sheeprl_tpu.data.prefetch import sampled_batches
from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.ops.distributions import Bernoulli, Independent, Normal
from sheeprl_tpu.ops.math import compute_lambda_values_bootstrap
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs

from sheeprl_tpu.parallel.shard_map import shard_map

METRIC_ORDER = (
    "Loss/world_model_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Loss/ensemble_loss",
    "Loss/policy_loss_exploration",
    "Loss/value_loss_exploration",
    "Loss/policy_loss_task",
    "Loss/value_loss_task",
    "Rewards/intrinsic",
    "Values_exploration/predicted_values",
    "Values_exploration/lambda_values",
    "Grads/world_model",
    "Grads/ensemble",
    "Grads/actor_exploration",
    "Grads/critic_exploration",
    "Grads/actor_task",
    "Grads/critic_task",
)


def make_train_fn(
    fabric,
    wm: WorldModelDV2,
    actor,
    critic,
    ensemble,
    world_tx,
    actor_task_tx,
    critic_task_tx,
    actor_expl_tx,
    critic_expl_tx,
    ensemble_tx,
    cfg: Dict[str, Any],
    is_continuous: bool,
    actions_dim: Sequence[int],
):
    algo = cfg.algo
    wmc = algo.world_model
    cnn_keys = tuple(algo.cnn_keys.encoder)
    mlp_keys = tuple(algo.mlp_keys.encoder)
    cnn_dec_keys = tuple(algo.cnn_keys.decoder)
    mlp_dec_keys = tuple(algo.mlp_keys.decoder)
    horizon = int(algo.horizon)
    gamma = float(algo.gamma)
    lmbda = float(algo.lmbda)
    ent_coef = float(algo.actor.ent_coef)
    kl_balancing_alpha = float(wmc.kl_balancing_alpha)
    kl_free_nats, kl_free_avg = float(wmc.kl_free_nats), bool(wmc.kl_free_avg)
    kl_regularizer = float(wmc.kl_regularizer)
    discount_scale = float(wmc.discount_scale_factor)
    use_continues = bool(wmc.use_continues)
    intrinsic_multiplier = float(algo.intrinsic_reward_multiplier)
    n_actions = int(np.sum(actions_dim))
    data_axis = fabric.data_axis
    multi_device = fabric.world_size > 1

    def pmean(x):
        return lax.pmean(x, data_axis) if multi_device else x

    def local_train(
        wm_params,
        actor_task_params,
        critic_task_params,
        target_critic_task_params,
        actor_expl_params,
        critic_expl_params,
        target_critic_expl_params,
        ens_params,
        world_opt,
        actor_task_opt,
        critic_task_opt,
        actor_expl_opt,
        critic_expl_opt,
        ensemble_opt,
        data,
        key,
    ):
        if multi_device:
            key = jax.random.fold_in(key, lax.axis_index(data_axis))
        k_scan, k_img_expl, k_img_task = jax.random.split(key, 3)
        sg = lax.stop_gradient

        T = data["rewards"].shape[0]
        B = data["rewards"].shape[1]
        is_first = data["is_first"].at[0].set(1.0)
        batch_obs = {k: data[k] for k in cnn_keys + mlp_keys}
        obs_targets = {k: data[k].astype(jnp.float32) / 255.0 - 0.5 for k in cnn_dec_keys}
        obs_targets.update({k: data[k].astype(jnp.float32) for k in mlp_dec_keys})

        # ---------------- 1. world model ---------------- #
        def world_loss_fn(p):
            embedded = wm.apply(p, batch_obs, method=WorldModelDV2.encode)
            hs, zs, post_logits, prior_logits = rssm_scan(
                wm, p, embedded, data["actions"], is_first, k_scan
            )
            latents = jnp.concatenate([zs, hs], axis=-1)
            recon = wm.apply(p, latents, method=WorldModelDV2.decode)
            po = {
                k: Independent(Normal(recon[k], jnp.ones_like(recon[k])), 3 if k in cnn_dec_keys else 1)
                for k in cnn_dec_keys + mlp_dec_keys
            }
            # reward/continue heads on detached latents in P2E (reference :150-154)
            pr = Independent(Normal(wm.apply(p, sg(latents), method=WorldModelDV2.reward_mean), 1.0), 1)
            if use_continues:
                pc = Independent(
                    Bernoulli(logits=wm.apply(p, sg(latents), method=WorldModelDV2.continue_logits)), 1
                )
                continue_targets = (1 - data["terminated"]) * gamma
            else:
                pc = continue_targets = None
            loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
                po,
                obs_targets,
                pr,
                data["rewards"],
                prior_logits,
                post_logits,
                kl_balancing_alpha,
                kl_free_nats,
                kl_free_avg,
                kl_regularizer,
                pc,
                continue_targets,
                discount_scale,
            )
            aux = (hs, zs, post_logits, prior_logits, kl, state_loss, reward_loss, observation_loss, continue_loss)
            return loss, aux

        (rec_loss, aux), wm_grads = jax.value_and_grad(world_loss_fn, has_aux=True)(wm_params)
        hs, zs, post_logits, prior_logits = aux[:4]
        kl, state_loss, reward_loss, observation_loss, continue_loss = aux[4:]
        wm_grads = pmean(wm_grads)
        wm_gnorm = optax.global_norm(wm_grads)
        wm_updates, world_opt = world_tx.update(wm_grads, world_opt, wm_params)
        wm_params = optax.apply_updates(wm_params, wm_updates)

        # ---------------- 2. ensemble learning (posterior space) ----------- #
        ens_in = jnp.concatenate([sg(zs), sg(hs), data["actions"]], axis=-1)
        ens_target = sg(zs)[1:]

        def ens_loss_fn(ep):
            outs = ensemble_apply(ensemble, ep, ens_in)[:, :-1]  # [N, T-1, B, S]
            logp = Independent(Normal(outs, jnp.ones_like(outs)), 1).log_prob(
                jnp.broadcast_to(ens_target[None], outs.shape)
            )
            return -logp.mean(axis=(1, 2)).sum()

        ens_loss, ens_grads = jax.value_and_grad(ens_loss_fn)(ens_params)
        ens_grads = pmean(ens_grads)
        ens_gnorm = optax.global_norm(ens_grads)
        ens_updates, ensemble_opt = ensemble_tx.update(ens_grads, ensemble_opt, ens_params)
        ens_params = optax.apply_updates(ens_params, ens_updates)

        start_z = sg(zs).reshape(T * B, -1)
        start_h = sg(hs).reshape(T * B, -1)
        true_continue = (1 - data["terminated"]).reshape(1, T * B, 1) * gamma

        def imagine(actor_params, key):
            """DV2 imagination (reference :219-244): H+1 latents including the
            replayed start; ``acts[0]`` zeros, ``acts[i>=1]`` sampled at
            ``lats[i-1]``."""
            lat0 = jnp.concatenate([start_z, start_h], axis=-1)

            def step(carry, _):
                z, h, lat, key = carry
                key, k_act, k_state = jax.random.split(key, 3)
                action = sample_actor_actions(actor, actor_params, sg(lat), k_act)
                z, h = wm.apply(wm_params, z, h, action, k_state, method=WorldModelDV2.imagination)
                new_lat = jnp.concatenate([z, h], axis=-1)
                return (z, h, new_lat, key), (new_lat, action)

            _, (lats, acts) = lax.scan(step, (start_z, start_h, lat0, key), None, length=horizon)
            lats = jnp.concatenate([lat0[None], lats], axis=0)
            acts = jnp.concatenate([jnp.zeros((1, T * B, n_actions), acts.dtype), acts], axis=0)
            return lats, acts

        def continues_of(lats, like):
            if use_continues:
                continues = jax.nn.sigmoid(
                    wm.apply(wm_params, lats, method=WorldModelDV2.continue_logits)
                )
                return jnp.concatenate([true_continue, continues[1:]], axis=0)
            return jnp.ones_like(like) * gamma

        def behaviour_loss(actor_params, key, target_critic_params, reward_fn):
            """Shared DV2 behaviour objective (reference :265-330 expl /
            :332-420 task): lambda targets from TARGET-critic values with
            bootstrap; reinforce for discrete, dynamics for continuous."""
            lats, acts = imagine(actor_params, key)
            target_values = critic.apply(target_critic_params, lats)
            reward, reward_aux = reward_fn(lats, acts)
            continues = continues_of(lats, reward)
            lambda_values = compute_lambda_values_bootstrap(
                reward[:-1], target_values[:-1], continues[:-1], bootstrap=target_values[-1:], lmbda=lmbda
            )
            discount = sg(
                jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-1]], axis=0), axis=0)
            )
            if is_continuous:
                objective = lambda_values[1:]
            else:
                advantage = sg(lambda_values[1:] - target_values[:-2])
                logp, _ = actor_logprob_entropy(actor, actor_params, sg(lats[:-2]), sg(acts[1:-1]))
                objective = logp[..., None] * advantage
            _, entropy = actor_logprob_entropy(actor, actor_params, sg(lats[:-2]), sg(acts[1:-1]))
            policy_loss = -jnp.mean(sg(discount[:-2]) * (objective + ent_coef * entropy[..., None]))
            return policy_loss, (lats, lambda_values, discount, reward_aux, target_values)

        # ---------------- 3. exploration behaviour ---------------- #
        def intrinsic_reward_fn(lats, acts):
            ens_preds = ensemble_apply(
                ensemble, ens_params, jnp.concatenate([sg(lats), sg(acts)], axis=-1)
            )
            reward = ens_preds.var(axis=0).mean(axis=-1, keepdims=True) * intrinsic_multiplier
            return reward, reward.mean()

        (policy_loss_expl, (expl_lats, expl_lambda, expl_discount, intrinsic_mean, expl_values)), expl_grads = (
            jax.value_and_grad(behaviour_loss, has_aux=True)(
                actor_expl_params, k_img_expl, target_critic_expl_params, intrinsic_reward_fn
            )
        )
        expl_grads = pmean(expl_grads)
        actor_expl_gnorm = optax.global_norm(expl_grads)
        upd, actor_expl_opt = actor_expl_tx.update(expl_grads, actor_expl_opt, actor_expl_params)
        actor_expl_params = optax.apply_updates(actor_expl_params, upd)

        expl_traj_in = sg(expl_lats[:-1])

        def critic_expl_loss_fn(p):
            qv = Independent(Normal(critic.apply(p, expl_traj_in), 1.0), 1)
            return -jnp.mean(sg(expl_discount[:-1])[..., 0] * qv.log_prob(sg(expl_lambda)))

        value_loss_expl, cg = jax.value_and_grad(critic_expl_loss_fn)(critic_expl_params)
        cg = pmean(cg)
        critic_expl_gnorm = optax.global_norm(cg)
        upd, critic_expl_opt = critic_expl_tx.update(cg, critic_expl_opt, critic_expl_params)
        critic_expl_params = optax.apply_updates(critic_expl_params, upd)

        # ---------------- 4. task behaviour (zero-shot) ---------------- #
        def task_reward_fn(lats, acts):
            reward = wm.apply(wm_params, lats, method=WorldModelDV2.reward_mean)
            return reward, jnp.zeros(())

        (policy_loss_task, (task_lats, task_lambda, task_discount, _, _)), task_grads = jax.value_and_grad(
            behaviour_loss, has_aux=True
        )(actor_task_params, k_img_task, target_critic_task_params, task_reward_fn)
        task_grads = pmean(task_grads)
        actor_task_gnorm = optax.global_norm(task_grads)
        upd, actor_task_opt = actor_task_tx.update(task_grads, actor_task_opt, actor_task_params)
        actor_task_params = optax.apply_updates(actor_task_params, upd)

        task_traj_in = sg(task_lats[:-1])

        def critic_task_loss_fn(p):
            qv = Independent(Normal(critic.apply(p, task_traj_in), 1.0), 1)
            return -jnp.mean(sg(task_discount[:-1])[..., 0] * qv.log_prob(sg(task_lambda)))

        value_loss_task, cg = jax.value_and_grad(critic_task_loss_fn)(critic_task_params)
        cg = pmean(cg)
        critic_task_gnorm = optax.global_norm(cg)
        upd, critic_task_opt = critic_task_tx.update(cg, critic_task_opt, critic_task_params)
        critic_task_params = optax.apply_updates(critic_task_params, upd)

        from sheeprl_tpu.ops.distributions import OneHotCategorical

        post_ent = Independent(OneHotCategorical(logits=sg(post_logits)), 1).entropy().mean()
        prior_ent = Independent(OneHotCategorical(logits=sg(prior_logits)), 1).entropy().mean()
        metrics = pmean(
            jnp.stack(
                [
                    rec_loss,
                    observation_loss,
                    reward_loss,
                    state_loss,
                    continue_loss,
                    kl,
                    post_ent,
                    prior_ent,
                    ens_loss,
                    policy_loss_expl,
                    value_loss_expl,
                    policy_loss_task,
                    value_loss_task,
                    intrinsic_mean,
                    sg(expl_values).mean(),
                    sg(expl_lambda).mean(),
                    wm_gnorm,
                    ens_gnorm,
                    actor_expl_gnorm,
                    critic_expl_gnorm,
                    actor_task_gnorm,
                    critic_task_gnorm,
                ]
            )
        )
        return (
            wm_params,
            actor_task_params,
            critic_task_params,
            actor_expl_params,
            critic_expl_params,
            ens_params,
            world_opt,
            actor_task_opt,
            critic_task_opt,
            actor_expl_opt,
            critic_expl_opt,
            ensemble_opt,
            metrics,
        )

    if multi_device:
        train_fn = shard_map(
            local_train,
            mesh=fabric.mesh,
            in_specs=(P(),) * 14 + (P(None, data_axis), P()),
            out_specs=(P(),) * 13,
        )
    else:
        train_fn = local_train
    # donate only optimizer/aux state: param buffers stay un-donated because
    # concurrent readers (async param streaming to the host player, the ema /
    # hard-copy target refresh) may still be in flight when the next train
    # dispatch would otherwise alias over them (observed on the remote chip
    # as spurious INVALID_ARGUMENT errors surfacing at unrelated fetches)
    return jax.jit(train_fn, donate_argnums=(8, 9, 10, 11, 12, 13))


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)

    cfg.env.screen_size = 64
    cfg.env.frame_stack = 1
    cfg.algo.player.actor_type = "exploration"

    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")

    rank = fabric.process_index
    num_envs = int(cfg.env.num_envs)
    world_size = fabric.data_parallel_size  # batch-split width: the data axis (= device count on a 1-D mesh)
    num_processes = fabric.num_processes

    envs = build_vector_env(cfg, rank, log_dir if rank == 0 else None, "train", restart_on_exception=True)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape if is_continuous else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)

    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    if (
        len(set(cnn_keys).intersection(cfg.algo.cnn_keys.decoder)) == 0
        and len(set(mlp_keys).intersection(cfg.algo.mlp_keys.decoder)) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    if set(cfg.algo.cnn_keys.decoder) - set(cnn_keys):
        raise RuntimeError("The CNN keys of the decoder must be contained in the encoder ones.")
    if set(cfg.algo.mlp_keys.decoder) - set(mlp_keys):
        raise RuntimeError("The MLP keys of the decoder must be contained in the encoder ones.")
    obs_keys = cnn_keys + mlp_keys

    (
        wm,
        wm_params,
        actor,
        actor_task_params,
        critic,
        critic_task_params,
        target_critic_task_params,
        actor_expl_params,
        critic_expl_params,
        target_critic_expl_params,
        ensemble,
        ensembles_params,
        player,
    ) = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"] if cfg.checkpoint.resume_from else None,
        state["ensembles"] if cfg.checkpoint.resume_from else None,
        state["actor_task"] if cfg.checkpoint.resume_from else None,
        state["critic_task"] if cfg.checkpoint.resume_from else None,
        state["target_critic_task"] if cfg.checkpoint.resume_from else None,
        state["actor_exploration"] if cfg.checkpoint.resume_from else None,
        state["critic_exploration"] if cfg.checkpoint.resume_from else None,
        state["target_critic_exploration"] if cfg.checkpoint.resume_from else None,
    )

    world_tx = build_tx(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_task_tx = build_tx(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_task_tx = build_tx(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    actor_expl_tx = build_tx(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_expl_tx = build_tx(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    ensemble_tx = build_tx(cfg.algo.ensembles.optimizer, cfg.algo.ensembles.clip_gradients)

    world_opt = fabric.replicate(world_tx.init(jax.device_get(wm_params)))
    actor_task_opt = fabric.replicate(actor_task_tx.init(jax.device_get(actor_task_params)))
    critic_task_opt = fabric.replicate(critic_task_tx.init(jax.device_get(critic_task_params)))
    actor_expl_opt = fabric.replicate(actor_expl_tx.init(jax.device_get(actor_expl_params)))
    critic_expl_opt = fabric.replicate(critic_expl_tx.init(jax.device_get(critic_expl_params)))
    ensemble_opt = fabric.replicate(ensemble_tx.init(jax.device_get(ensembles_params)))
    if cfg.checkpoint.resume_from:
        world_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["world_optimizer"]))
        actor_task_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["actor_task_optimizer"]))
        critic_task_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["critic_task_optimizer"]))
        actor_expl_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["actor_exploration_optimizer"]))
        critic_expl_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["critic_exploration_optimizer"]))
        ensemble_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["ensemble_optimizer"]))

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in (set(METRIC_ORDER) | {"Rewards/rew_avg", "Game/ep_len_avg", "Params/exploration_amount"}) - set(
        aggregator.metrics
    ):
        aggregator.add(k, "mean")

    buffer_size = cfg.buffer.size // int(num_envs * num_processes) if not cfg.dry_run else 4
    rb = make_sequential_replay(
        cfg,
        fabric,
        observation_space,
        actions_dim,
        buffer_size,
        num_envs,
        obs_keys,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        seed=cfg.seed,
    )
    if cfg.checkpoint.resume_from and cfg.buffer.checkpoint:
        from sheeprl_tpu.utils.checkpoint import select_buffer

        rb = adapt_restored_buffer(
            select_buffer(state["rb"], rank, num_processes),
            isinstance(rb, DeviceReplayBuffer),
            seed=cfg.seed,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        )

    # hard target copies (reference :823-833)
    @jax.jit
    def hard_copy(cp):
        return jax.tree.map(jnp.copy, cp)

    train_fn = make_train_fn(
        fabric,
        wm,
        actor,
        critic,
        ensemble,
        world_tx,
        actor_task_tx,
        critic_task_tx,
        actor_expl_tx,
        critic_expl_tx,
        ensemble_tx,
        cfg,
        is_continuous,
        actions_dim,
    )

    train_step = 0
    last_train = 0
    start_step = state["update"] + 1 if cfg.checkpoint.resume_from else 1
    policy_step = state["update"] * num_envs * num_processes if cfg.checkpoint.resume_from else 0
    last_log = state["last_log"] if cfg.checkpoint.resume_from else 0
    last_checkpoint = state["last_checkpoint"] if cfg.checkpoint.resume_from else 0
    policy_steps_per_update = int(num_envs * num_processes)
    num_updates = int(cfg.algo.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    per_rank_batch_size = int(cfg.algo.per_rank_batch_size)
    sequence_length = int(cfg.algo.per_rank_sequence_length)
    if cfg.checkpoint.resume_from:
        from sheeprl_tpu.utils.checkpoint import elastic_per_rank_batch_size

        per_rank_batch_size = elastic_per_rank_batch_size(state["batch_size"], world_size)
        if not cfg.buffer.checkpoint:
            learning_starts += start_step

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if cfg.checkpoint.resume_from:
        ratio.load_state_dict(state["ratio"])

    key = jax.random.PRNGKey(int(cfg.seed))
    if cfg.checkpoint.resume_from and "rng_key" in state:
        key = jnp.asarray(state["rng_key"])
    # action keys live on the player's device so a host-pinned player
    # never blocks on a chip round trip per env step
    from sheeprl_tpu.parallel.fabric import put_tree as _put_tree

    player_key = _put_tree(jax.random.fold_in(key, 1), player.device)
    if cfg.checkpoint.resume_from and "player_rng_key" in state:
        # continue the pre-resume action-sampling stream
        player_key = _put_tree(jnp.asarray(state["player_rng_key"]), player.device)

    step_data: Dict[str, np.ndarray] = {}
    obs, _ = envs.reset(seed=cfg.seed)
    prepared = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=num_envs)
    for k in obs_keys:
        step_data[k] = prepared[k][np.newaxis]
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["actions"] = np.zeros((1, num_envs, int(np.sum(actions_dim))), np.float32)
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    rb.add(step_data, validate_args=cfg.buffer.validate_args)
    player.init_states()

    cumulative_per_rank_gradient_steps = 0
    for update in range(start_step, num_updates + 1):
        policy_step += num_envs * num_processes

        with timer("Time/env_interaction_time"):
            if update <= learning_starts and cfg.checkpoint.resume_from is None:
                real_actions = actions = np.array(envs.action_space.sample())
                if not is_continuous:
                    actions = np.concatenate(
                        [
                            np.eye(act_dim, dtype=np.float32)[act.reshape(-1)]
                            for act, act_dim in zip(actions.reshape(len(actions_dim), -1), actions_dim)
                        ],
                        axis=-1,
                    )
            else:
                player_key, action_key = jax.random.split(player_key)
                prepared = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=num_envs)
                actions = player.get_actions(
                    prepared, action_key, expl_step=policy_step, with_exploration=True
                )
                if is_continuous:
                    real_actions = actions
                else:
                    splits = np.cumsum(actions_dim)[:-1]
                    real_actions = np.stack(
                        [p.argmax(-1) for p in np.split(actions, splits, axis=-1)], axis=-1
                    )
                    if real_actions.shape[-1] == 1 and not is_multidiscrete:
                        real_actions = real_actions[..., 0]

            step_data["is_first"] = np.logical_or(
                step_data["terminated"], step_data["truncated"]
            ).astype(np.float32)
            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        if "restart_on_exception" in infos:
            for i, roe in enumerate(np.asarray(infos["restart_on_exception"]).reshape(-1)):
                if roe and not dones[i]:
                    step_data["is_first"][0, i] = 1.0

        if cfg.metric.log_level > 0 and "final_info" in infos:
            ep = infos["final_info"].get("episode")
            if ep is not None:
                for i in np.nonzero(ep.get("_r", []))[0]:
                    aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                    aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                    print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

        real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items()}
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    for k, v in final_obs.items():
                        real_next_obs[k][idx] = v

        prepared_next = prepare_obs(real_next_obs, cnn_keys=cnn_keys, num_envs=num_envs)
        for k in obs_keys:
            step_data[k] = prepared_next[k][np.newaxis]
        obs = next_obs

        step_data["terminated"] = np.asarray(terminated, np.float32).reshape(1, num_envs, 1)
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape(1, num_envs, 1)
        step_data["actions"] = np.asarray(actions, np.float32).reshape(1, num_envs, -1)
        step_data["rewards"] = clip_rewards_fn(np.asarray(rewards, np.float32).reshape(1, num_envs, 1))
        rb.add(step_data, validate_args=cfg.buffer.validate_args)

        dones_idxes = dones.nonzero()[0].tolist()
        if dones_idxes:
            prepared_reset = prepare_obs(
                {k: np.asarray(next_obs[k])[dones_idxes] for k in obs_keys},
                cnn_keys=cnn_keys,
                num_envs=len(dones_idxes),
            )
            reset_data = {k: prepared_reset[k][np.newaxis] for k in obs_keys}
            reset_data["terminated"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            reset_data["truncated"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            reset_data["actions"] = np.zeros((1, len(dones_idxes), int(np.sum(actions_dim))), np.float32)
            reset_data["rewards"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            reset_data["is_first"] = np.ones_like(reset_data["terminated"])
            rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
            step_data["terminated"][0, dones_idxes] = 0.0
            step_data["truncated"][0, dones_idxes] = 0.0
            player.init_states(dones_idxes)

        # ---------------- training ---------------- #
        if update >= learning_starts:
            per_rank_gradient_steps = ratio(policy_step / num_processes)
            if per_rank_gradient_steps > 0:
                # batch i+1's host->HBM transfer overlaps gradient step i
                batches = sampled_batches(
                    rb,
                    per_rank_batch_size * fabric.local_data_parallel_size,
                    sequence_length,
                    per_rank_gradient_steps,
                    cnn_keys,
                    fabric,
                    prefetch=int(cfg.buffer.get("prefetch", 0) or 0),
                )
                with timer("Time/train_time"):
                    for i, batch in enumerate(batches):
                        if (
                            cumulative_per_rank_gradient_steps
                            % cfg.algo.critic.per_rank_target_network_update_freq
                            == 0
                        ):
                            target_critic_task_params = hard_copy(critic_task_params)
                            target_critic_expl_params = hard_copy(critic_expl_params)
                        key, train_key = jax.random.split(key)
                        (
                            wm_params,
                            actor_task_params,
                            critic_task_params,
                            actor_expl_params,
                            critic_expl_params,
                            ensembles_params,
                            world_opt,
                            actor_task_opt,
                            critic_task_opt,
                            actor_expl_opt,
                            critic_expl_opt,
                            ensemble_opt,
                            metrics,
                        ) = train_fn(
                            wm_params,
                            actor_task_params,
                            critic_task_params,
                            target_critic_task_params,
                            actor_expl_params,
                            critic_expl_params,
                            target_critic_expl_params,
                            ensembles_params,
                            world_opt,
                            actor_task_opt,
                            critic_task_opt,
                            actor_expl_opt,
                            critic_expl_opt,
                            ensemble_opt,
                            batch,
                            train_key,
                        )
                        cumulative_per_rank_gradient_steps += 1
                    metrics = np.asarray(jax.device_get(metrics))
                    train_step += num_processes
                # non-blocking in host-player mode: the trees stream through the
                # async pipe and flip a block or two later (fabric.stream_attr)
                player.stream_attr("wm_params", wm_params)
                player.stream_attr("actor_params", actor_expl_params)
                if cfg.metric.log_level > 0:
                    for name, value in zip(METRIC_ORDER, metrics):
                        aggregator.update(name, float(value))
                    aggregator.update(
                        "Params/exploration_amount", float(actor.get_expl_amount(policy_step))
                    )

        # ---------------- logging ---------------- #
        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or update == num_updates):
            metrics_dict = aggregator.compute()
            logger.log_metrics(metrics_dict, policy_step)
            aggregator.reset()
            if policy_step > 0:
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * num_processes / policy_step},
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time"):
                    logger.log_metrics(
                        {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                        policy_step,
                    )
                if timer_metrics.get("Time/env_interaction_time"):
                    logger.log_metrics(
                        {
                            "Time/sps_env_interaction": (
                                (policy_step - last_log) / num_processes * cfg.env.action_repeat
                            )
                            / timer_metrics["Time/env_interaction_time"]
                        },
                        policy_step,
                    )
                timer.reset()
            last_log = policy_step
            last_train = train_step

        # ---------------- checkpoint ---------------- #
        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            update == num_updates and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "world_model": jax.device_get(wm_params),
                "actor_task": jax.device_get(actor_task_params),
                "critic_task": jax.device_get(critic_task_params),
                "target_critic_task": jax.device_get(target_critic_task_params),
                "ensembles": jax.device_get(ensembles_params),
                "actor_exploration": jax.device_get(actor_expl_params),
                "critic_exploration": jax.device_get(critic_expl_params),
                "target_critic_exploration": jax.device_get(target_critic_expl_params),
                "world_optimizer": jax.device_get(world_opt),
                "actor_task_optimizer": jax.device_get(actor_task_opt),
                "critic_task_optimizer": jax.device_get(critic_task_opt),
                "actor_exploration_optimizer": jax.device_get(actor_expl_opt),
                "critic_exploration_optimizer": jax.device_get(critic_expl_opt),
                "ensemble_optimizer": jax.device_get(ensemble_opt),
                "ratio": ratio.state_dict(),
                "update": update,
                "batch_size": per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "rng_key": jax.device_get(key),
                "player_rng_key": jax.device_get(player_key),
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )

    # land any in-flight async param stream so the final evaluation and
    # model registration use the last update's weights
    player.flush_stream_attrs()
    envs.close()
    if fabric.is_global_zero and cfg.algo.run_test:
        player.actor_params = actor_task_params
        test(player, fabric, cfg, log_dir, "zero-shot", greedy=False)
    logger.finalize()
