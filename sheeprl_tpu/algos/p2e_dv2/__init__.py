from sheeprl_tpu.algos.p2e_dv2 import p2e_dv2_exploration, p2e_dv2_finetuning, evaluate  # noqa: F401
