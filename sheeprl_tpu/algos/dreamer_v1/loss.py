"""Dreamer-V1 world-model loss (reference: sheeprl/algos/dreamer_v1/loss.py:42-95).

KL(Normal(post) || Normal(prior)) with a free-nats floor — no KL balancing
(that arrives in V2). The continue term uses the standard negative log
likelihood (the reference adds ``+log_prob`` at loss.py:92-94, which only
matters when ``use_continues=True`` — off by default in its configs)."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.ops.distributions import Independent, Normal, kl_divergence

Array = jax.Array


def reconstruction_loss(
    qo: Dict[str, object],
    observations: Dict[str, Array],
    qr: object,
    rewards: Array,
    post_mean: Array,
    post_std: Array,
    prior_mean: Array,
    prior_std: Array,
    kl_free_nats: float = 3.0,
    kl_regularizer: float = 1.0,
    qc: Optional[object] = None,
    continue_targets: Optional[Array] = None,
    continue_scale_factor: float = 10.0,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Eq. 10 of the Dreamer paper: observation + reward (+ continue) NLL
    plus ``max(KL(post || prior), free_nats)``.

    Returns ``(loss, kl, state_loss, reward_loss, observation_loss,
    continue_loss)`` — same order as the reference."""
    observation_loss = -sum(qo[k].log_prob(observations[k]).mean() for k in qo.keys())
    reward_loss = -qr.log_prob(rewards).mean()
    kl = kl_divergence(
        Independent(Normal(post_mean, post_std), 1),
        Independent(Normal(prior_mean, prior_std), 1),
    ).mean()
    state_loss = jnp.maximum(kl, jnp.asarray(kl_free_nats, kl.dtype))
    if qc is not None and continue_targets is not None:
        continue_loss = continue_scale_factor * -qc.log_prob(continue_targets).mean()
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    total = kl_regularizer * state_loss + observation_loss + reward_loss + continue_loss
    return total, kl, state_loss, reward_loss, observation_loss, continue_loss
