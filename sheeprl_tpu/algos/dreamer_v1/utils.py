"""Dreamer-V1 helpers (reference: sheeprl/algos/dreamer_v1/utils.py).

``compute_lambda_values`` (the V1 recurrence, H-1 targets from an H-step
rollout) lives in ``sheeprl_tpu.ops.math.compute_lambda_values_dv1``; the
Gaussian stochastic-state helper is ``WorldModelDV1._stoch``.
"""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test as _dv3_test

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/post_entropy",
    "State/prior_entropy",
    "State/kl",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
    "Params/exploration_amount",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic"}

__all__ = ["AGGREGATOR_KEYS", "MODELS_TO_REGISTER", "prepare_obs", "test"]


def test(player: Any, fabric: Any, cfg: Dict[str, Any], log_dir: str, test_name: str = "", greedy: bool = True) -> None:
    """Frozen-policy evaluation episode (reference dv2/utils.py:122-168 is
    shared by V1 too) — the player API matches Dreamer-V3's."""
    _dv3_test(player, fabric, cfg, log_dir, test_name=test_name, greedy=greedy)


def log_models_from_checkpoint(fabric, cfg, state, artifacts_dir):
    """Pickle this algorithm's registered sub-models from a checkpoint
    (reference per-algo log_models_from_checkpoint; shared body in
    utils/model_manager.py)."""
    from sheeprl_tpu.utils.model_manager import log_models_from_checkpoint as _log

    return _log(state, sorted(MODELS_TO_REGISTER), artifacts_dir)
