"""Dreamer-V1 agent (reference: sheeprl/algos/dreamer_v1/agent.py:32-547).

flax re-design sharing this repo's DV2 layout (``algos/dreamer_v2/agent.py``).
What makes V1 different from V2, encoded here:

- the stochastic latent is a **continuous diagonal Gaussian** (no discrete
  codes): the representation/transition heads emit ``2 * stochastic_size``
  values split into (mean, std) with ``std = softplus(std) + min_std``
  (reference dreamer_v1/utils.py:81-110),
- the recurrent model is Dense+act into a **plain GRU** (reference
  agent.py:32-62 — no LayerNorm variant),
- no ``is_first`` gating in the RSSM (reference RSSM.dynamic,
  agent.py:99-137, predates that machinery),
- the actor/critic are the DV2 modules verbatim (the reference itself
  aliases ``Actor = DV2Actor``, agent.py:28-29).

All sequence loops are ``lax.scan``; images NHWC uint8 normalized in-graph.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v2.agent import (
    ActorDV2,
    CNNDecoderDV2,
    CNNEncoderDV2,
    CriticDV2,
    MLPDecoderDV2,
    MLPEncoderDV2,
    PlayerDV2,
    _dense,
    _MLPBlock,
    actor_dists,
    actor_logprob_entropy,
    add_exploration_noise,
    sample_actor_actions,
)

Array = jax.Array

# V1 reuses the V2 actor/critic/player wholesale (reference agent.py:28-29).
ActorDV1 = ActorDV2
CriticDV1 = CriticDV2
PlayerDV1 = PlayerDV2

__all__ = [
    "ActorDV1",
    "CriticDV1",
    "PlayerDV1",
    "WorldModelDV1",
    "actor_dists",
    "actor_logprob_entropy",
    "add_exploration_noise",
    "build_agent",
    "rssm_scan_dv1",
    "sample_actor_actions",
]


class RecurrentModelDV1(nn.Module):
    """Dense+act projection into a standard GRU (reference
    RecurrentModel, agent.py:32-62; projection width equals the recurrent
    state size there)."""

    recurrent_state_size: int
    act: str = "elu"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array, h: Array) -> Array:
        feat = _MLPBlock(1, self.recurrent_state_size, self.act, False, self.dtype)(x)
        new_h, _ = nn.GRUCell(self.recurrent_state_size, dtype=self.dtype, param_dtype=jnp.float32)(
            h.astype(self.dtype), feat
        )
        return new_h.astype(jnp.float32)


class WorldModelDV1(nn.Module):
    """Encoder + Gaussian RSSM + decoders + reward (+ optional continue) in
    one param tree (reference WorldModel container agent.py:199-217 and RSSM
    agent.py:65-196). Methods are ``apply(..., method=...)`` entry points."""

    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    cnn_output_channels: Tuple[int, ...]
    mlp_output_dims: Tuple[int, ...]
    image_size: Tuple[int, int]
    actions_dim: Tuple[int, ...]
    stochastic_size: int = 30
    min_std: float = 0.1
    recurrent_state_size: int = 200
    encoder_cnn_multiplier: int = 32
    encoder_mlp_layers: int = 4
    encoder_dense_units: int = 400
    decoder_cnn_multiplier: int = 32
    decoder_mlp_layers: int = 4
    decoder_dense_units: int = 400
    representation_hidden_size: int = 200
    transition_hidden_size: int = 200
    reward_layers: int = 4
    reward_dense_units: int = 400
    use_continues: bool = False
    continue_layers: int = 4
    continue_dense_units: int = 400
    dense_act: str = "elu"
    cnn_act: str = "relu"
    dtype: Any = jnp.float32

    @property
    def stoch_state_size(self) -> int:
        return self.stochastic_size

    @property
    def latent_state_size(self) -> int:
        return self.stochastic_size + self.recurrent_state_size

    @property
    def cnn_encoder_output_dim(self) -> int:
        hw = self.image_size[0]
        for _ in range(4):
            hw = (hw - 4) // 2 + 1
        return hw * hw * 8 * self.encoder_cnn_multiplier

    def setup(self) -> None:
        if self.cnn_keys:
            self.cnn_encoder = CNNEncoderDV2(
                self.cnn_keys, self.encoder_cnn_multiplier, self.cnn_act, False, self.dtype
            )
            self.cnn_decoder = CNNDecoderDV2(
                self.cnn_keys,
                self.cnn_output_channels,
                self.decoder_cnn_multiplier,
                self.cnn_encoder_output_dim,
                self.image_size,
                self.cnn_act,
                False,
                self.dtype,
            )
        if self.mlp_keys:
            self.mlp_encoder = MLPEncoderDV2(
                self.mlp_keys, self.encoder_mlp_layers, self.encoder_dense_units, self.dense_act, False, self.dtype
            )
            self.mlp_decoder = MLPDecoderDV2(
                self.mlp_keys,
                self.mlp_output_dims,
                self.decoder_mlp_layers,
                self.decoder_dense_units,
                self.dense_act,
                False,
                self.dtype,
            )
        self.recurrent_model = RecurrentModelDV1(self.recurrent_state_size, self.dense_act, self.dtype)
        self.representation_model = nn.Sequential(
            [
                _MLPBlock(1, self.representation_hidden_size, self.dense_act, False, self.dtype),
                _dense(2 * self.stochastic_size, jnp.float32),
            ]
        )
        self.transition_model = nn.Sequential(
            [
                _MLPBlock(1, self.transition_hidden_size, self.dense_act, False, self.dtype),
                _dense(2 * self.stochastic_size, jnp.float32),
            ]
        )
        self.reward_model = nn.Sequential(
            [
                _MLPBlock(self.reward_layers, self.reward_dense_units, self.dense_act, False, self.dtype),
                _dense(1, jnp.float32),
            ]
        )
        if self.use_continues:
            self.continue_model = nn.Sequential(
                [
                    _MLPBlock(self.continue_layers, self.continue_dense_units, self.dense_act, False, self.dtype),
                    _dense(1, jnp.float32),
                ]
            )

    # ------------------------------------------------------------------ #
    # entry points
    # ------------------------------------------------------------------ #
    def encode(self, obs: Dict[str, Array]) -> Array:
        feats = []
        if self.cnn_keys:
            feats.append(self.cnn_encoder(obs))
        if self.mlp_keys:
            feats.append(self.mlp_encoder(obs))
        out = feats[0] if len(feats) == 1 else jnp.concatenate(feats, axis=-1)
        return out.astype(jnp.float32)

    def decode(self, latent: Array) -> Dict[str, Array]:
        out: Dict[str, Array] = {}
        if self.cnn_keys:
            out.update(self.cnn_decoder(latent.astype(self.dtype)))
        if self.mlp_keys:
            out.update(self.mlp_decoder(latent.astype(self.dtype)))
        return out

    def reward_mean(self, latent: Array) -> Array:
        return self.reward_model(latent.astype(self.dtype))

    def continue_logits(self, latent: Array) -> Array:
        return self.continue_model(latent.astype(self.dtype))

    def _stoch(self, out: Array, key: Array) -> Tuple[Array, Array, Array]:
        """(mean, std, rsample) of the Gaussian state (reference
        compute_stochastic_state, dreamer_v1/utils.py:81-110)."""
        mean, std = jnp.split(out, 2, axis=-1)
        std = jax.nn.softplus(std) + self.min_std
        z = mean + std * jax.random.normal(key, mean.shape, mean.dtype)
        return mean, std, z

    def dynamic(
        self, z: Array, h: Array, action: Array, embedded: Array, key: Array
    ) -> Tuple[Array, Array, Array, Array, Array, Array]:
        """One posterior step (reference RSSM.dynamic, agent.py:99-137):
        returns ``(h', posterior, post_mean, post_std, prior_mean,
        prior_std)``."""
        k_prior, k_post = jax.random.split(key)
        h = self.recurrent_model(jnp.concatenate([z, action], axis=-1).astype(self.dtype), h)
        prior_mean, prior_std, _ = self._stoch(self.transition_model(h.astype(self.dtype)), k_prior)
        post_in = jnp.concatenate([h, embedded], axis=-1)
        post_mean, post_std, z = self._stoch(self.representation_model(post_in.astype(self.dtype)), k_post)
        return h, z, post_mean, post_std, prior_mean, prior_std

    def imagination(self, z: Array, h: Array, action: Array, key: Array) -> Tuple[Array, Array]:
        """One prior step in latent space (reference RSSM.imagination,
        agent.py:174-196)."""
        h = self.recurrent_model(jnp.concatenate([z, action], axis=-1).astype(self.dtype), h)
        _, _, z = self._stoch(self.transition_model(h.astype(self.dtype)), key)
        return z, h

    def observe_step(self, z, h, action, obs, key):
        """Policy-time posterior update (reference PlayerDV1.get_actions,
        agent.py:303-330)."""
        embedded = self.encode(obs)
        h = self.recurrent_model(jnp.concatenate([z, action], axis=-1).astype(self.dtype), h)
        post_in = jnp.concatenate([h, embedded], axis=-1)
        _, _, z = self._stoch(self.representation_model(post_in.astype(self.dtype)), key)
        return z, h


def rssm_scan_dv1(
    wm: WorldModelDV1,
    params: Any,
    embedded: Array,  # [T, B, E]
    actions: Array,  # [T, B, A] (already shifted)
    key: Array,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """The DV1 RSSM sequence as one ``lax.scan`` (replaces the reference's
    Python loop, dreamer_v1.py:144-156). Returns time-major
    ``(hs, posteriors, post_means, post_stds, prior_means, prior_stds)``."""
    B = embedded.shape[1]
    h = jnp.zeros((B, wm.recurrent_state_size), jnp.float32)
    z = jnp.zeros((B, wm.stochastic_size), jnp.float32)

    def step(carry, xs):
        h, z, key = carry
        emb_t, act_t = xs
        key, sub = jax.random.split(key)
        h, z, post_mean, post_std, prior_mean, prior_std = wm.apply(
            params, z, h, act_t, emb_t, sub, method=WorldModelDV1.dynamic
        )
        return (h, z, key), (h, z, post_mean, post_std, prior_mean, prior_std)

    _, outs = jax.lax.scan(step, (h, z, key), (embedded, actions))
    return outs


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Any] = None,
    actor_state: Optional[Any] = None,
    critic_state: Optional[Any] = None,
) -> Tuple[WorldModelDV1, Any, ActorDV2, Any, CriticDV2, Any, PlayerDV2]:
    """Construct modules + init/replicate params (reference build_agent,
    agent.py:333-547). Returns ``(wm, wm_params, actor, actor_params,
    critic, critic_params, player)`` — no target critic in V1."""
    wm_cfg = cfg["algo"]["world_model"]
    actor_cfg = cfg["algo"]["actor"]
    cnn_keys = tuple(cfg["algo"]["cnn_keys"]["encoder"])
    mlp_keys = tuple(cfg["algo"]["mlp_keys"]["encoder"])
    compute_dtype = fabric.precision.compute_dtype
    screen = int(cfg["env"]["screen_size"])

    def _channels(k):
        shape = obs_space[k].shape
        return int(np.prod(shape[:-3]) * shape[-1]) if len(shape) >= 3 else 1

    wm = WorldModelDV1(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        cnn_output_channels=tuple(_channels(k) for k in cfg["algo"]["cnn_keys"]["decoder"]),
        mlp_output_dims=tuple(int(obs_space[k].shape[0]) for k in cfg["algo"]["mlp_keys"]["decoder"]),
        image_size=(screen, screen),
        actions_dim=tuple(actions_dim),
        stochastic_size=int(wm_cfg["stochastic_size"]),
        min_std=float(wm_cfg["min_std"]),
        recurrent_state_size=int(wm_cfg["recurrent_model"]["recurrent_state_size"]),
        encoder_cnn_multiplier=int(wm_cfg["encoder"]["cnn_channels_multiplier"]),
        encoder_mlp_layers=int(wm_cfg["encoder"]["mlp_layers"]),
        encoder_dense_units=int(wm_cfg["encoder"]["dense_units"]),
        decoder_cnn_multiplier=int(wm_cfg["observation_model"]["cnn_channels_multiplier"]),
        decoder_mlp_layers=int(wm_cfg["observation_model"]["mlp_layers"]),
        decoder_dense_units=int(wm_cfg["observation_model"]["dense_units"]),
        representation_hidden_size=int(wm_cfg["representation_model"]["hidden_size"]),
        transition_hidden_size=int(wm_cfg["transition_model"]["hidden_size"]),
        reward_layers=int(wm_cfg["reward_model"]["mlp_layers"]),
        reward_dense_units=int(wm_cfg["reward_model"]["dense_units"]),
        use_continues=bool(wm_cfg["use_continues"]),
        continue_layers=int(wm_cfg["discount_model"]["mlp_layers"]),
        continue_dense_units=int(wm_cfg["discount_model"]["dense_units"]),
        dense_act=str(cfg["algo"]["dense_act"]),
        cnn_act=str(cfg["algo"]["cnn_act"]),
        dtype=compute_dtype,
    )

    actor = ActorDV2(
        latent_state_size=wm.latent_state_size,
        actions_dim=tuple(actions_dim),
        is_continuous=bool(is_continuous),
        distribution=str(cfg.get("distribution", {}).get("type", "auto")),
        init_std=float(actor_cfg["init_std"]),
        min_std=float(actor_cfg["min_std"]),
        dense_units=int(actor_cfg["dense_units"]),
        mlp_layers=int(actor_cfg["mlp_layers"]),
        act=str(actor_cfg["dense_act"]),
        use_layer_norm=False,
        expl_amount=float(actor_cfg.get("expl_amount", 0.0) or 0.0),
        expl_decay=float(actor_cfg.get("expl_decay", 0.0) or 0.0),
        expl_min=float(actor_cfg.get("expl_min", 0.0) or 0.0),
        dtype=compute_dtype,
    )
    critic_cfg = cfg["algo"]["critic"]
    critic = CriticDV2(
        mlp_layers=int(critic_cfg["mlp_layers"]),
        dense_units=int(critic_cfg["dense_units"]),
        act=str(critic_cfg["dense_act"]),
        use_layer_norm=False,
        dtype=compute_dtype,
    )

    key = jax.random.PRNGKey(int(cfg["seed"]))
    k_wm, k_actor, k_critic, k_dyn = jax.random.split(key, 4)

    B = 1
    dummy_obs = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        if len(shape) == 4:
            s, hh, ww, c = shape
            shape = (hh, ww, s * c)
        dummy_obs[k] = jnp.zeros((B, *shape), jnp.uint8)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((B, *obs_space[k].shape), jnp.float32)

    if world_model_state is not None:
        wm_params = jax.tree.map(jnp.asarray, world_model_state)
    else:

        def wm_init(mod: WorldModelDV1):
            emb = mod.encode(dummy_obs)
            h = jnp.zeros((B, wm.recurrent_state_size), jnp.float32)
            z = jnp.zeros((B, wm.stochastic_size), jnp.float32)
            a = jnp.zeros((B, int(np.sum(actions_dim))), jnp.float32)
            h, z, *_ = mod.dynamic(z, h, a, emb, k_dyn)
            latent = jnp.concatenate([z, h], axis=-1)
            mod.decode(latent)
            mod.reward_mean(latent)
            if mod.use_continues:
                mod.continue_logits(latent)
            return ()

        wm_params = nn.init(wm_init, wm)(k_wm)

    latent = jnp.zeros((B, wm.latent_state_size), jnp.float32)
    actor_params = (
        jax.tree.map(jnp.asarray, actor_state) if actor_state is not None else actor.init(k_actor, latent)
    )
    critic_params = (
        jax.tree.map(jnp.asarray, critic_state) if critic_state is not None else critic.init(k_critic, latent)
    )

    wm_params = fabric.replicate(wm_params)
    actor_params = fabric.replicate(actor_params)
    critic_params = fabric.replicate(critic_params)

    from sheeprl_tpu.parallel.fabric import resolve_player_device

    player = PlayerDV2(
        wm,
        wm_params,
        actor,
        actor_params,
        actions_dim,
        int(cfg["env"]["num_envs"]),
        int(cfg["seed"]),
        device=resolve_player_device(cfg["algo"].get("player_device", "auto")),
    )
    return wm, wm_params, actor, actor_params, critic, critic_params, player
