"""Dreamer-V2 agent (reference: sheeprl/algos/dreamer_v2/agent.py:40-1104).

flax re-design, TPU-first, sharing the DV3 layout of this repo
(``algos/dreamer_v3/agent.py``): one ``WorldModel`` param tree (the
reference's WorldModel container, agent.py:707-732), an Actor tree and a
critic tree. Differences from the Dreamer-V3 agent that this module encodes:

- ELU activations and *optional* LayerNorm everywhere (reference config
  ``layer_norm: False`` — DV3 always LN+SiLU),
- VALID-padded conv stacks: encoder 4x(k4 s2) from 64x64 -> 2x2, decoder
  1x1 seed -> k5,k5,k6,k6 s2 transposed convs back to 64x64
  (reference agent.py:62-76, 166-186),
- no unimix on the categorical logits,
- scalar Normal(mean, 1) reward head (no two-hot) and an *optional*
  continue model (``use_continues``),
- zero (non-learnable) initial RSSM states, gated by ``is_first``
  (reference RSSM.dynamic, agent.py:380-385),
- trunc_normal continuous actor with exploration-noise support
  (reference Actor, agent.py:417-560).

All sequence loops are ``lax.scan``; images are NHWC uint8 normalized
in-graph.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models import LayerNormGRUCell
from sheeprl_tpu.models.blocks import LayerNorm, get_activation
from sheeprl_tpu.ops.distributions import (
    Independent,
    Normal,
    OneHotCategoricalStraightThrough,
    TanhNormal,
    TruncatedNormal,
)
from sheeprl_tpu.parallel.fabric import HostPlayerParams, put_tree, resolve_player_device

Array = jax.Array

xavier_init = nn.initializers.xavier_normal()


def _dense(units: int, dtype: Any, name: Optional[str] = None) -> nn.Dense:
    return nn.Dense(units, dtype=dtype, param_dtype=jnp.float32, kernel_init=xavier_init, name=name)


class _MLPBlock(nn.Module):
    """Dense -> (LayerNorm) -> act, repeated — the DV1/DV2 block shape."""

    layers: int
    units: int
    act: str = "elu"
    use_layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        act = get_activation(self.act)
        for _ in range(self.layers):
            x = _dense(self.units, self.dtype)(x)
            if self.use_layer_norm:
                x = LayerNorm()(x)
            x = act(x)
        return x


class CNNEncoderDV2(nn.Module):
    """4-stage VALID k4 s2 conv encoder (reference agent.py:62-76):
    channels ``[1,2,4,8]*multiplier``, for 64x64 inputs the output is
    ``2*2*8*multiplier`` features."""

    keys: Tuple[str, ...]
    channels_multiplier: int
    act: str = "elu"
    use_layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, Array]) -> Array:
        act = get_activation(self.act)
        x = jnp.concatenate([obs[k].astype(self.dtype) / 255.0 - 0.5 for k in self.keys], axis=-1)
        for i in range(4):
            x = nn.Conv(
                (2**i) * self.channels_multiplier,
                kernel_size=(4, 4),
                strides=(2, 2),
                padding="VALID",
                dtype=self.dtype,
                param_dtype=jnp.float32,
                kernel_init=xavier_init,
            )(x)
            if self.use_layer_norm:
                x = LayerNorm()(x)
            x = act(x)
        return x.reshape(*x.shape[:-3], -1)


class CNNDecoderDV2(nn.Module):
    """Inverse of :class:`CNNEncoderDV2` (reference agent.py:131-195):
    Dense(latent -> encoder_output_dim), 1x1 seed, then transposed convs
    k5,k5,k6,k6 stride 2 VALID back to 64x64. Returns normalized-pixel
    reconstructions per key."""

    keys: Tuple[str, ...]
    output_channels: Tuple[int, ...]
    channels_multiplier: int
    cnn_encoder_output_dim: int
    image_size: Tuple[int, int]
    act: str = "elu"
    use_layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: Array) -> Dict[str, Array]:
        act = get_activation(self.act)
        lead = latent.shape[:-1]
        x = _dense(self.cnn_encoder_output_dim, self.dtype)(latent)
        x = x.reshape(-1, 1, 1, self.cnn_encoder_output_dim)
        channels = [4 * self.channels_multiplier, 2 * self.channels_multiplier, self.channels_multiplier]
        kernels = [5, 5, 6, 6]
        for i, ch in enumerate(channels):
            x = nn.ConvTranspose(
                ch,
                kernel_size=(kernels[i], kernels[i]),
                strides=(2, 2),
                padding="VALID",
                dtype=self.dtype,
                param_dtype=jnp.float32,
                kernel_init=xavier_init,
            )(x)
            if self.use_layer_norm:
                x = LayerNorm()(x)
            x = act(x)
        x = nn.ConvTranspose(
            sum(self.output_channels),
            kernel_size=(kernels[-1], kernels[-1]),
            strides=(2, 2),
            padding="VALID",
            dtype=self.dtype,
            param_dtype=jnp.float32,
            kernel_init=xavier_init,
        )(x)
        x = x.reshape(*lead, *self.image_size, sum(self.output_channels)).astype(jnp.float32)
        splits = np.cumsum(self.output_channels)[:-1]
        return {k: part for k, part in zip(self.keys, jnp.split(x, splits, axis=-1))}


class MLPEncoderDV2(nn.Module):
    """N x (Dense + optional LN + act) over concatenated vector obs
    (reference agent.py:83-129; no symlog in DV2)."""

    keys: Tuple[str, ...]
    mlp_layers: int = 4
    dense_units: int = 400
    act: str = "elu"
    use_layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, Array]) -> Array:
        x = jnp.concatenate([obs[k].astype(jnp.float32) for k in self.keys], axis=-1)
        return _MLPBlock(self.mlp_layers, self.dense_units, self.act, self.use_layer_norm, self.dtype)(
            x.astype(self.dtype)
        )


class MLPDecoderDV2(nn.Module):
    """Trunk + per-key linear heads (reference agent.py:198-246)."""

    keys: Tuple[str, ...]
    output_dims: Tuple[int, ...]
    mlp_layers: int = 4
    dense_units: int = 400
    act: str = "elu"
    use_layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, latent: Array) -> Dict[str, Array]:
        x = _MLPBlock(self.mlp_layers, self.dense_units, self.act, self.use_layer_norm, self.dtype)(
            latent.astype(self.dtype)
        )
        return {
            k: _dense(d, self.dtype, name=f"head_{k}")(x).astype(jnp.float32)
            for k, d in zip(self.keys, self.output_dims)
        }


class RecurrentModelDV2(nn.Module):
    """Dense(+LN)+act projection then LayerNorm-GRU (reference
    agent.py:249-298). ``gru_layer_norm`` mirrors
    ``world_model.recurrent_model.layer_norm`` (True by default in DV2)."""

    recurrent_state_size: int
    dense_units: int
    act: str = "elu"
    mlp_layer_norm: bool = False
    gru_layer_norm: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array, h: Array) -> Array:
        feat = _MLPBlock(1, self.dense_units, self.act, self.mlp_layer_norm, self.dtype)(x)
        new_h, _ = LayerNormGRUCell(
            self.recurrent_state_size, bias=True, layer_norm=self.gru_layer_norm, dtype=self.dtype
        )(h.astype(self.dtype), feat)
        return new_h.astype(jnp.float32)


def compute_stochastic_state(logits: Array, key: Optional[Array], sample: bool = True) -> Array:
    """Straight-through sample (or mode) of the ``[..., S, D]`` categorical,
    flattened to ``[..., S*D]`` (reference dreamer_v2/utils.py:44-60 — no
    unimix in DV2)."""
    dist = Independent(OneHotCategoricalStraightThrough(logits=logits), 1)
    state = dist.rsample(seed=key) if sample else dist.mode
    return state.reshape(*state.shape[:-2], -1)


class WorldModelDV2(nn.Module):
    """Encoder + RSSM + decoders + reward (+ optional continue) in one param
    tree (reference WorldModel container agent.py:707-732 and RSSM
    agent.py:300-415). Methods are ``apply(..., method=...)`` entry points."""

    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    cnn_output_channels: Tuple[int, ...]
    mlp_output_dims: Tuple[int, ...]
    image_size: Tuple[int, int]
    actions_dim: Tuple[int, ...]
    stochastic_size: int = 32
    discrete_size: int = 32
    recurrent_state_size: int = 600
    recurrent_dense_units: int = 400
    gru_layer_norm: bool = True
    encoder_cnn_multiplier: int = 48
    encoder_mlp_layers: int = 4
    encoder_dense_units: int = 400
    decoder_cnn_multiplier: int = 48
    decoder_mlp_layers: int = 4
    decoder_dense_units: int = 400
    representation_hidden_size: int = 600
    transition_hidden_size: int = 600
    reward_layers: int = 4
    reward_dense_units: int = 400
    use_continues: bool = False
    continue_layers: int = 4
    continue_dense_units: int = 400
    dense_act: str = "elu"
    cnn_act: str = "elu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @property
    def stoch_state_size(self) -> int:
        return self.stochastic_size * self.discrete_size

    @property
    def latent_state_size(self) -> int:
        return self.stoch_state_size + self.recurrent_state_size

    @property
    def cnn_encoder_output_dim(self) -> int:
        # 4 VALID k4 s2 stages: 64 -> 31 -> 14 -> 6 -> 2
        hw = self.image_size[0]
        for _ in range(4):
            hw = (hw - 4) // 2 + 1
        return hw * hw * 8 * self.encoder_cnn_multiplier

    def setup(self) -> None:
        if self.cnn_keys:
            self.cnn_encoder = CNNEncoderDV2(
                self.cnn_keys, self.encoder_cnn_multiplier, self.cnn_act, self.layer_norm, self.dtype
            )
            self.cnn_decoder = CNNDecoderDV2(
                self.cnn_keys,
                self.cnn_output_channels,
                self.decoder_cnn_multiplier,
                self.cnn_encoder_output_dim,
                self.image_size,
                self.cnn_act,
                self.layer_norm,
                self.dtype,
            )
        if self.mlp_keys:
            self.mlp_encoder = MLPEncoderDV2(
                self.mlp_keys,
                self.encoder_mlp_layers,
                self.encoder_dense_units,
                self.dense_act,
                self.layer_norm,
                self.dtype,
            )
            self.mlp_decoder = MLPDecoderDV2(
                self.mlp_keys,
                self.mlp_output_dims,
                self.decoder_mlp_layers,
                self.decoder_dense_units,
                self.dense_act,
                self.layer_norm,
                self.dtype,
            )
        self.recurrent_model = RecurrentModelDV2(
            self.recurrent_state_size,
            self.recurrent_dense_units,
            self.dense_act,
            False,
            self.gru_layer_norm,
            self.dtype,
        )
        self.representation_model = nn.Sequential(
            [
                _MLPBlock(1, self.representation_hidden_size, self.dense_act, self.layer_norm, self.dtype),
                _dense(self.stoch_state_size, jnp.float32),
            ]
        )
        self.transition_model = nn.Sequential(
            [
                _MLPBlock(1, self.transition_hidden_size, self.dense_act, self.layer_norm, self.dtype),
                _dense(self.stoch_state_size, jnp.float32),
            ]
        )
        self.reward_model = nn.Sequential(
            [
                _MLPBlock(self.reward_layers, self.reward_dense_units, self.dense_act, self.layer_norm, self.dtype),
                _dense(1, jnp.float32),
            ]
        )
        if self.use_continues:
            self.continue_model = nn.Sequential(
                [
                    _MLPBlock(
                        self.continue_layers, self.continue_dense_units, self.dense_act, self.layer_norm, self.dtype
                    ),
                    _dense(1, jnp.float32),
                ]
            )

    # ------------------------------------------------------------------ #
    # entry points
    # ------------------------------------------------------------------ #
    def encode(self, obs: Dict[str, Array]) -> Array:
        feats = []
        if self.cnn_keys:
            feats.append(self.cnn_encoder(obs))
        if self.mlp_keys:
            feats.append(self.mlp_encoder(obs))
        out = feats[0] if len(feats) == 1 else jnp.concatenate(feats, axis=-1)
        return out.astype(jnp.float32)

    def decode(self, latent: Array) -> Dict[str, Array]:
        out: Dict[str, Array] = {}
        if self.cnn_keys:
            out.update(self.cnn_decoder(latent.astype(self.dtype)))
        if self.mlp_keys:
            out.update(self.mlp_decoder(latent.astype(self.dtype)))
        return out

    def reward_mean(self, latent: Array) -> Array:
        return self.reward_model(latent.astype(self.dtype))

    def continue_logits(self, latent: Array) -> Array:
        return self.continue_model(latent.astype(self.dtype))

    def _stoch_logits(self, logits: Array) -> Array:
        return logits.reshape(*logits.shape[:-1], self.stochastic_size, self.discrete_size)

    def dynamic(
        self,
        z: Array,
        h: Array,
        action: Array,
        embedded: Array,
        is_first: Array,
        key: Array,
    ) -> Tuple[Array, Array, Array, Array]:
        """One posterior step (reference RSSM.dynamic, agent.py:334-385):
        zero initial states gated by ``is_first``; returns
        ``(h', z'_flat, posterior_logits, prior_logits)`` with logits
        ``[B, S, D]``."""
        action = (1 - is_first) * action
        z = (1 - is_first) * z
        h = (1 - is_first) * h
        h = self.recurrent_model(jnp.concatenate([z, action], axis=-1).astype(self.dtype), h)
        prior_logits = self._stoch_logits(self.transition_model(h.astype(self.dtype)))
        post_in = jnp.concatenate([h, embedded], axis=-1)
        post_logits = self._stoch_logits(self.representation_model(post_in.astype(self.dtype)))
        z = compute_stochastic_state(post_logits, key)
        return h, z, post_logits, prior_logits

    def imagination(self, z: Array, h: Array, action: Array, key: Array) -> Tuple[Array, Array]:
        """One prior step in latent space (reference RSSM.imagination,
        agent.py:397-414)."""
        h = self.recurrent_model(jnp.concatenate([z, action], axis=-1).astype(self.dtype), h)
        prior_logits = self._stoch_logits(self.transition_model(h.astype(self.dtype)))
        z = compute_stochastic_state(prior_logits, key)
        return z, h

    def observe_step(self, z, h, action, obs, key):
        """Policy-time posterior update (reference PlayerDV2.get_actions,
        agent.py:823-852)."""
        embedded = self.encode(obs)
        h = self.recurrent_model(jnp.concatenate([z, action], axis=-1).astype(self.dtype), h)
        post_in = jnp.concatenate([h, embedded], axis=-1)
        post_logits = self._stoch_logits(self.representation_model(post_in.astype(self.dtype)))
        z = compute_stochastic_state(post_logits, key)
        return z, h


def rssm_scan(
    wm: WorldModelDV2,
    params: Any,
    embedded: Array,  # [T, B, E]
    actions: Array,  # [T, B, A] (already shifted)
    is_first: Array,  # [T, B, 1]
    key: Array,
) -> Tuple[Array, Array, Array, Array]:
    """The DV2 RSSM sequence as one ``lax.scan`` (replaces the reference's
    Python loop, dreamer_v2.py:148-158). Returns time-major
    ``(recurrent_states, posteriors, posterior_logits, prior_logits)``."""
    B = embedded.shape[1]
    h = jnp.zeros((B, wm.recurrent_state_size), jnp.float32)
    z = jnp.zeros((B, wm.stoch_state_size), jnp.float32)

    def step(carry, xs):
        h, z, key = carry
        emb_t, act_t, first_t = xs
        key, sub = jax.random.split(key)
        h, z, post_logits, prior_logits = wm.apply(
            params, z, h, act_t, emb_t, first_t, sub, method=WorldModelDV2.dynamic
        )
        return (h, z, key), (h, z, post_logits, prior_logits)

    (_, _, _), (hs, zs, post_logits, prior_logits) = jax.lax.scan(step, (h, z, key), (embedded, actions, is_first))
    return hs, zs, post_logits, prior_logits


class ActorDV2(nn.Module):
    """Dreamer-V2 actor (reference agent.py:417-560): MLP trunk + heads.
    ``__call__`` returns raw head outputs; distribution math lives in
    :func:`actor_dists`. Default continuous distribution is trunc_normal."""

    latent_state_size: int
    actions_dim: Tuple[int, ...]
    is_continuous: bool
    distribution: str = "auto"
    init_std: float = 0.0
    min_std: float = 0.1
    dense_units: int = 400
    mlp_layers: int = 4
    act: str = "elu"
    use_layer_norm: bool = False
    expl_amount: float = 0.0
    expl_decay: float = 0.0
    expl_min: float = 0.0
    dtype: Any = jnp.float32

    def resolved_distribution(self) -> str:
        dist = self.distribution.lower()
        if dist not in ("auto", "normal", "tanh_normal", "discrete", "trunc_normal"):
            raise ValueError(f"unknown actor distribution: {dist}")
        if dist == "discrete" and self.is_continuous:
            raise ValueError("discrete distribution with continuous action space")
        if dist == "auto":
            dist = "trunc_normal" if self.is_continuous else "discrete"
        return dist

    @nn.compact
    def __call__(self, state: Array) -> List[Array]:
        x = _MLPBlock(self.mlp_layers, self.dense_units, self.act, self.use_layer_norm, self.dtype)(
            state.astype(self.dtype)
        )
        if self.is_continuous:
            return [_dense(sum(self.actions_dim) * 2, jnp.float32, name="head_0")(x)]
        return [_dense(d, jnp.float32, name=f"head_{i}")(x) for i, d in enumerate(self.actions_dim)]

    def get_expl_amount(self, step: int) -> float:
        amount = self.expl_amount
        if self.expl_decay:
            amount *= 0.5 ** (float(step) / self.expl_decay)
        return max(amount, self.expl_min)


def actor_dists(actor: ActorDV2, pre_dist: List[Array]):
    """Build action distributions from raw head outputs (reference
    Actor.forward, agent.py:506-549)."""
    dist_type = actor.resolved_distribution()
    if actor.is_continuous:
        mean, std = jnp.split(pre_dist[0], 2, axis=-1)
        if dist_type == "tanh_normal":
            mean = 5 * jnp.tanh(mean / 5)
            std = jax.nn.softplus(std + actor.init_std) + actor.min_std
            return [TanhNormal(mean, std)]
        if dist_type == "normal":
            return [Independent(Normal(mean, std), 1)]
        # trunc_normal (DV1/DV2 default)
        std = 2 * jax.nn.sigmoid((std + actor.init_std) / 2) + actor.min_std
        mean = jnp.tanh(mean)
        return [
            Independent(
                TruncatedNormal(mean, std, -jnp.ones_like(mean), jnp.ones_like(mean)), 1
            )
        ]
    return [OneHotCategoricalStraightThrough(logits=logits) for logits in pre_dist]


def sample_actor_actions(
    actor: ActorDV2, params: Any, state: Array, key: Array, greedy: bool = False
) -> Array:
    """Sample (or mode-of-100-candidates) actions; returns the concatenated
    action vector (reference Actor.forward sampling, agent.py:538-549)."""
    dists = actor_dists(actor, actor.apply(params, state))
    if actor.is_continuous:
        d = dists[0]
        if greedy:
            cand = d.sample(seed=key, sample_shape=(100,))
            logp = jax.vmap(d.log_prob)(cand)
            idx = jnp.argmax(logp, axis=0)
            return jnp.take_along_axis(cand, idx[None, ..., None], axis=0)[0]
        return d.rsample(seed=key)
    keys = jax.random.split(key, len(dists))
    parts = [(d.mode if greedy else d.rsample(seed=k)) for d, k in zip(dists, keys)]
    return jnp.concatenate(parts, axis=-1)


def actor_logprob_entropy(
    actor: ActorDV2, params: Any, states: Array, actions: Array
) -> Tuple[Array, Array]:
    """log pi(a|s) and entropy for stored (imagined) actions."""
    dists = actor_dists(actor, actor.apply(params, states))
    if actor.is_continuous:
        d = dists[0]
        try:
            ent = d.entropy()
        except NotImplementedError:
            ent = jnp.zeros(states.shape[:-1])
        return d.log_prob(actions), ent
    splits = np.cumsum(actor.actions_dim)[:-1]
    parts = jnp.split(actions, splits, axis=-1)
    logp = sum(d.log_prob(p) for d, p in zip(dists, parts))
    ent = sum(d.entropy() for d in dists)
    return logp, ent


def add_exploration_noise(
    actor: ActorDV2,
    actions: np.ndarray,
    actions_dim: Sequence[int],
    step: int,
    rng: np.random.Generator,
) -> np.ndarray:
    """Epsilon-style exploration noise on host actions (reference
    Actor.add_exploration_noise, agent.py:551-575): Gaussian jitter for
    continuous actions, uniform-resample for discrete one-hots."""
    expl_amount = actor.get_expl_amount(step)
    if expl_amount <= 0.0:
        return actions
    if actor.is_continuous:
        return np.clip(rng.normal(actions, expl_amount), -1, 1).astype(np.float32)
    out = []
    splits = np.cumsum(actions_dim)[:-1]
    for part in np.split(actions, splits, axis=-1):
        d = part.shape[-1]
        sample = np.eye(d, dtype=part.dtype)[rng.integers(0, d, part.shape[:-1])]
        mask = (rng.random(part.shape[:-1]) < expl_amount)[..., None]
        out.append(np.where(mask, sample, part))
    return np.concatenate(out, axis=-1)


class CriticDV2(nn.Module):
    """MLP critic with scalar Normal(mean, 1) head (reference build_agent,
    agent.py:1032-1055)."""

    mlp_layers: int = 4
    dense_units: int = 400
    act: str = "elu"
    use_layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = _MLPBlock(self.mlp_layers, self.dense_units, self.act, self.use_layer_norm, self.dtype)(
            x.astype(self.dtype)
        )
        return _dense(1, jnp.float32)(x)


class PlayerDV2(HostPlayerParams):
    """Stateful env-interaction handle (reference PlayerDV2,
    agent.py:735-860): per-env (h, z, prev_action) advanced by one jitted
    observe+act step; zero initial states.

    ``device`` optionally pins the observe+act step to the host CPU backend
    (learner-on-chip/actor-on-host; see ``parallel.fabric.resolve_player_device``)."""

    _placed_attrs = ("wm_params", "actor_params")

    def __init__(
        self,
        wm: WorldModelDV2,
        wm_params: Any,
        actor: ActorDV2,
        actor_params: Any,
        actions_dim: Sequence[int],
        num_envs: int,
        seed: int = 0,
        device: Optional[Any] = None,
    ) -> None:
        self.wm = wm
        self.actor = actor
        self.device = device  # must precede the param assignments below
        self.wm_params = wm_params
        self.actor_params = actor_params
        self.actions_dim = tuple(actions_dim)
        self.num_envs = num_envs
        self.expl_rng = np.random.default_rng(seed)
        # recurrent state lives on device between steps (one less host round
        # trip per env step on a remote-attached chip); exploration noise is
        # host-side, so the action still crosses to host every step
        self.h: Optional[Any] = None
        self.z: Optional[Any] = None
        self.actions: Optional[Any] = None

        def _step(wm_params, actor_params, obs, h, z, prev_action, key, greedy):
            k1, k2 = jax.random.split(key)
            # method-by-name so the same player drives any world model with an
            # ``observe_step`` entry point (DV1 reuses this class, mirroring
            # the reference's Actor aliasing in dreamer_v1/agent.py:28-29)
            z, h = wm.apply(wm_params, z, h, prev_action, obs, k1, method="observe_step")
            latent = jnp.concatenate([z, h], axis=-1)
            action = sample_actor_actions(actor, actor_params, latent, k2, greedy)
            return action, h, z

        self._step = jax.jit(_step, static_argnames="greedy")

    def init_states(self, reset_envs: Optional[Sequence[int]] = None) -> None:
        if reset_envs is None or len(reset_envs) == 0:
            # host-side zeros: uncommitted, so the jitted step pulls them
            # onto whichever backend the params live on
            self.h = np.zeros((self.num_envs, self.wm.recurrent_state_size), np.float32)
            self.z = np.zeros((self.num_envs, self.wm.stoch_state_size), np.float32)
            self.actions = np.zeros((self.num_envs, int(np.sum(self.actions_dim))), np.float32)
        else:
            mask = np.zeros((self.num_envs, 1), np.float32)
            mask[list(reset_envs)] = 1.0
            m = jnp.asarray(mask)
            self.h = jnp.where(m, 0.0, self.h)
            self.z = jnp.where(m, 0.0, self.z)
            self.actions = np.asarray(self.actions).copy()
            self.actions[list(reset_envs)] = 0.0

    def get_actions(
        self,
        obs: Dict[str, Array],
        key: Array,
        greedy: bool = False,
        expl_step: int = 0,
        with_exploration: bool = False,
    ) -> Array:
        self.poll_stream_attrs()
        action, h, z = self._step(
            self.wm_params, self.actor_params, obs, self.h, self.z, self.actions, put_tree(key, self.device), greedy
        )
        self.h, self.z = h, z
        actions = np.asarray(jax.device_get(action))
        if with_exploration:
            actions = add_exploration_noise(self.actor, actions, self.actions_dim, expl_step, self.expl_rng)
        self.actions = actions
        return self.actions


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Any] = None,
    actor_state: Optional[Any] = None,
    critic_state: Optional[Any] = None,
    target_critic_state: Optional[Any] = None,
) -> Tuple[WorldModelDV2, Any, ActorDV2, Any, Any, Any, Any, PlayerDV2]:
    """Construct modules + init/replicate params (reference build_agent,
    agent.py:863-1104). Returns the same tuple shape as the DV3 builder."""
    wm_cfg = cfg["algo"]["world_model"]
    actor_cfg = cfg["algo"]["actor"]
    cnn_keys = tuple(cfg["algo"]["cnn_keys"]["encoder"])
    mlp_keys = tuple(cfg["algo"]["mlp_keys"]["encoder"])
    compute_dtype = fabric.precision.compute_dtype
    screen = int(cfg["env"]["screen_size"])

    def _channels(k):
        shape = obs_space[k].shape
        return int(np.prod(shape[:-3]) * shape[-1]) if len(shape) >= 3 else 1

    wm = WorldModelDV2(
        cnn_keys=cnn_keys,
        mlp_keys=mlp_keys,
        cnn_output_channels=tuple(_channels(k) for k in cfg["algo"]["cnn_keys"]["decoder"]),
        mlp_output_dims=tuple(int(obs_space[k].shape[0]) for k in cfg["algo"]["mlp_keys"]["decoder"]),
        image_size=(screen, screen),
        actions_dim=tuple(actions_dim),
        stochastic_size=int(wm_cfg["stochastic_size"]),
        discrete_size=int(wm_cfg["discrete_size"]),
        recurrent_state_size=int(wm_cfg["recurrent_model"]["recurrent_state_size"]),
        recurrent_dense_units=int(wm_cfg["recurrent_model"]["dense_units"]),
        gru_layer_norm=bool(wm_cfg["recurrent_model"]["layer_norm"]),
        encoder_cnn_multiplier=int(wm_cfg["encoder"]["cnn_channels_multiplier"]),
        encoder_mlp_layers=int(wm_cfg["encoder"]["mlp_layers"]),
        encoder_dense_units=int(wm_cfg["encoder"]["dense_units"]),
        decoder_cnn_multiplier=int(wm_cfg["observation_model"]["cnn_channels_multiplier"]),
        decoder_mlp_layers=int(wm_cfg["observation_model"]["mlp_layers"]),
        decoder_dense_units=int(wm_cfg["observation_model"]["dense_units"]),
        representation_hidden_size=int(wm_cfg["representation_model"]["hidden_size"]),
        transition_hidden_size=int(wm_cfg["transition_model"]["hidden_size"]),
        reward_layers=int(wm_cfg["reward_model"]["mlp_layers"]),
        reward_dense_units=int(wm_cfg["reward_model"]["dense_units"]),
        use_continues=bool(wm_cfg["use_continues"]),
        continue_layers=int(wm_cfg["discount_model"]["mlp_layers"]),
        continue_dense_units=int(wm_cfg["discount_model"]["dense_units"]),
        dense_act=str(cfg["algo"]["dense_act"]),
        cnn_act=str(cfg["algo"]["cnn_act"]),
        layer_norm=bool(cfg["algo"]["layer_norm"]),
        dtype=compute_dtype,
    )

    actor = ActorDV2(
        latent_state_size=wm.latent_state_size,
        actions_dim=tuple(actions_dim),
        is_continuous=bool(is_continuous),
        distribution=str(cfg.get("distribution", {}).get("type", "auto")),
        init_std=float(actor_cfg["init_std"]),
        min_std=float(actor_cfg["min_std"]),
        dense_units=int(actor_cfg["dense_units"]),
        mlp_layers=int(actor_cfg["mlp_layers"]),
        act=str(actor_cfg["dense_act"]),
        use_layer_norm=bool(actor_cfg["layer_norm"]),
        expl_amount=float(actor_cfg.get("expl_amount", 0.0) or 0.0),
        expl_decay=float(actor_cfg.get("expl_decay", 0.0) or 0.0),
        expl_min=float(actor_cfg.get("expl_min", 0.0) or 0.0),
        dtype=compute_dtype,
    )
    critic_cfg = cfg["algo"]["critic"]
    critic = CriticDV2(
        mlp_layers=int(critic_cfg["mlp_layers"]),
        dense_units=int(critic_cfg["dense_units"]),
        act=str(critic_cfg["dense_act"]),
        use_layer_norm=bool(critic_cfg["layer_norm"]),
        dtype=compute_dtype,
    )

    key = jax.random.PRNGKey(int(cfg["seed"]))
    k_wm, k_actor, k_critic, k_dyn = jax.random.split(key, 4)

    B = 1
    dummy_obs = {}
    for k in cnn_keys:
        shape = obs_space[k].shape
        if len(shape) == 4:
            s, hh, ww, c = shape
            shape = (hh, ww, s * c)
        dummy_obs[k] = jnp.zeros((B, *shape), jnp.uint8)
    for k in mlp_keys:
        dummy_obs[k] = jnp.zeros((B, *obs_space[k].shape), jnp.float32)

    if world_model_state is not None:
        wm_params = jax.tree.map(jnp.asarray, world_model_state)
    else:

        def wm_init(mod: WorldModelDV2):
            emb = mod.encode(dummy_obs)
            h = jnp.zeros((B, wm.recurrent_state_size), jnp.float32)
            z = jnp.zeros((B, wm.stoch_state_size), jnp.float32)
            a = jnp.zeros((B, int(np.sum(actions_dim))), jnp.float32)
            first = jnp.ones((B, 1), jnp.float32)
            h, z, _, _ = mod.dynamic(z, h, a, emb, first, k_dyn)
            latent = jnp.concatenate([z, h], axis=-1)
            mod.decode(latent)
            mod.reward_mean(latent)
            if mod.use_continues:
                mod.continue_logits(latent)
            return ()

        wm_params = nn.init(wm_init, wm)(k_wm)

    latent = jnp.zeros((B, wm.latent_state_size), jnp.float32)
    actor_params = (
        jax.tree.map(jnp.asarray, actor_state) if actor_state is not None else actor.init(k_actor, latent)
    )
    critic_params = (
        jax.tree.map(jnp.asarray, critic_state) if critic_state is not None else critic.init(k_critic, latent)
    )
    target_critic_params = (
        jax.tree.map(jnp.asarray, target_critic_state)
        if target_critic_state is not None
        else jax.tree.map(jnp.copy, critic_params)
    )

    wm_params = fabric.replicate(wm_params)
    actor_params = fabric.replicate(actor_params)
    critic_params = fabric.replicate(critic_params)
    target_critic_params = fabric.replicate(target_critic_params)

    player = PlayerDV2(
        wm,
        wm_params,
        actor,
        actor_params,
        actions_dim,
        int(cfg["env"]["num_envs"]),
        int(cfg["seed"]),
        device=resolve_player_device(cfg["algo"].get("player_device", "auto")),
    )
    return wm, wm_params, actor, actor_params, critic, critic_params, target_critic_params, player
