"""Dreamer-V2 world-model loss with KL balancing
(reference: sheeprl/algos/dreamer_v2/loss.py:9-89)."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.ops.distributions import (
    Independent,
    OneHotCategoricalStraightThrough,
    kl_divergence,
)

Array = jax.Array


def reconstruction_loss(
    po: Dict[str, object],
    observations: Dict[str, Array],
    pr: object,
    rewards: Array,
    priors_logits: Array,
    posteriors_logits: Array,
    kl_balancing_alpha: float = 0.8,
    kl_free_nats: float = 0.0,
    kl_free_avg: bool = True,
    kl_regularizer: float = 1.0,
    pc: Optional[object] = None,
    continue_targets: Optional[Array] = None,
    discount_scale_factor: float = 1.0,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Eq. 2 of the DV2 paper: observation + reward (+ continue) NLL plus the
    KL-balanced state term:
    ``alpha * KL(sg(post) || prior) + (1 - alpha) * KL(post || sg(prior))``
    with free nats applied per-side (averaged first when ``kl_free_avg``).

    ``priors_logits``/``posteriors_logits`` are ``[T, B, S, D]``.
    Returns ``(loss, kl, state_loss, reward_loss, observation_loss,
    continue_loss)`` — same order as the reference.
    """
    observation_loss = -sum(po[k].log_prob(observations[k]).mean() for k in po.keys())
    reward_loss = -pr.log_prob(rewards).mean()

    sg = jax.lax.stop_gradient
    lhs = kl = kl_divergence(
        Independent(OneHotCategoricalStraightThrough(logits=sg(posteriors_logits)), 1),
        Independent(OneHotCategoricalStraightThrough(logits=priors_logits), 1),
    )
    rhs = kl_divergence(
        Independent(OneHotCategoricalStraightThrough(logits=posteriors_logits), 1),
        Independent(OneHotCategoricalStraightThrough(logits=sg(priors_logits)), 1),
    )
    free_nats = jnp.asarray(kl_free_nats, lhs.dtype)
    if kl_free_avg:
        loss_lhs = jnp.maximum(lhs.mean(), free_nats)
        loss_rhs = jnp.maximum(rhs.mean(), free_nats)
    else:
        loss_lhs = jnp.maximum(lhs, free_nats).mean()
        loss_rhs = jnp.maximum(rhs, free_nats).mean()
    kl_loss = kl_balancing_alpha * loss_lhs + (1 - kl_balancing_alpha) * loss_rhs

    if pc is not None and continue_targets is not None:
        continue_loss = discount_scale_factor * -pc.log_prob(continue_targets).mean()
    else:
        continue_loss = jnp.zeros_like(reward_loss)

    total = kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss
    return total, kl.mean(), kl_loss, reward_loss, observation_loss, continue_loss
