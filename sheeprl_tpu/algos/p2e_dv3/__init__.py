from sheeprl_tpu.algos.p2e_dv3 import p2e_dv3_exploration, p2e_dv3_finetuning, evaluate  # noqa: F401
