"""P2E DV3 helpers (reference: sheeprl/algos/p2e_dv3/utils.py)."""

from __future__ import annotations

from sheeprl_tpu.algos.dreamer_v3.utils import AGGREGATOR_KEYS as _DV3_AGGREGATOR_KEYS
from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401
from sheeprl_tpu.algos.p2e_common import P2E_EXPLORATION_KEYS, make_log_models

# The finetuning entrypoint logs the plain Dreamer-V3 metric set; both
# entrypoints share this module's AGGREGATOR_KEYS for the CLI's metric
# whitelist, so the union must cover the finetuning names too. Generic
# exploration names are expanded to one per exploration critic by the
# exploration entrypoint (reference p2e_dv3_exploration.py:680-707).
AGGREGATOR_KEYS_FINETUNING = set(_DV3_AGGREGATOR_KEYS)
AGGREGATOR_KEYS = set(P2E_EXPLORATION_KEYS) | AGGREGATOR_KEYS_FINETUNING
MODELS_TO_REGISTER = {
    "world_model",
    "ensembles",
    "actor_exploration",
    "actor_task",
    "critic_task",
    "target_critic_task",
    "moments_task",
}

__all__ = ["AGGREGATOR_KEYS", "MODELS_TO_REGISTER", "prepare_obs", "test"]

log_models_from_checkpoint = make_log_models(MODELS_TO_REGISTER)
