"""Plan2Explore on Dreamer-V3 — agent builders (reference:
sheeprl/algos/p2e_dv3/agent.py:27-223).

TPU-first redesign of the exploration machinery:

- the **ensemble is ONE vmapped param tree** (N stacked member trees) applied
  with ``jax.vmap`` — replacing the reference's ``nn.ModuleList`` Python loop
  (agent.py:175-204), the same pattern this repo uses for SAC critic
  ensembles;
- the exploration critics are a dict ``name -> {weight, reward_type, params,
  target_params}`` sharing the task critic's two-hot module (reference
  agent.py:118-153);
- the exploration actor shares the task Actor module definition with its own
  params; the player binds whichever actor ``cfg.algo.player.actor_type``
  selects (reference agent.py:207-211).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import (
    Actor,
    PlayerDV3,
    WorldModel,
    _dense,
    _LNMLP,
    hafner_init,
    make_critic,
)
from sheeprl_tpu.algos.dreamer_v3.agent import build_agent as dv3_build_agent

Array = jax.Array


class Ensemble(nn.Module):
    """One ensemble member: MLP from (latent, action) to the flattened
    stochastic state (reference agent.py:181-198)."""

    output_dim: int
    mlp_layers: int = 5
    dense_units: int = 1024
    use_layer_norm: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = _LNMLP(self.mlp_layers, self.dense_units, self.dtype, use_layer_norm=self.use_layer_norm)(
            x.astype(self.dtype)
        )
        return _dense(self.output_dim, jnp.float32, kernel_init=hafner_init)(x)


def ensemble_apply(ens: Ensemble, stacked_params: Any, x: Array) -> Array:
    """Apply all N members to the same input: ``[N, ..., output_dim]``."""
    return jax.vmap(lambda p: ens.apply(p, x))(stacked_params)


def init_ensembles(ens: Ensemble, n: int, key: Array, dummy_in: Array) -> Any:
    """N independently-seeded member trees stacked on a leading axis
    (reference agent.py:174-200 seeds each member with ``seed + i``)."""
    keys = jax.random.split(key, n)
    return jax.vmap(lambda k: ens.init(k, dummy_in))(keys)


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Any] = None,
    ensembles_state: Optional[Any] = None,
    actor_task_state: Optional[Any] = None,
    critic_task_state: Optional[Any] = None,
    target_critic_task_state: Optional[Any] = None,
    actor_exploration_state: Optional[Any] = None,
    critics_exploration_state: Optional[Dict[str, Any]] = None,
) -> Tuple[
    WorldModel,
    Any,
    Actor,
    Any,
    Any,
    Any,
    Any,
    Any,
    Dict[str, Dict[str, Any]],
    Ensemble,
    Any,
    PlayerDV3,
]:
    """Build task models (via the DV3 builder) + exploration actor/critics +
    vmapped ensembles (reference build_agent, agent.py:27-223). Returns
    ``(wm, wm_params, actor, actor_task_params, critic, critic_task_params,
    target_critic_task_params, actor_exploration_params,
    critics_exploration, ensemble, ensembles_params, player)``."""
    wm, wm_params, actor, actor_task_params, critic, critic_task_params, target_critic_task_params, player = (
        dv3_build_agent(
            fabric,
            actions_dim,
            is_continuous,
            cfg,
            obs_space,
            world_model_state,
            actor_task_state,
            critic_task_state,
            target_critic_task_state,
        )
    )

    key = jax.random.PRNGKey(int(cfg["seed"]) + 1)
    k_actor, k_ens, k_crit = jax.random.split(key, 3)
    latent = jnp.zeros((1, wm.latent_state_size), jnp.float32)

    actor_exploration_params = (
        jax.tree.map(jnp.asarray, actor_exploration_state)
        if actor_exploration_state is not None
        else actor.init(k_actor, latent)
    )
    actor_exploration_params = fabric.replicate(actor_exploration_params)

    # exploration critics: {name: {weight, reward_type, params, target_params}}
    critics_exploration: Dict[str, Dict[str, Any]] = {}
    intrinsic_critics = 0
    crit_keys = jax.random.split(k_crit, max(1, len(cfg["algo"]["critics_exploration"])))
    for i, (k, v) in enumerate(cfg["algo"]["critics_exploration"].items()):
        if float(v["weight"]) <= 0:
            continue
        if str(v["reward_type"]) == "intrinsic":
            intrinsic_critics += 1
        if critics_exploration_state is not None:
            params = jax.tree.map(jnp.asarray, critics_exploration_state[k]["module"])
            target = jax.tree.map(jnp.asarray, critics_exploration_state[k]["target_module"])
        else:
            params = critic.init(crit_keys[i], latent)
            target = jax.tree.map(jnp.copy, params)
        critics_exploration[k] = {
            "weight": float(v["weight"]),
            "reward_type": str(v["reward_type"]),
            "params": fabric.replicate(params),
            "target_params": fabric.replicate(target),
        }
    if intrinsic_critics == 0:
        raise RuntimeError("You must specify at least one intrinsic critic (`reward_type='intrinsic'`)")

    # vmapped ensemble: predicts the next flattened stochastic state from
    # (z, h, action)
    ens_cfg = cfg["algo"]["ensembles"]
    ensemble = Ensemble(
        output_dim=wm.stoch_state_size,
        mlp_layers=int(ens_cfg["mlp_layers"]),
        dense_units=int(ens_cfg["dense_units"]),
        use_layer_norm=bool(ens_cfg.get("layer_norm", True)),
        dtype=fabric.precision.compute_dtype,
    )
    dummy_in = jnp.zeros((1, wm.latent_state_size + int(np.sum(actions_dim))), jnp.float32)
    if ensembles_state is not None:
        ensembles_params = jax.tree.map(jnp.asarray, ensembles_state)
    else:
        ensembles_params = init_ensembles(ensemble, int(ens_cfg["n"]), k_ens, dummy_in)
    ensembles_params = fabric.replicate(ensembles_params)

    # the player explores with the exploration actor during the exploration
    # phase (reference agent.py:207-211)
    if str(cfg["algo"]["player"].get("actor_type", "task")) == "exploration":
        player.actor_params = actor_exploration_params

    return (
        wm,
        wm_params,
        actor,
        actor_task_params,
        critic,
        critic_task_params,
        target_critic_task_params,
        actor_exploration_params,
        critics_exploration,
        ensemble,
        ensembles_params,
        player,
    )
