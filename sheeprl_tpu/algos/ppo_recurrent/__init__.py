from sheeprl_tpu.algos.ppo_recurrent import ppo_recurrent, evaluate  # noqa: F401
