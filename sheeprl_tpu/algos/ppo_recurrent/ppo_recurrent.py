"""Recurrent PPO (reference: sheeprl/algos/ppo_recurrent/ppo_recurrent.py:31-531)
— TPU-native.

The redesign:

- **Sequence-chunked rollouts with static shapes.** The reference splits the
  rollout into variable-length episodes, chunks them, and pads to the max
  length. Here every chunk is padded to exactly ``per_rank_sequence_length``
  and the sequence COUNT is padded to a multiple of
  ``devices * per_rank_num_batches`` with fully-masked dummies — the jitted
  update only recompiles when that padded count changes, not every update.
- **Whole-update fusion**: epochs x shuffled sequence-minibatches run as two
  nested ``lax.scan``s inside one ``shard_map``-ped XLA program; sequences
  are sharded across the mesh's data axis and gradients ``pmean``-reduced
  over ICI (the reference's DDP+Join, :45-56).
- **Masked losses** replace ``pack_padded_sequence``: padded steps contribute
  zero to every loss term (reference masks via boolean indexing, :77-101).
- Hidden states are reset on done during the rollout
  (``reset_recurrent_state_on_done``, reference :367-371), and sequences
  restart the LSTM from the STORED per-step states (``prev_hx/prev_cx``,
  reference :72).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Dict, List, Tuple

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.ppo import (
    resolve_fused_rollout_spec,
    resolve_scenario_family,
    scenario_theta_matrix,
)
from sheeprl_tpu.algos.ppo_recurrent.agent import (
    RecurrentPPOPlayer,
    build_agent,
    evaluate_actions,
    evaluate_actions_resettable,
    recurrent_rollout_step,
)
from sheeprl_tpu.algos.ppo_recurrent.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.config.compose import instantiate
from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.envs.variants import ScenarioFamily
from sheeprl_tpu.obs import (
    log_sps_and_heartbeat,
    telemetry_advance,
    telemetry_mark_warm,
    telemetry_register_flops,
    telemetry_run_metrics,
    telemetry_train_window,
)
from sheeprl_tpu.ops.math import gae
from sheeprl_tpu.ops.rollout_scan import (
    ENV_STREAM_SALT,
    init_recurrent_env_carry,
    make_recurrent_onpolicy_superstep_fn,
)
from sheeprl_tpu.ops.superstep import fused_fallback, reset_fused_fallback_warnings
from sheeprl_tpu.parallel.shard_map import shard_map
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.prealloc import RolloutStore
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs


def build_sequences(
    local_data: Dict[str, np.ndarray],
    train_keys: List[str],
    seq_len: int,
    num_envs: int,
    pad_multiple: int,
) -> Dict[str, np.ndarray]:
    """Split the ``[T, E, ...]`` rollout into per-episode chunks of at most
    ``seq_len`` steps (reference :406-444), pad each chunk to ``seq_len`` and
    the chunk count to a multiple of ``pad_multiple``. Only ``train_keys``
    are shipped as ``[seq_len, N_pad, ...]`` arrays; the chunk-initial LSTM
    states are emitted once per sequence as ``hx0``/``cx0`` ``[N_pad, H]``
    (the update reads nothing else from them), plus a ``mask`` of valid
    steps."""
    T = next(iter(local_data.values())).shape[0]
    chunks: List[Dict[str, np.ndarray]] = []
    starts: List[Tuple[int, int]] = []  # (env, t) of each chunk's first step
    for e in range(num_envs):
        env_data = {k: local_data[k][:, e] for k in train_keys}
        ends = np.nonzero(local_data["dones"][:, e, 0])[0].tolist()
        ends.append(T - 1)
        start = 0
        for end in ends:
            stop = min(end + 1, T)  # include the done step
            if stop <= start:
                continue
            for i in range(start, stop, seq_len):
                chunks.append({k: v[i : min(i + seq_len, stop)] for k, v in env_data.items()})
                starts.append((e, i))
            start = stop
    n = len(chunks)
    n_pad = ((n + pad_multiple - 1) // pad_multiple) * pad_multiple
    out: Dict[str, np.ndarray] = {}
    for k in train_keys:
        proto = chunks[0][k]
        arr = np.zeros((seq_len, n_pad, *proto.shape[1:]), proto.dtype)
        for j, ch in enumerate(chunks):
            arr[: ch[k].shape[0], j] = ch[k]
        out[k] = arr
    mask = np.zeros((seq_len, n_pad, 1), np.float32)
    lengths = [ch[train_keys[0]].shape[0] for ch in chunks]
    for j, ln in enumerate(lengths):
        mask[:ln, j] = 1.0
    out["mask"] = mask
    hidden = local_data["prev_hx"].shape[-1]
    hx0 = np.zeros((n_pad, hidden), np.float32)
    cx0 = np.zeros((n_pad, hidden), np.float32)
    for j, (e, t) in enumerate(starts):
        hx0[j] = local_data["prev_hx"][t, e]
        cx0[j] = local_data["prev_cx"][t, e]
    out["hx0"] = hx0
    out["cx0"] = cx0
    return out


def make_local_train(fabric, agent, tx, cfg, obs_keys, *, use_mesh: bool, sequence_dones: bool = False):
    """The UNJITTED masked-sequence update body (replaces reference train(),
    :31-116).  ``use_mesh`` guards the collectives (and the per-shard key
    fork) so the same body serves the ``shard_map``-ped host-path update and
    the fused superstep's embedded call.  ``sequence_dones`` marks batches
    whose sequences are FIXED windows that may cross episode boundaries (the
    fused rollout): the replay then resets the LSTM carry at the stored
    per-step dones (``evaluate_actions_resettable``) instead of assuming
    episode-aligned chunks."""
    update_epochs = int(cfg.algo.update_epochs)
    num_batches = max(1, int(cfg.algo.per_rank_num_batches))
    vf_coef = float(cfg.algo.vf_coef)
    clip_vloss = bool(cfg.algo.clip_vloss)
    normalize_adv = bool(cfg.algo.normalize_advantages)
    reduction = str(cfg.algo.loss_reduction)
    reset_on_done = bool(cfg.algo.reset_recurrent_state_on_done)
    data_axis = fabric.data_axis

    def local_train(params, opt_state, data, hx0, cx0, key, clip_coef, ent_coef):
        if use_mesh:
            key = jax.random.fold_in(key, lax.axis_index(data_axis))
        n_local = data["mask"].shape[1]
        bs = n_local // num_batches

        def minibatch_step(carry, xs):
            params, opt_state = carry
            batch, h0, c0 = xs

            def loss_fn(p):
                obs = {k: batch[k] for k in obs_keys}
                if sequence_dones:
                    logprobs, entropy, values = evaluate_actions_resettable(
                        agent,
                        p,
                        obs,
                        batch["prev_actions"],
                        h0,
                        c0,
                        batch["actions"],
                        batch["dones"],
                        reset_on_done=reset_on_done,
                    )
                else:
                    logprobs, entropy, values = evaluate_actions(
                        agent,
                        p,
                        obs,
                        batch["prev_actions"],
                        h0,
                        c0,
                        batch["actions"],
                    )
                mask = batch["mask"]
                msum = mask.sum() + 1e-8
                adv = batch["advantages"]
                if normalize_adv:
                    mean = (adv * mask).sum() / msum
                    var = (jnp.square(adv - mean) * mask).sum() / jnp.maximum(msum - 1, 1.0)
                    adv = (adv - mean) / (jnp.sqrt(var) + 1e-8)
                # the reference hardcodes 'mean' for the policy/value terms;
                # cfg.algo.loss_reduction only affects the entropy term
                # (reference train(), :82-101)
                pg = (policy_loss(logprobs, batch["logprobs"], adv, clip_coef, "none") * mask).sum() / msum
                v = (
                    value_loss(values, batch["values"], batch["returns"], clip_coef, clip_vloss, "none") * mask
                ).sum() / msum
                ent = (entropy_loss(entropy, "none") * mask).sum()
                if reduction == "mean":
                    ent = ent / msum
                return pg + vf_coef * v + ent_coef * ent, (pg, v, ent)

            (_, (pg, v, ent)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            if use_mesh:
                grads = lax.pmean(grads, data_axis)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), jnp.stack([pg, v, ent])

        def epoch_step(carry, _):
            params, opt_state, key = carry
            key, perm_key = jax.random.split(key)
            perm = jax.random.permutation(perm_key, n_local)[: num_batches * bs]
            minibatches = jax.tree.map(
                lambda x: jnp.moveaxis(
                    x[:, perm].reshape(x.shape[0], num_batches, bs, *x.shape[2:]), 1, 0
                ),
                data,
            )
            mb_h0 = hx0[perm].reshape(num_batches, bs, -1)
            mb_c0 = cx0[perm].reshape(num_batches, bs, -1)
            (params, opt_state), metrics = lax.scan(
                minibatch_step, (params, opt_state), (minibatches, mb_h0, mb_c0)
            )
            return (params, opt_state, key), metrics

        (params, opt_state, _), metrics = lax.scan(
            epoch_step, (params, opt_state, key), None, length=update_epochs
        )
        metrics = metrics.mean(axis=(0, 1))
        if use_mesh:
            metrics = lax.pmean(metrics, data_axis)
        return params, opt_state, metrics

    return local_train


def make_train_fn(fabric, agent, tx, cfg, obs_keys):
    """The host-path jitted update: :func:`make_local_train` ``shard_map``-ped
    over the data axis (sequences sharded, params/opt replicated, gradient
    ``pmean`` as the DDP all-reduce)."""
    data_axis = fabric.data_axis
    local_train = make_local_train(fabric, agent, tx, cfg, obs_keys, use_mesh=True)
    train_fn = shard_map(
        local_train,
        mesh=fabric.mesh,
        in_specs=(P(), P(), P(None, data_axis), P(data_axis), P(data_axis), P(), P(), P()),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(train_fn, donate_argnums=(0, 1))


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)

    if "minedojo" in str(cfg.env.wrapper.get("_target_", "")).lower():
        raise ValueError(
            "MineDojo is not currently supported by PPO Recurrent agent, since it does not take "
            "into consideration the action masks provided by the environment."
        )

    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")

    initial_clip_coef = float(cfg.algo.clip_coef)
    initial_ent_coef = float(cfg.algo.ent_coef)

    rank = fabric.process_index
    num_envs = int(cfg.env.num_envs)
    envs = build_vector_env(cfg, rank, log_dir if rank == 0 else None, "train")
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    if not obs_keys:
        raise RuntimeError(
            "You should specify at least one CNN key or MLP key from the cli: "
            "`algo.cnn_keys.encoder=[rgb]` or `algo.mlp_keys.encoder=[state]`"
        )

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )
    n_actions = int(np.sum(actions_dim))

    # scenario variants ride the fused rollout only (same contract as PPO);
    # `distractors` widens the observation the agent is built against
    # resolved unconditionally: enabled variants with the fused path off must
    # hit the loud RuntimeError below, never silently train the base env
    scenario_family = resolve_scenario_family(cfg)
    obs_widened = False
    if scenario_family is not None and not cnn_keys and len(mlp_keys) == 1:
        k0 = mlp_keys[0]
        if tuple(observation_space[k0].shape) != (scenario_family.obs_dim,):
            spaces_d = dict(observation_space.spaces)
            spaces_d[k0] = gym.spaces.Box(-np.inf, np.inf, (scenario_family.obs_dim,), np.float32)
            observation_space = gym.spaces.Dict(spaces_d)
            obs_widened = True

    agent, params = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["agent"] if cfg.checkpoint.resume_from else None,
    )
    from sheeprl_tpu.parallel.fabric import resolve_player_device

    player = RecurrentPPOPlayer(
        agent, params, device=resolve_player_device(cfg.algo.get("player_device", "auto"))
    )

    rollout_steps = int(cfg.algo.rollout_steps)
    seq_len = int(cfg.algo.per_rank_sequence_length)
    world_size = fabric.data_parallel_size  # batch-split width: the data axis (= device count on a 1-D mesh)
    policy_steps_per_update = num_envs * rollout_steps * fabric.num_processes
    num_updates = int(cfg.algo.total_steps) // policy_steps_per_update if not cfg.dry_run else 1
    pad_multiple = world_size * max(1, int(cfg.algo.per_rank_num_batches))

    opt_cfg = dict(cfg.algo.optimizer.to_dict() if hasattr(cfg.algo.optimizer, "to_dict") else cfg.algo.optimizer)
    if cfg.algo.max_grad_norm and float(cfg.algo.max_grad_norm) > 0:
        opt_cfg["max_grad_norm"] = float(cfg.algo.max_grad_norm)
    if cfg.algo.anneal_lr:
        steps_per_update = int(cfg.algo.update_epochs) * max(1, int(cfg.algo.per_rank_num_batches))
        opt_cfg["schedule"] = optax.linear_schedule(
            float(opt_cfg.get("lr", 1e-3)), 0.0, num_updates * steps_per_update
        )
    tx = instantiate(opt_cfg)
    opt_state = fabric.replicate(tx.init(jax.device_get(params)))
    if cfg.checkpoint.resume_from:
        opt_state = fabric.replicate(
            jax.tree.map(jnp.asarray, state["opt_state"], is_leaf=lambda x: isinstance(x, np.ndarray))
        )

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in AGGREGATOR_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    train_fn = make_train_fn(fabric, agent, tx, cfg, obs_keys)
    gae_fn = jax.jit(partial(gae, gamma=float(cfg.algo.gamma), gae_lambda=float(cfg.algo.gae_lambda)))

    # fused on-policy collection (`algo.fused_rollout`, ported from PPO): the
    # T-step rollout — LSTM state carried through the scan — plus GAE and the
    # whole epochs x minibatches update compile into ONE donated jit
    num_batches = max(1, int(cfg.algo.per_rank_num_batches))
    fused_rollout = bool(cfg.algo.get("fused_rollout", False))
    reset_fused_fallback_warnings()
    fused_spec = None
    if fused_rollout:
        fused_spec = resolve_fused_rollout_spec(
            cfg, fabric, cnn_keys, mlp_keys, observation_space, is_continuous, is_multidiscrete, actions_dim
        )
        if fused_spec is not None and rollout_steps % seq_len != 0:
            fused_fallback(
                "recurrent_seq",
                f"algo.rollout_steps ({rollout_steps}) must be a multiple of "
                f"per_rank_sequence_length ({seq_len}) for fixed-window fused sequences",
            )
            fused_spec = None
        if fused_spec is not None and num_envs % world_size != 0:
            fused_fallback(
                "env_shard", f"env.num_envs ({num_envs}) must be divisible by the device count ({world_size})"
            )
            fused_spec = None
        if fused_spec is not None:
            n_seq_local = (rollout_steps // seq_len) * (num_envs // world_size)
            if n_seq_local % num_batches != 0:
                # the in-graph minibatch permutation truncates to
                # num_batches * bs — an indivisible count would drop sequences
                fused_fallback(
                    "sequence_batches",
                    f"per-shard sequence count ({n_seq_local}) must be divisible by "
                    f"per_rank_num_batches ({num_batches})",
                )
                fused_spec = None
    if scenario_family is not None and fused_spec is None:
        raise RuntimeError(
            "env.variants requires the fused rollout path; set "
            "algo.fused_rollout=True (if it is set, the fused_fallback "
            "telemetry event names the gate that failed)"
        )
    superstep_fn = None
    if fused_spec is not None:
        superstep_fn = make_recurrent_onpolicy_superstep_fn(
            fused_spec,
            policy_fn=partial(recurrent_rollout_step, agent),
            value_fn=lambda p, o, pa, hx, cx: agent.apply(p, o, pa, hx, cx)[1],
            local_train=make_local_train(
                fabric, agent, tx, cfg, obs_keys, use_mesh=True, sequence_dones=True
            ),
            obs_key=mlp_keys[0],
            rollout_steps=rollout_steps,
            seq_len=seq_len,
            step_increment=num_envs * fabric.num_processes,
            gamma=float(cfg.algo.gamma),
            gae_lambda=float(cfg.algo.gae_lambda),
            reset_on_done=bool(cfg.algo.reset_recurrent_state_on_done),
            mesh=fabric.mesh,
            data_axis=fabric.data_axis,
        )

    start_update = (state["update"] + 1) if cfg.checkpoint.resume_from else 1
    policy_step = state["update"] * policy_steps_per_update if cfg.checkpoint.resume_from else 0
    last_log = state["last_log"] if cfg.checkpoint.resume_from else 0
    last_checkpoint = state["last_checkpoint"] if cfg.checkpoint.resume_from else 0
    train_step = 0
    last_train = 0

    key = jax.random.PRNGKey(int(cfg.seed))
    if cfg.checkpoint.resume_from and "rng_key" in state:
        key = jnp.asarray(state["rng_key"])
    # action keys live on the player's device so a host-pinned player
    # never blocks on a chip round trip per env step
    from sheeprl_tpu.parallel.fabric import put_tree as _put_tree

    player_key = _put_tree(jax.random.fold_in(key, 1), player.device)
    if cfg.checkpoint.resume_from and "player_rng_key" in state:
        # continue the pre-resume action-sampling stream
        player_key = _put_tree(jnp.asarray(state["player_rng_key"]), player.device)

    clip_coef = float(cfg.algo.clip_coef)
    ent_coef = float(cfg.algo.ent_coef)
    reset_on_done = bool(cfg.algo.reset_recurrent_state_on_done)

    next_obs, _ = envs.reset(seed=cfg.seed)
    next_obs = prepare_obs(next_obs, cnn_keys=cnn_keys, num_envs=num_envs)
    hx = np.zeros((num_envs, agent.lstm_hidden_size), np.float32)
    cx = np.zeros((num_envs, agent.lstm_hidden_size), np.float32)
    prev_actions = np.zeros((num_envs, n_actions), np.float32)

    steps_per_dispatch = int(cfg.algo.update_epochs) * num_batches
    if superstep_fn is not None:
        # ------------------------------------------------------------------
        # fused on-policy path: the rollout (LSTM carry riding the scan),
        # GAE, sequence windowing and the epochs x minibatches update are ONE
        # donated jit; the metrics fetch is the only host sync per update
        # ------------------------------------------------------------------
        def place_carry(carry):
            return jax.tree.map(lambda x: jax.device_put(x, fabric.batch_sharding), carry)

        key = jax.device_put(key, fabric.replicated)
        # one scenario row per env for the run's lifetime (PPO's contract)
        thetas = (
            scenario_theta_matrix(cfg, fused_spec, num_envs)
            if isinstance(fused_spec, ScenarioFamily)
            else None
        )
        env_carry = place_carry(
            init_recurrent_env_carry(
                fused_spec,
                num_envs,
                jax.random.fold_in(jax.random.PRNGKey(int(cfg.seed)), ENV_STREAM_SALT),
                hidden_size=agent.lstm_hidden_size,
                action_dim=n_actions,
                thetas=thetas,
            )
        )
        for update in range(start_update, num_updates + 1):
            telemetry_advance(policy_step)
            if update == start_update + 1:
                # no bench probe in this loop — warm the recompile watchdog here
                telemetry_mark_warm()
            # rollout_actions' fold schedule on top of a per-update key — the
            # same in-graph discipline as the fused PPO loop
            update_key = jax.random.fold_in(player_key, update)
            step_before = policy_step
            with timer("Time/env_interaction_time"):
                params, opt_state, env_carry, key, metrics, ep_stats = superstep_fn(
                    params,
                    opt_state,
                    env_carry,
                    update_key,
                    key,
                    np.uint32(step_before),
                    # host numpy scalars — jnp.float32 would materialize them
                    # on the default backend every update (see ppo.py)
                    np.float32(clip_coef),
                    np.float32(ent_coef),
                )
                policy_step += policy_steps_per_update
                metrics = np.asarray(metrics)
            telemetry_train_window(1, steps_per_dispatch)
            train_step += world_size
            if update == start_update:
                # one dispatch covers collection AND all gradient steps, so
                # scale the program flops down to per-gradient-step for MFU
                telemetry_register_flops(
                    superstep_fn,
                    params,
                    opt_state,
                    env_carry,
                    update_key,
                    key,
                    np.uint32(step_before),
                    np.float32(clip_coef),
                    np.float32(ent_coef),
                    scale=1.0 / steps_per_dispatch,
                )
            if cfg.metric.log_level > 0:
                # one fetch of the per-step episode flags replaces the host
                # loop's final_info plumbing
                ep_done = np.asarray(ep_stats["done"])
                finished = np.nonzero(ep_done)
                if finished[0].size:
                    finished_rets = np.asarray(ep_stats["ret"])[finished]
                    for r in finished_rets:
                        aggregator.update("Rewards/rew_avg", float(r))
                    for length in np.asarray(ep_stats["len"])[finished]:
                        aggregator.update("Game/ep_len_avg", float(length))
                    # same per-episode evidence lines as the host loop — the
                    # learning-check recipes (benchmarks/learning_checks.sh,
                    # tools/sweep.py) grep these for the reward trend
                    for i, r in zip(finished[-1], finished_rets):
                        print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={float(r)}")
                aggregator.update("Loss/policy_loss", float(metrics[0]))
                aggregator.update("Loss/value_loss", float(metrics[1]))
                aggregator.update("Loss/entropy_loss", float(metrics[2]))
                if policy_step - last_log >= cfg.metric.log_every or update == num_updates:
                    metrics_dict = aggregator.compute()
                    logger.log_metrics(metrics_dict, policy_step)
                    telemetry_run_metrics(metrics_dict)
                    aggregator.reset()
                    log_sps_and_heartbeat(
                        logger,
                        policy_step=policy_step,
                        env_steps=(policy_step - last_log) * cfg.env.action_repeat,
                        train_steps=train_step - last_train,
                        train_invocations=(train_step - last_train) // world_size,
                    )
                    last_log = policy_step
                    last_train = train_step
            if cfg.algo.anneal_clip_coef:
                clip_coef = polynomial_decay(
                    update, initial=initial_clip_coef, final=0.0, max_decay_steps=num_updates, power=1.0
                )
            if cfg.algo.anneal_ent_coef:
                ent_coef = polynomial_decay(
                    update, initial=initial_ent_coef, final=0.0, max_decay_steps=num_updates, power=1.0
                )
            if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
                update == num_updates and cfg.checkpoint.save_last
            ):
                last_checkpoint = policy_step
                ckpt_state = {
                    "agent": jax.device_get(params),
                    "opt_state": jax.device_get(opt_state),
                    "update": update,
                    "batch_size": int(cfg.algo.per_rank_batch_size) * world_size,
                    "last_log": last_log,
                    "last_checkpoint": last_checkpoint,
                    "rng_key": jax.device_get(key),
                    "player_rng_key": jax.device_get(player_key),
                }
                ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
                fabric.call("on_checkpoint_coupled", ckpt_path=ckpt_path, state=ckpt_state)
        # the player sampled nothing during the fused loop; publish the final
        # params once for the eval rollout below
        player.update_params(params)
    else:
        # rollout arrays preallocated once and written in place — no per-step
        # list appends (or the defensive hx/cx/prev_actions .copy()s: the indexed
        # write is itself the copy), no end-of-window np.stack
        store = RolloutStore(rollout_steps)
        for update in range(start_update, num_updates + 1):
            buf = store.begin(update)
            with timer("Time/env_interaction_time"):
                # fused rollout step: key folding, sampling and the real-action
                # conversion in one jitted dispatch + one fetch per env step
                update_key = player_key
                for t in range(rollout_steps):
                    policy_step += num_envs * fabric.num_processes
                    obs_t = {k: v[None] for k, v in next_obs.items()}
                    actions, real_actions, logprobs, values, new_hx, new_cx = player.rollout_actions(
                        obs_t, prev_actions[None], hx, cx, update_key, policy_step
                    )
                    actions_np, real_actions, logprobs_np, values_np, new_hx, new_cx = jax.device_get(
                        (actions, real_actions, logprobs, values, new_hx, new_cx)
                    )
                    actions_np = actions_np[0]
                    logprobs_np = logprobs_np[0]
                    values_np = values_np[0]
                    real_actions = real_actions[0]
                    if not is_continuous and real_actions.shape[-1] == 1 and not is_multidiscrete:
                        real_actions = real_actions[..., 0]

                    obs, rewards, terminated, truncated, info = envs.step(
                        real_actions.reshape(envs.action_space.shape)
                    )
                    rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, 1)

                    # truncation bootstrap with the POST-step recurrent state
                    # (reference :312-336)
                    truncated_envs = np.nonzero(truncated)[0]
                    if len(truncated_envs) > 0 and "final_obs" in info:
                        final_obs = {
                            k: np.stack([np.asarray(info["final_obs"][e][k]) for e in truncated_envs])
                            for k in obs_keys
                        }
                        final_obs = prepare_obs(final_obs, cnn_keys=cnn_keys, num_envs=len(truncated_envs))
                        vals = np.asarray(
                            player.get_values(
                                {k: v[None] for k, v in final_obs.items()},
                                actions_np[truncated_envs][None],
                                new_hx[truncated_envs],
                                new_cx[truncated_envs],
                            )
                        ).reshape(len(truncated_envs))
                        rewards[truncated_envs, 0] += float(cfg.algo.gamma) * vals

                    dones = np.logical_or(terminated, truncated).reshape(num_envs, 1).astype(np.float32)
                    step_values = {k: next_obs[k] for k in obs_keys}
                    step_values["dones"] = dones
                    step_values["values"] = values_np
                    step_values["actions"] = actions_np
                    step_values["logprobs"] = logprobs_np
                    step_values["rewards"] = rewards
                    step_values["prev_hx"] = hx
                    step_values["prev_cx"] = cx
                    step_values["prev_actions"] = prev_actions
                    buf.put(t, step_values)

                    prev_actions = (1 - dones) * actions_np
                    if reset_on_done:
                        hx = (1 - dones) * new_hx
                        cx = (1 - dones) * new_cx
                    else:
                        hx, cx = new_hx, new_cx
                    next_obs = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=num_envs)

                    if cfg.metric.log_level > 0 and "final_info" in info:
                        ep = info["final_info"].get("episode")
                        if ep is not None:
                            for i in np.nonzero(ep.get("_r", []))[0]:
                                aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                                aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                                print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

            local_data = buf.arrays()  # [T, E, ...]

            # GAE on device (reference :386-398)
            next_values = np.asarray(
                player.get_values({k: v[None] for k, v in next_obs.items()}, prev_actions[None], hx, cx)
            )[0]
            returns, advantages = gae_fn(
                jnp.asarray(local_data["rewards"]),
                jnp.asarray(local_data["values"]),
                jnp.asarray(local_data["dones"]),
                jnp.asarray(next_values),
            )
            local_data["returns"] = np.asarray(returns)
            local_data["advantages"] = np.asarray(advantages)

            # episode split + fixed-length chunking + padding (reference :406-444)
            train_keys = [*obs_keys, "actions", "logprobs", "values", "returns", "advantages", "prev_actions"]
            sequences = build_sequences(local_data, train_keys, seq_len, num_envs, pad_multiple)
            hx0 = sequences.pop("hx0")
            cx0 = sequences.pop("cx0")
            if fabric.num_processes > 1:
                # every process must contribute the SAME padded count to the
                # global array — agree on the max and pad with masked dummies
                from sheeprl_tpu.parallel.collectives import all_gather_object

                n_here = sequences["mask"].shape[1]
                n_target = max(all_gather_object(n_here))
                if n_here < n_target:
                    extra = n_target - n_here
                    sequences = {
                        k: np.concatenate(
                            [v, np.zeros((v.shape[0], extra, *v.shape[2:]), v.dtype)], axis=1
                        )
                        for k, v in sequences.items()
                    }
                    hx0 = np.concatenate([hx0, np.zeros((extra, hx0.shape[1]), hx0.dtype)], axis=0)
                    cx0 = np.concatenate([cx0, np.zeros((extra, cx0.shape[1]), cx0.dtype)], axis=0)
                sequences = fabric.make_global(sequences, (None, fabric.data_axis))
                hx0 = fabric.make_global(hx0, (fabric.data_axis,))
                cx0 = fabric.make_global(cx0, (fabric.data_axis,))

            with timer("Time/train_time"):
                key, train_key = jax.random.split(key)
                params, opt_state, metrics = train_fn(
                    params,
                    opt_state,
                    sequences,
                    hx0,
                    cx0,
                    train_key,
                    # host numpy scalars — jnp.float32 would materialize them on
                    # the default backend every update (see ppo.py)
                    np.float32(clip_coef),
                    np.float32(ent_coef),
                )
                # one host fetch serves the sync point and the three aggregator
                # scalars below — block_until_ready plus a second asarray (or a
                # blocking transfer per float()) would each be an extra round trip
                metrics = np.asarray(metrics)
            player.params = params
            train_step += world_size

            if cfg.metric.log_level > 0:
                aggregator.update("Loss/policy_loss", float(metrics[0]))
                aggregator.update("Loss/value_loss", float(metrics[1]))
                aggregator.update("Loss/entropy_loss", float(metrics[2]))

                if policy_step - last_log >= cfg.metric.log_every or update == num_updates:
                    metrics_dict = aggregator.compute()
                    logger.log_metrics(metrics_dict, policy_step)
                    aggregator.reset()
                    if not timer.disabled:
                        timer_metrics = timer.compute()
                        if timer_metrics.get("Time/train_time"):
                            logger.log_metrics(
                                {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                                policy_step,
                            )
                        if timer_metrics.get("Time/env_interaction_time"):
                            logger.log_metrics(
                                {
                                    "Time/sps_env_interaction": (
                                        (policy_step - last_log) * cfg.env.action_repeat
                                    )
                                    / timer_metrics["Time/env_interaction_time"]
                                },
                                policy_step,
                            )
                        timer.reset()
                    last_log = policy_step
                    last_train = train_step

            if cfg.algo.anneal_clip_coef:
                clip_coef = polynomial_decay(
                    update, initial=initial_clip_coef, final=0.0, max_decay_steps=num_updates, power=1.0
                )
            if cfg.algo.anneal_ent_coef:
                ent_coef = polynomial_decay(
                    update, initial=initial_ent_coef, final=0.0, max_decay_steps=num_updates, power=1.0
                )

            if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
                update == num_updates and cfg.checkpoint.save_last
            ):
                last_checkpoint = policy_step
                ckpt_state = {
                    "agent": jax.device_get(params),
                    "opt_state": jax.device_get(opt_state),
                    "update": update,
                    "batch_size": int(cfg.algo.per_rank_batch_size) * world_size,
                    "last_log": last_log,
                    "last_checkpoint": last_checkpoint,
                    "rng_key": jax.device_get(key),
                    "player_rng_key": jax.device_get(player_key),
                }
                ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
                fabric.call("on_checkpoint_coupled", ckpt_path=ckpt_path, state=ckpt_state)

    envs.close()
    if fabric.is_global_zero and cfg.algo.run_test:
        if obs_widened:
            import warnings

            warnings.warn("skipping run_test: env.variants widened the observation past the host env's")
        else:
            test(player, fabric, cfg, log_dir)
    logger.finalize()
