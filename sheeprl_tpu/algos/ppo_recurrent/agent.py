"""Recurrent PPO agent (reference: sheeprl/algos/ppo_recurrent/agent.py:18-262).

flax re-design: the LSTM time loop is a ``nn.scan``-lifted
``OptimizedLSTMCell`` — one fused XLA while-loop over the sequence instead of
cuDNN packed sequences. Padded positions are handled by masking the LOSSES
(the reference's ``pack_padded_sequence`` only skips compute; sequences are
independent, so states at padded tails are never consumed).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.agent import CNNEncoder, MLPEncoder, real_actions_from_onehot
from sheeprl_tpu.models import MLP
from sheeprl_tpu.ops.distributions import Categorical, Independent, Normal
from sheeprl_tpu.parallel.fabric import HostPlayerParams, put_tree

Array = jax.Array


class RecurrentPPOAgent(nn.Module):
    """Encoder -> (pre-MLP) -> LSTM -> (post-MLP) -> actor heads + critic
    (reference RecurrentPPOAgent, agent.py:85-262). ``__call__`` consumes a
    time-major ``[T, B]`` batch plus the initial LSTM state and returns raw
    actor head outputs, values, and the final state."""

    actions_dim: Tuple[int, ...]
    is_continuous: bool
    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    cnn_features_dim: int = 512
    mlp_features_dim: Optional[int] = 64
    encoder_units: int = 64
    encoder_layers: int = 1
    lstm_hidden_size: int = 64
    pre_rnn_apply: bool = False
    pre_rnn_units: int = 64
    pre_rnn_layer_norm: bool = True
    post_rnn_apply: bool = False
    post_rnn_units: int = 64
    post_rnn_layer_norm: bool = True
    actor_units: int = 64
    actor_layers: int = 1
    critic_units: int = 64
    critic_layers: int = 1
    dense_act: str = "relu"
    layer_norm: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(
        self,
        obs: Dict[str, Array],  # [T, B, ...]
        prev_actions: Array,  # [T, B, A]
        hx: Array,  # [B, H]
        cx: Array,  # [B, H]
    ) -> Tuple[List[Array], Array, Tuple[Array, Array]]:
        T, B = prev_actions.shape[:2]
        feats = []
        if self.cnn_keys:
            flat = {k: obs[k].reshape(T * B, *obs[k].shape[2:]) for k in self.cnn_keys}
            cnn_feat = CNNEncoder(self.cnn_keys, self.cnn_features_dim, dtype=self.dtype)(flat)
            feats.append(cnn_feat.reshape(T, B, -1))
        if self.mlp_keys:
            feats.append(
                MLPEncoder(
                    self.mlp_keys,
                    self.mlp_features_dim,
                    self.encoder_units,
                    self.encoder_layers,
                    self.dense_act,
                    self.layer_norm,
                    dtype=self.dtype,
                )(obs)
            )
        feat = feats[0] if len(feats) == 1 else jnp.concatenate(feats, axis=-1)
        x = jnp.concatenate([feat, prev_actions.astype(feat.dtype)], axis=-1)

        if self.pre_rnn_apply:
            x = MLP(
                hidden_sizes=(self.pre_rnn_units,),
                output_dim=None,
                activation=self.dense_act,
                norm_layer="layer_norm" if self.pre_rnn_layer_norm else None,
                dtype=self.dtype,
                name="pre_rnn_mlp",
            )(x)

        # LSTM over time as one fused scan (reference RecurrentModel._lstm)
        ScanLSTM = nn.scan(
            nn.OptimizedLSTMCell,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            out_axes=0,
        )
        carry = (cx.astype(self.dtype), hx.astype(self.dtype))
        carry, out = ScanLSTM(self.lstm_hidden_size, dtype=self.dtype, param_dtype=jnp.float32)(
            carry, x.astype(self.dtype)
        )
        new_cx, new_hx = carry

        if self.post_rnn_apply:
            out = MLP(
                hidden_sizes=(self.post_rnn_units,),
                output_dim=None,
                activation=self.dense_act,
                norm_layer="layer_norm" if self.post_rnn_layer_norm else None,
                dtype=self.dtype,
                name="post_rnn_mlp",
            )(out)

        values = MLP(
            hidden_sizes=(self.critic_units,) * self.critic_layers,
            output_dim=1,
            activation=self.dense_act,
            norm_layer="layer_norm" if self.layer_norm else None,
            dtype=self.dtype,
            name="critic",
        )(out)

        a = MLP(
            hidden_sizes=(self.actor_units,) * self.actor_layers,
            output_dim=None,
            activation=self.dense_act,
            norm_layer="layer_norm" if self.layer_norm else None,
            dtype=self.dtype,
            name="actor_backbone",
        )(out)
        if self.is_continuous:
            heads = [nn.Dense(sum(self.actions_dim) * 2, dtype=self.dtype, name="actor_head_0")(a)]
        else:
            heads = [nn.Dense(d, dtype=self.dtype, name=f"actor_head_{i}")(a) for i, d in enumerate(self.actions_dim)]
        return heads, values.astype(jnp.float32), (new_hx.astype(jnp.float32), new_cx.astype(jnp.float32))


def _dists(agent: RecurrentPPOAgent, actor_out: List[Array]):
    if agent.is_continuous:
        mean, log_std = jnp.split(actor_out[0].astype(jnp.float32), 2, axis=-1)
        return [Independent(Normal(mean, jnp.exp(log_std)), 1)]
    return [Categorical(logits=h.astype(jnp.float32)) for h in actor_out]


def sample_actions(
    agent: RecurrentPPOAgent,
    params: Any,
    obs: Dict[str, Array],  # [1, B, ...]
    prev_actions: Array,  # [1, B, A]
    hx: Array,
    cx: Array,
    key: Array,
    greedy: bool = False,
) -> Tuple[Array, Array, Array, Array, Array]:
    """Rollout-time policy (reference agent.py forward at play). Returns
    ``(actions, logprobs, values, hx', cx')`` with the concatenated
    one-hot/raw action layout of the buffer."""
    actor_out, values, (new_hx, new_cx) = agent.apply(params, obs, prev_actions, hx, cx)
    dists = _dists(agent, actor_out)
    keys = jax.random.split(key, len(dists))
    if agent.is_continuous:
        d = dists[0]
        act = d.mode if greedy else d.sample(seed=keys[0])
        logprob = d.log_prob(act)[..., None]
        return act, logprob, values, new_hx, new_cx
    samples = [(d.mode if greedy else d.sample(seed=k)) for d, k in zip(dists, keys)]
    logprob = sum(d.log_prob(s) for d, s in zip(dists, samples))[..., None]
    onehots = [jax.nn.one_hot(s, dim, dtype=jnp.float32) for s, dim in zip(samples, agent.actions_dim)]
    return jnp.concatenate(onehots, axis=-1), logprob, values, new_hx, new_cx


def evaluate_actions(
    agent: RecurrentPPOAgent,
    params: Any,
    obs: Dict[str, Array],  # [L, N, ...]
    prev_actions: Array,  # [L, N, A]
    hx0: Array,  # [N, H]
    cx0: Array,  # [N, H]
    actions: Array,  # [L, N, A]
) -> Tuple[Array, Array, Array]:
    """Train-time re-evaluation of stored sequences (reference train(),
    ppo_recurrent.py:69-75). Returns ``(logprobs, entropy, values)``, each
    ``[L, N, 1]`` — the caller masks the padded tail."""
    actor_out, values, _ = agent.apply(params, obs, prev_actions, hx0, cx0)
    dists = _dists(agent, actor_out)
    if agent.is_continuous:
        d = dists[0]
        return d.log_prob(actions)[..., None], d.entropy()[..., None], values
    splits = np.cumsum(agent.actions_dim)[:-1]
    onehot_parts = jnp.split(actions, splits, axis=-1)
    idx_parts = [jnp.argmax(p, axis=-1) for p in onehot_parts]
    logprob = sum(d.log_prob(i) for d, i in zip(dists, idx_parts))[..., None]
    entropy = sum(d.entropy() for d in dists)[..., None]
    return logprob, entropy, values


def evaluate_actions_resettable(
    agent: RecurrentPPOAgent,
    params: Any,
    obs: Dict[str, Array],  # [L, N, ...]
    prev_actions: Array,  # [L, N, A]
    hx0: Array,  # [N, H]
    cx0: Array,  # [N, H]
    actions: Array,  # [L, N, A]
    dones: Array,  # [L, N, 1]
    *,
    reset_on_done: bool = True,
) -> Tuple[Array, Array, Array]:
    """:func:`evaluate_actions` for sequences that may CROSS episode
    boundaries (the fused rollout's fixed windows): the LSTM carry is zeroed
    after every stored done, replaying ``reset_recurrent_state_on_done``
    rollouts state-for-state.  The time loop is a ``lax.scan`` of
    single-step ``agent.apply`` calls — same params, same module — with the
    reset applied between steps."""

    def step(carry, xs):
        hx, cx = carry
        obs_t, pa_t, done_t = xs
        actor_out, values, (new_hx, new_cx) = agent.apply(
            params, {k: v[None] for k, v in obs_t.items()}, pa_t[None], hx, cx
        )
        if reset_on_done:
            keep = 1.0 - done_t
            new_hx = keep * new_hx
            new_cx = keep * new_cx
        return (new_hx, new_cx), (tuple(h[0] for h in actor_out), values[0])

    _, (heads, values) = jax.lax.scan(step, (hx0, cx0), (obs, prev_actions, dones))
    dists = _dists(agent, list(heads))
    if agent.is_continuous:
        d = dists[0]
        return d.log_prob(actions)[..., None], d.entropy()[..., None], values
    splits = np.cumsum(agent.actions_dim)[:-1]
    onehot_parts = jnp.split(actions, splits, axis=-1)
    idx_parts = [jnp.argmax(p, axis=-1) for p in onehot_parts]
    logprob = sum(d.log_prob(i) for d, i in zip(dists, idx_parts))[..., None]
    entropy = sum(d.entropy() for d in dists)[..., None]
    return logprob, entropy, values


def recurrent_rollout_step(
    agent: RecurrentPPOAgent,
    params: Any,
    obs: Dict[str, Array],  # [1, E, ...]
    prev_actions: Array,  # [1, E, A]
    hx: Array,
    cx: Array,
    key: Array,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """The fused-rollout policy head (``ops/rollout_scan.py``'s recurrent
    ``policy_fn``): sampling plus the one-hot -> env-action conversion of
    ``RecurrentPPOPlayer.rollout_actions``, minus its key fold (the superstep
    folds the counter in-graph)."""
    actions, logprob, values, new_hx, new_cx = sample_actions(
        agent, params, obs, prev_actions, hx, cx, key
    )
    real = real_actions_from_onehot(agent.actions_dim, agent.is_continuous, actions)
    return actions, real, logprob, values, new_hx, new_cx


class RecurrentPPOPlayer(HostPlayerParams):
    """Host-side rollout handle: params + jitted single-step functions; the
    caller owns the recurrent state (reference player usage,
    ppo_recurrent.py:283-371).

    ``device`` optionally pins inference to the host CPU backend
    (see ``parallel.fabric.resolve_player_device``)."""

    _placed_attrs = ("params",)

    def __init__(self, agent: RecurrentPPOAgent, params: Any, device: Optional[Any] = None) -> None:
        self.agent = agent
        self.device = device  # must precede the params assignment
        self.params = params
        self._sample = jax.jit(
            lambda p, o, pa, hx, cx, k, greedy: sample_actions(agent, p, o, pa, hx, cx, k, greedy),
            static_argnames="greedy",
        )
        self._values = jax.jit(lambda p, o, pa, hx, cx: agent.apply(p, o, pa, hx, cx)[1])

        def fused(p, o, pa, hx, cx, k, c):
            actions, logprob, values, new_hx, new_cx = sample_actions(
                agent, p, o, pa, hx, cx, jax.random.fold_in(k, c)
            )
            real = real_actions_from_onehot(agent.actions_dim, agent.is_continuous, actions)
            return actions, real, logprob, values, new_hx, new_cx

        # fused rollout step, same rationale as ppo.agent.rollout_step
        self._rollout = jax.jit(fused)

    def update_params(self, params: Any) -> None:
        self.params = params

    def get_actions(self, obs, prev_actions, hx, cx, key, greedy: bool = False):
        return self._sample(self.params, obs, prev_actions, hx, cx, put_tree(key, self.device), greedy)

    def rollout_actions(self, obs, prev_actions, hx, cx, key, counter):
        """Fused rollout step (same rationale as ``ppo.agent.rollout_step``):
        key folding by counter, sampling, and the one-hot→index conversion in
        one jitted dispatch. Returns
        ``(actions, real_actions, logprobs, values, hx, cx)``."""
        return self._rollout(self.params, obs, prev_actions, hx, cx, key, counter)

    def get_values(self, obs, prev_actions, hx, cx) -> Array:
        return self._values(self.params, obs, prev_actions, hx, cx)


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    agent_state: Optional[Any] = None,
) -> Tuple[RecurrentPPOAgent, Any]:
    """Construct the module and init/replicate its params
    (reference build_agent, agent.py:265-300)."""
    algo = cfg["algo"]
    rnn = algo["rnn"]
    agent = RecurrentPPOAgent(
        actions_dim=tuple(int(d) for d in actions_dim),
        is_continuous=bool(is_continuous),
        cnn_keys=tuple(algo["cnn_keys"]["encoder"]),
        mlp_keys=tuple(algo["mlp_keys"]["encoder"]),
        cnn_features_dim=int(algo["encoder"]["cnn_features_dim"]),
        mlp_features_dim=algo["encoder"]["mlp_features_dim"],
        encoder_units=int(algo["encoder"]["dense_units"]),
        encoder_layers=int(algo["encoder"]["mlp_layers"]),
        lstm_hidden_size=int(rnn["lstm"]["hidden_size"]),
        pre_rnn_apply=bool(rnn["pre_rnn_mlp"]["apply"]),
        pre_rnn_units=int(rnn["pre_rnn_mlp"]["dense_units"]),
        pre_rnn_layer_norm=bool(rnn["pre_rnn_mlp"]["layer_norm"]),
        post_rnn_apply=bool(rnn["post_rnn_mlp"]["apply"]),
        post_rnn_units=int(rnn["post_rnn_mlp"]["dense_units"]),
        post_rnn_layer_norm=bool(rnn["post_rnn_mlp"]["layer_norm"]),
        actor_units=int(algo["actor"]["dense_units"]),
        actor_layers=int(algo["actor"]["mlp_layers"]),
        critic_units=int(algo["critic"]["dense_units"]),
        critic_layers=int(algo["critic"]["mlp_layers"]),
        dense_act=str(algo["dense_act"]),
        layer_norm=bool(algo["layer_norm"]),
        dtype=fabric.precision.compute_dtype,
    )
    if agent_state is not None:
        params = jax.tree.map(jnp.asarray, agent_state)
    else:
        dummy_obs = {}
        for k in agent.cnn_keys:
            shape = obs_space[k].shape
            if len(shape) == 4:
                s, h, w, c = shape
                shape = (h, w, s * c)
            dummy_obs[k] = jnp.zeros((1, 1, *shape), dtype=jnp.uint8)
        for k in agent.mlp_keys:
            dummy_obs[k] = jnp.zeros((1, 1, *obs_space[k].shape), dtype=jnp.float32)
        prev_actions = jnp.zeros((1, 1, int(np.sum(actions_dim))), jnp.float32)
        h0 = jnp.zeros((1, agent.lstm_hidden_size), jnp.float32)
        params = agent.init(jax.random.PRNGKey(int(cfg["seed"])), dummy_obs, prev_actions, h0, h0)
    params = jax.tree.map(lambda x: x.astype(fabric.precision.param_dtype), params)
    return agent, fabric.replicate(params)
