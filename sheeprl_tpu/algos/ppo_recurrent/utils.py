"""Recurrent PPO helpers (reference: sheeprl/algos/ppo_recurrent/utils.py)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.obs.telemetry import telemetry_deliberate_compiles
import jax
import numpy as np

from sheeprl_tpu.algos.ppo.utils import prepare_obs  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
}
MODELS_TO_REGISTER = {"agent"}


# the eval rollout compiles fresh programs (eval batch shapes) after the
# loop's warm point; that is a deliberate one-time compile, not a retrace
@telemetry_deliberate_compiles("eval_rollout")
def test(player: Any, fabric: Any, cfg: Dict[str, Any], log_dir: str) -> None:
    """Greedy evaluation episode threading the recurrent state
    (reference ppo_recurrent/utils.py test)."""
    from sheeprl_tpu.envs import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    agent = player.agent
    done = False
    cumulative_rew = 0.0
    key = jax.random.PRNGKey(cfg.seed)
    obs, _ = env.reset(seed=cfg.seed)
    hx = np.zeros((1, agent.lstm_hidden_size), np.float32)
    cx = np.zeros((1, agent.lstm_hidden_size), np.float32)
    prev_actions = np.zeros((1, int(np.sum(agent.actions_dim))), np.float32)
    while not done:
        key, sub = jax.random.split(key)
        torch_obs = prepare_obs(obs, cnn_keys=cfg.algo.cnn_keys.encoder)
        obs_t = {k: v[None] for k, v in torch_obs.items()}
        actions, _, _, hx, cx = player.get_actions(obs_t, prev_actions[None], hx, cx, sub, greedy=True)
        actions, hx, cx = jax.device_get((actions, hx, cx))
        actions = np.asarray(actions)[0]
        prev_actions = actions
        if agent.is_continuous:
            real_actions = actions[0]
        else:
            splits = np.cumsum(agent.actions_dim)[:-1]
            real_actions = np.array([p.argmax(-1) for p in np.split(actions[0], splits, axis=-1)])
            if len(real_actions) == 1:
                real_actions = real_actions[0]
        obs, reward, terminated, truncated, _ = env.step(real_actions)
        done = terminated or truncated or cfg.dry_run
        cumulative_rew += float(reward)
    fabric_print = getattr(fabric, "print", print)
    fabric_print(f"Test - Reward: {cumulative_rew}")
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()


def log_models_from_checkpoint(fabric, cfg, state, artifacts_dir):
    """Pickle this algorithm's registered sub-models from a checkpoint
    (reference per-algo log_models_from_checkpoint; shared body in
    utils/model_manager.py)."""
    from sheeprl_tpu.utils.model_manager import log_models_from_checkpoint as _log

    return _log(state, sorted(MODELS_TO_REGISTER), artifacts_dir)
