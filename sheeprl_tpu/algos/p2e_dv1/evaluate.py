"""P2E DV1 evaluation entrypoint (reference: sheeprl/algos/p2e_dv1/evaluate.py).

Evaluates the TASK actor of either phase's checkpoint (reference
evaluate.py:29-56)."""

from __future__ import annotations

from typing import Any, Dict

from sheeprl_tpu.algos.dreamer_v1.agent import build_agent as dv1_build_agent
from sheeprl_tpu.algos.p2e_dv1.utils import test
from sheeprl_tpu.utils.evaluation import dreamer_family_evaluate
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["p2e_dv1_exploration", "p2e_dv1_finetuning"])
def evaluate(fabric, cfg: Dict[str, Any], state: Dict[str, Any]) -> None:
    dreamer_family_evaluate(
        fabric, cfg, state, dv1_build_agent, test,
        state_keys=("world_model", "actor_task", "critic_task"),
    )
