from sheeprl_tpu.algos.p2e_dv1 import p2e_dv1_exploration, p2e_dv1_finetuning, evaluate  # noqa: F401
