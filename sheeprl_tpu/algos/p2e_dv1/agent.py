"""Plan2Explore on Dreamer-V1 — agent builders (reference:
sheeprl/algos/p2e_dv1/agent.py:27-155).

The ensemble is ONE vmapped param tree (N stacked member MLP trees) predicting
the next *embedded observation* from (z, h, action) (reference
agent.py:125-140 — V1 measures disagreement in embedding space, unlike
V2/V3's posterior space). One exploration critic (Normal head, no target)
plus an exploration actor sharing the DV2-style Actor module.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v1.agent import (
    ActorDV1,
    CriticDV1,
    PlayerDV1,
    WorldModelDV1,
    build_agent as dv1_build_agent,
)
from sheeprl_tpu.algos.dreamer_v2.agent import _dense, _MLPBlock

Array = jax.Array


class EnsembleDV1(nn.Module):
    """One ensemble member: MLP from (z, h, action) to the embedding size
    (reference agent.py:125-140)."""

    output_dim: int
    mlp_layers: int = 4
    dense_units: int = 400
    act: str = "elu"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = _MLPBlock(self.mlp_layers, self.dense_units, self.act, False, self.dtype)(x.astype(self.dtype))
        return _dense(self.output_dim, jnp.float32)(x)


def ensemble_apply(ens: nn.Module, stacked_params: Any, x: Array) -> Array:
    return jax.vmap(lambda p: ens.apply(p, x))(stacked_params)


def init_ensembles(ens: nn.Module, n: int, key: Array, dummy_in: Array) -> Any:
    keys = jax.random.split(key, n)
    return jax.vmap(lambda k: ens.init(k, dummy_in))(keys)


def embedding_dim(wm: WorldModelDV1) -> int:
    """Encoder output width (reference world_model.encoder.cnn_output_dim +
    mlp_output_dim, agent.py:136)."""
    dim = 0
    if wm.cnn_keys:
        dim += wm.cnn_encoder_output_dim
    if wm.mlp_keys:
        dim += wm.encoder_dense_units
    return dim


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Any] = None,
    ensembles_state: Optional[Any] = None,
    actor_task_state: Optional[Any] = None,
    critic_task_state: Optional[Any] = None,
    actor_exploration_state: Optional[Any] = None,
    critic_exploration_state: Optional[Any] = None,
) -> Tuple[WorldModelDV1, Any, ActorDV1, Any, CriticDV1, Any, Any, Any, Any, Any, PlayerDV1]:
    """Returns ``(wm, wm_params, actor, actor_task_params, critic,
    critic_task_params, actor_exploration_params, critic_exploration_params,
    ensemble, ensembles_params, player)``."""
    wm, wm_params, actor, actor_task_params, critic, critic_task_params, player = dv1_build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        actor_task_state,
        critic_task_state,
    )

    key = jax.random.PRNGKey(int(cfg["seed"]) + 1)
    k_actor, k_ens, k_crit = jax.random.split(key, 3)
    latent = jnp.zeros((1, wm.latent_state_size), jnp.float32)

    actor_exploration_params = (
        jax.tree.map(jnp.asarray, actor_exploration_state)
        if actor_exploration_state is not None
        else actor.init(k_actor, latent)
    )
    critic_exploration_params = (
        jax.tree.map(jnp.asarray, critic_exploration_state)
        if critic_exploration_state is not None
        else critic.init(k_crit, latent)
    )
    actor_exploration_params = fabric.replicate(actor_exploration_params)
    critic_exploration_params = fabric.replicate(critic_exploration_params)

    ens_cfg = cfg["algo"]["ensembles"]
    ensemble = EnsembleDV1(
        output_dim=embedding_dim(wm),
        mlp_layers=int(ens_cfg["mlp_layers"]),
        dense_units=int(ens_cfg["dense_units"]),
        act=str(ens_cfg.get("dense_act", "elu")),
        dtype=fabric.precision.compute_dtype,
    )
    dummy_in = jnp.zeros((1, wm.latent_state_size + int(np.sum(actions_dim))), jnp.float32)
    if ensembles_state is not None:
        ensembles_params = jax.tree.map(jnp.asarray, ensembles_state)
    else:
        ensembles_params = init_ensembles(ensemble, int(ens_cfg["n"]), k_ens, dummy_in)
    ensembles_params = fabric.replicate(ensembles_params)

    if str(cfg["algo"]["player"].get("actor_type", "task")) == "exploration":
        player.actor_params = actor_exploration_params

    return (
        wm,
        wm_params,
        actor,
        actor_task_params,
        critic,
        critic_task_params,
        actor_exploration_params,
        critic_exploration_params,
        ensemble,
        ensembles_params,
        player,
    )
