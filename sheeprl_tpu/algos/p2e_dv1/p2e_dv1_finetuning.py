"""Plan2Explore on Dreamer-V1 — finetuning phase (reference:
sheeprl/algos/p2e_dv1/p2e_dv1_finetuning.py:28-439) — TPU-native.

Loads the exploration checkpoint and runs the plain fused Dreamer-V1 train
step on the task models; the player acts with the EXPLORATION actor (with
exploration noise) until the first gradient step, then switches to the task
actor (reference :260, :328-331)."""

from __future__ import annotations

import os
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.ops.optim import build_tx
from sheeprl_tpu.algos.dreamer_v1.dreamer_v1 import METRIC_ORDER, make_train_fn
from sheeprl_tpu.algos.p2e_dv1.agent import build_agent
from sheeprl_tpu.algos.p2e_dv1.utils import prepare_obs, test
from sheeprl_tpu.data.device_buffer import (
    DeviceReplayBuffer,
    adapt_restored_buffer,
    make_sequential_replay,
)
from sheeprl_tpu.data.prefetch import sampled_batches
from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, save_configs

FINETUNING_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Params/exploration_amount",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}


@register_algorithm()
def main(fabric, cfg: Dict[str, Any], exploration_cfg: Dict[str, Any]):
    resume_from_checkpoint = bool(cfg.checkpoint.resume_from)
    if resume_from_checkpoint:
        state = fabric.load(cfg.checkpoint.resume_from)
    else:
        state = fabric.load(cfg.checkpoint.exploration_ckpt_path)

    # model hyperparameters must match the exploration phase (reference :50-71)
    for k in (
        "gamma",
        "lmbda",
        "horizon",
        "dense_units",
        "mlp_layers",
        "dense_act",
        "cnn_act",
        "world_model",
        "actor",
        "critic",
        "cnn_keys",
        "mlp_keys",
    ):
        if k in exploration_cfg.algo:
            cfg.algo[k] = exploration_cfg.algo[k]
    cfg.env.clip_rewards = exploration_cfg.env.clip_rewards
    if cfg.buffer.get("load_from_exploration") and exploration_cfg.buffer.checkpoint:
        cfg.env.num_envs = exploration_cfg.env.num_envs
    cfg.env.screen_size = 64
    cfg.env.frame_stack = 1

    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")

    rank = fabric.process_index
    num_envs = int(cfg.env.num_envs)
    world_size = fabric.data_parallel_size  # batch-split width: the data axis (= device count on a 1-D mesh)
    num_processes = fabric.num_processes

    envs = build_vector_env(cfg, rank, log_dir if rank == 0 else None, "train", restart_on_exception=True)
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")

    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape if is_continuous else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    clip_rewards_fn = (lambda r: np.tanh(r)) if cfg.env.clip_rewards else (lambda r: r)

    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys

    (
        wm,
        wm_params,
        actor,
        actor_task_params,
        critic,
        critic_task_params,
        actor_expl_params,
        _critic_expl_params,
        _ensemble,
        _ensembles_params,
        player,
    ) = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"],
        None,
        state["actor_task"],
        state["critic_task"],
        state["actor_exploration"],
        None,
    )

    world_tx = build_tx(cfg.algo.world_model.optimizer, cfg.algo.world_model.clip_gradients)
    actor_tx = build_tx(cfg.algo.actor.optimizer, cfg.algo.actor.clip_gradients)
    critic_tx = build_tx(cfg.algo.critic.optimizer, cfg.algo.critic.clip_gradients)
    world_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["world_optimizer"]))
    actor_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["actor_task_optimizer"]))
    critic_opt = fabric.replicate(jax.tree.map(jnp.asarray, state["critic_task_optimizer"]))

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in FINETUNING_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    buffer_size = cfg.buffer.size // int(num_envs * num_processes) if not cfg.dry_run else 4
    rb = make_sequential_replay(
        cfg,
        fabric,
        observation_space,
        actions_dim,
        buffer_size,
        num_envs,
        obs_keys,
        memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        seed=cfg.seed,
    )
    if (resume_from_checkpoint and cfg.buffer.checkpoint) or (
        cfg.buffer.get("load_from_exploration") and exploration_cfg.buffer.checkpoint
    ):
        from sheeprl_tpu.utils.checkpoint import select_buffer

        rb = adapt_restored_buffer(
            select_buffer(state["rb"], rank, num_processes),
            isinstance(rb, DeviceReplayBuffer),
            seed=cfg.seed,
            memmap=cfg.buffer.memmap,
            memmap_dir=os.path.join(log_dir, "memmap_buffer", f"rank_{rank}"),
        )

    train_fn = make_train_fn(
        fabric, wm, actor, critic, world_tx, actor_tx, critic_tx, cfg, is_continuous, actions_dim
    )

    train_step = 0
    last_train = 0
    start_step = state["update"] + 1 if resume_from_checkpoint else 1
    policy_step = state["update"] * num_envs * num_processes if resume_from_checkpoint else 0
    last_log = state["last_log"] if resume_from_checkpoint else 0
    last_checkpoint = state["last_checkpoint"] if resume_from_checkpoint else 0
    policy_steps_per_update = int(num_envs * num_processes)
    num_updates = int(cfg.algo.total_steps // policy_steps_per_update) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_update if not cfg.dry_run else 0
    per_rank_batch_size = int(cfg.algo.per_rank_batch_size)
    sequence_length = int(cfg.algo.per_rank_sequence_length)
    if resume_from_checkpoint:
        from sheeprl_tpu.utils.checkpoint import elastic_per_rank_batch_size

        per_rank_batch_size = elastic_per_rank_batch_size(state["batch_size"], world_size)
        if not cfg.buffer.checkpoint:
            learning_starts += start_step

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if resume_from_checkpoint:
        ratio.load_state_dict(state["ratio"])

    key = jax.random.PRNGKey(int(cfg.seed))
    if resume_from_checkpoint and "rng_key" in state:
        key = jnp.asarray(state["rng_key"])
    # action keys live on the player's device so a host-pinned player
    # never blocks on a chip round trip per env step
    from sheeprl_tpu.parallel.fabric import put_tree as _put_tree

    player_key = _put_tree(jax.random.fold_in(key, 1), player.device)
    if cfg.checkpoint.resume_from and "player_rng_key" in state:
        # continue the pre-resume action-sampling stream
        player_key = _put_tree(jnp.asarray(state["player_rng_key"]), player.device)

    step_data: Dict[str, np.ndarray] = {}
    obs, _ = envs.reset(seed=cfg.seed)
    prepared = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=num_envs)
    for k in obs_keys:
        step_data[k] = prepared[k][np.newaxis]
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["actions"] = np.zeros((1, num_envs, int(np.sum(actions_dim))), np.float32)
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    rb.add(step_data, validate_args=cfg.buffer.validate_args)
    player.init_states()

    # explore with the exploration actor (+noise) until the first gradient
    # step (reference :260, :328-331)
    player_actor_type = "exploration"
    player.actor_params = actor_expl_params

    cumulative_per_rank_gradient_steps = 0
    for update in range(start_step, num_updates + 1):
        policy_step += num_envs * num_processes

        with timer("Time/env_interaction_time"):
            player_key, action_key = jax.random.split(player_key)
            prepared = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=num_envs)
            actions = player.get_actions(
                prepared, action_key, expl_step=policy_step, with_exploration=True
            )
            if is_continuous:
                real_actions = actions
            else:
                splits = np.cumsum(actions_dim)[:-1]
                real_actions = np.stack(
                    [p.argmax(-1) for p in np.split(actions, splits, axis=-1)], axis=-1
                )
                if real_actions.shape[-1] == 1 and not is_multidiscrete:
                    real_actions = real_actions[..., 0]

            step_data["is_first"] = np.logical_or(
                step_data["terminated"], step_data["truncated"]
            ).astype(np.float32)
            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        if "restart_on_exception" in infos:
            for i, roe in enumerate(np.asarray(infos["restart_on_exception"]).reshape(-1)):
                if roe and not dones[i]:
                    step_data["is_first"][0, i] = 1.0

        if cfg.metric.log_level > 0 and "final_info" in infos:
            ep = infos["final_info"].get("episode")
            if ep is not None:
                for i in np.nonzero(ep.get("_r", []))[0]:
                    aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                    aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                    print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

        real_next_obs = {k: np.asarray(v).copy() for k, v in next_obs.items()}
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    for k, v in final_obs.items():
                        real_next_obs[k][idx] = v

        prepared_next = prepare_obs(real_next_obs, cnn_keys=cnn_keys, num_envs=num_envs)
        for k in obs_keys:
            step_data[k] = prepared_next[k][np.newaxis]
        obs = next_obs

        step_data["terminated"] = np.asarray(terminated, np.float32).reshape(1, num_envs, 1)
        step_data["truncated"] = np.asarray(truncated, np.float32).reshape(1, num_envs, 1)
        step_data["actions"] = np.asarray(actions, np.float32).reshape(1, num_envs, -1)
        step_data["rewards"] = clip_rewards_fn(np.asarray(rewards, np.float32).reshape(1, num_envs, 1))
        rb.add(step_data, validate_args=cfg.buffer.validate_args)

        dones_idxes = dones.nonzero()[0].tolist()
        if dones_idxes:
            prepared_reset = prepare_obs(
                {k: np.asarray(next_obs[k])[dones_idxes] for k in obs_keys},
                cnn_keys=cnn_keys,
                num_envs=len(dones_idxes),
            )
            reset_data = {k: prepared_reset[k][np.newaxis] for k in obs_keys}
            reset_data["terminated"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            reset_data["truncated"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            reset_data["actions"] = np.zeros((1, len(dones_idxes), int(np.sum(actions_dim))), np.float32)
            reset_data["rewards"] = np.zeros((1, len(dones_idxes), 1), np.float32)
            reset_data["is_first"] = np.ones_like(reset_data["terminated"])
            rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
            step_data["terminated"][0, dones_idxes] = 0.0
            step_data["truncated"][0, dones_idxes] = 0.0
            player.init_states(dones_idxes)

        # ---------------- training ---------------- #
        if update >= learning_starts:
            per_rank_gradient_steps = ratio(policy_step / num_processes)
            if per_rank_gradient_steps > 0:
                if player_actor_type != "task":
                    player_actor_type = "task"
                    player.actor_params = actor_task_params
                # batch i+1's host->HBM transfer overlaps gradient step i
                batches = sampled_batches(
                    rb,
                    per_rank_batch_size * fabric.local_data_parallel_size,
                    sequence_length,
                    per_rank_gradient_steps,
                    cnn_keys,
                    fabric,
                    prefetch=int(cfg.buffer.get("prefetch", 0) or 0),
                )
                with timer("Time/train_time"):
                    for i, batch in enumerate(batches):
                        key, train_key = jax.random.split(key)
                        (
                            wm_params,
                            actor_task_params,
                            critic_task_params,
                            world_opt,
                            actor_opt,
                            critic_opt,
                            metrics,
                        ) = train_fn(
                            wm_params,
                            actor_task_params,
                            critic_task_params,
                            world_opt,
                            actor_opt,
                            critic_opt,
                            batch,
                            train_key,
                        )
                        cumulative_per_rank_gradient_steps += 1
                    metrics = np.asarray(jax.device_get(metrics))
                    train_step += num_processes
                # non-blocking in host-player mode: the trees stream through the
                # async pipe and flip a block or two later (fabric.stream_attr)
                player.stream_attr("wm_params", wm_params)
                player.stream_attr("actor_params", actor_task_params)
                if cfg.metric.log_level > 0:
                    for name, value in zip(METRIC_ORDER, metrics):
                        aggregator.update(name, float(value))
                    aggregator.update(
                        "Params/exploration_amount", float(actor.get_expl_amount(policy_step))
                    )

        # ---------------- logging ---------------- #
        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or update == num_updates):
            metrics_dict = aggregator.compute()
            logger.log_metrics(metrics_dict, policy_step)
            aggregator.reset()
            if policy_step > 0:
                logger.log_metrics(
                    {"Params/replay_ratio": cumulative_per_rank_gradient_steps * num_processes / policy_step},
                    policy_step,
                )
            if not timer.disabled:
                timer_metrics = timer.compute()
                if timer_metrics.get("Time/train_time"):
                    logger.log_metrics(
                        {"Time/sps_train": (train_step - last_train) / timer_metrics["Time/train_time"]},
                        policy_step,
                    )
                if timer_metrics.get("Time/env_interaction_time"):
                    logger.log_metrics(
                        {
                            "Time/sps_env_interaction": (
                                (policy_step - last_log) / num_processes * cfg.env.action_repeat
                            )
                            / timer_metrics["Time/env_interaction_time"]
                        },
                        policy_step,
                    )
                timer.reset()
            last_log = policy_step
            last_train = train_step

        # ---------------- checkpoint ---------------- #
        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            update == num_updates and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            ckpt_state = {
                "world_model": jax.device_get(wm_params),
                "actor_task": jax.device_get(actor_task_params),
                "critic_task": jax.device_get(critic_task_params),
                "actor_exploration": jax.device_get(actor_expl_params),
                "world_optimizer": jax.device_get(world_opt),
                "actor_task_optimizer": jax.device_get(actor_opt),
                "critic_task_optimizer": jax.device_get(critic_opt),
                "ratio": ratio.state_dict(),
                "update": update,
                "batch_size": per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "rng_key": jax.device_get(key),
                "player_rng_key": jax.device_get(player_key),
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_{rank}.ckpt")
            fabric.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )

    # land any in-flight async param stream so the final evaluation and
    # model registration use the last update's weights
    player.flush_stream_attrs()
    envs.close()
    if fabric.is_global_zero and cfg.algo.run_test:
        player.actor_params = actor_task_params
        test(player, fabric, cfg, log_dir, "few-shot", greedy=False)
    logger.finalize()
