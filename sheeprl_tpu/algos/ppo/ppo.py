"""PPO, coupled (reference: sheeprl/algos/ppo/ppo.py:30-452) — TPU-native.

Differences from the reference that are the point of the redesign:

- **One SPMD process per host, no launcher.** The reference spawns DDP ranks
  (cli.py:190); here the rollout data ``[T*E, ...]`` is sharded across the
  mesh's data axis and the whole optimization (epochs x minibatches) runs as
  a single jitted ``shard_map`` — the per-minibatch gradient ``pmean`` over
  ICI is the DDP all-reduce (ppo.py:93 ``fabric.backward``).
- **Whole-update fusion.** The reference's Python epoch/minibatch loops with
  per-batch optimizer steps become two nested ``lax.scan``s inside one XLA
  program: one dispatch per update instead of epochs*minibatches.
- **GAE on device** as a reverse ``lax.scan`` (reference utils.py:63-100 is
  a Python loop over T).
- **uint8 to the MXU.** Pixels cross PCIe as bytes; normalization happens
  inside the agent (agent.py CNNEncoder), not in ``normalize_obs``.
- Annealed coefficients (clip/entropy) are *dynamic scalars* fed to the
  jitted step — annealing never recompiles.
"""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P
from sheeprl_tpu.parallel.shard_map import shard_map

from sheeprl_tpu.algos.ppo.agent import PPOPlayer, build_agent, evaluate_actions
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.config.compose import instantiate
from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.obs import (
    log_sps_and_heartbeat,
    telemetry_advance,
    telemetry_register_flops,
    telemetry_run_metrics,
)
from sheeprl_tpu.ops.math import gae
from sheeprl_tpu.parallel.fabric import put_tree, resolve_player_device, resolve_train_device
from sheeprl_tpu.resilience import RunResilience
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs


def make_train_fn(fabric, agent, tx, cfg, obs_keys, n_local: int, host_device=None):
    """Build the fused update: epochs x shuffled minibatches, grad-pmean'd
    over the data axis, one jit (replaces reference train(), ppo.py:30-102).

    ``host_device``: single-device escape hatch (``resolve_train_device``) —
    the same program without mesh collectives, jitted for the host CPU so a
    tiny model's update never touches a remote-attached accelerator."""
    batch_size = int(cfg.algo.per_rank_batch_size)
    update_epochs = int(cfg.algo.update_epochs)
    num_minibatches = n_local // batch_size
    if num_minibatches == 0:
        raise ValueError(
            f"per_rank_batch_size ({batch_size}) is larger than the per-device rollout ({n_local})"
        )
    dropped = n_local - num_minibatches * batch_size
    if dropped:
        warnings.warn(
            f"{dropped} of {n_local} per-device rollout samples are dropped each epoch because "
            f"per_rank_batch_size ({batch_size}) does not divide the per-device rollout; "
            "choose rollout_steps*num_envs divisible by (devices*batch_size) to use all data."
        )
    vf_coef = float(cfg.algo.vf_coef)
    clip_vloss = bool(cfg.algo.clip_vloss)
    normalize_adv = bool(cfg.algo.normalize_advantages)
    reduction = str(cfg.algo.loss_reduction)
    data_axis = fabric.data_axis
    use_mesh = host_device is None

    def pmean(x):
        return lax.pmean(x, data_axis) if use_mesh else x

    def local_train(params, opt_state, data, key, clip_coef, ent_coef):
        if use_mesh:
            # distinct permutation stream per device (reference: per-rank sampler)
            key = jax.random.fold_in(key, lax.axis_index(data_axis))

        def minibatch_step(carry, batch):
            params, opt_state = carry

            def loss_fn(p):
                obs = {k: batch[k] for k in obs_keys}
                new_logprobs, entropy, new_values = evaluate_actions(agent, p, obs, batch["actions"])
                adv = batch["advantages"]
                if normalize_adv:
                    adv = (adv - adv.mean()) / (adv.std(ddof=1) + 1e-8)
                pg = policy_loss(new_logprobs, batch["logprobs"], adv, clip_coef, reduction)
                v = value_loss(new_values, batch["values"], batch["returns"], clip_coef, clip_vloss, reduction)
                ent = entropy_loss(entropy, reduction)
                return pg + vf_coef * v + ent_coef * ent, (pg, v, ent)

            (_, (pg, v, ent)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            grads = pmean(grads)  # the DDP all-reduce, over ICI
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), jnp.stack([pg, v, ent])

        def epoch_step(carry, _):
            params, opt_state, key = carry
            key, perm_key = jax.random.split(key)
            perm = jax.random.permutation(perm_key, n_local)[: num_minibatches * batch_size]
            minibatches = jax.tree.map(
                lambda x: x[perm].reshape(num_minibatches, batch_size, *x.shape[1:]), data
            )
            (params, opt_state), metrics = lax.scan(minibatch_step, (params, opt_state), minibatches)
            return (params, opt_state, key), metrics

        (params, opt_state, _), metrics = lax.scan(
            epoch_step, (params, opt_state, key), None, length=update_epochs
        )
        # [epochs, minibatches, 3] -> [3], identical on every device after pmean
        return params, opt_state, pmean(metrics.mean(axis=(0, 1)))

    if not use_mesh:
        # inputs are committed to the host device by the caller, so the jit
        # executes entirely on the host CPU backend. Donate ONLY opt_state:
        # the host-pinned player aliases the very params buffers passed in
        # here (update_params hands them over without a copy), so donating
        # them would leave the player holding deleted arrays.
        return jax.jit(local_train, donate_argnums=(1,))
    train_fn = shard_map(
        local_train,
        mesh=fabric.mesh,
        in_specs=(P(), P(), P(data_axis), P(), P(), P()),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(train_fn, donate_argnums=(0, 1))


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)

    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")

    # preemption watcher + non-finite sentinel + checkpoint rollback
    resil = RunResilience(fabric, cfg, log_dir)

    initial_clip_coef = float(cfg.algo.clip_coef)
    initial_ent_coef = float(cfg.algo.ent_coef)

    # environment setup (reference ppo.py:137-163); SAME_STEP autoreset keeps
    # the 0.29 semantics the algorithms were specified against
    rank = fabric.process_index
    envs = build_vector_env(cfg, rank, log_dir if rank == 0 else None, "train")
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    if not obs_keys:
        raise RuntimeError(
            "You should specify at least one CNN key or MLP key from the cli: "
            "`algo.cnn_keys.encoder=[rgb]` or `algo.mlp_keys.encoder=[state]`"
        )

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )

    agent, params = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["agent"] if cfg.checkpoint.resume_from else None,
    )
    player = PPOPlayer(
        agent, params, device=resolve_player_device(cfg.algo.get("player_device", "auto"))
    )

    num_envs = int(cfg.env.num_envs)
    rollout_steps = int(cfg.algo.rollout_steps)
    # batch split width = the DATA axis only: shard_map's P(data_axis)
    # in_spec delivers n_global/data_width rows per device, so on a 2-D
    # (data, model) mesh dividing by world_size would silently train on a
    # fraction of each shard
    world_size = fabric.data_parallel_size
    policy_steps_per_update = num_envs * rollout_steps * fabric.num_processes
    num_updates = int(cfg.algo.total_steps) // policy_steps_per_update if not cfg.dry_run else 1

    # global rollout spans every process's envs; shard over all devices
    n_global = rollout_steps * num_envs * fabric.num_processes
    if n_global % world_size != 0:
        raise ValueError(
            f"rollout_steps*num_envs*processes ({n_global}) must be divisible by the device count ({world_size})"
        )
    n_local = n_global // world_size
    num_minibatches = max(1, n_local // int(cfg.algo.per_rank_batch_size))

    # optimizer; lr annealing is an optax schedule (reference PolynomialLR)
    opt_cfg = dict(cfg.algo.optimizer.to_dict() if hasattr(cfg.algo.optimizer, "to_dict") else cfg.algo.optimizer)
    if cfg.algo.max_grad_norm and float(cfg.algo.max_grad_norm) > 0:
        opt_cfg["max_grad_norm"] = float(cfg.algo.max_grad_norm)
    if cfg.algo.anneal_lr:
        steps_per_update = int(cfg.algo.update_epochs) * num_minibatches
        opt_cfg["schedule"] = optax.linear_schedule(
            float(opt_cfg.get("lr", 1e-3)), 0.0, num_updates * steps_per_update
        )
    tx = instantiate(opt_cfg)
    # remote-chip escape hatch: tiny models train on the host core, so the
    # env loop, player AND update never touch the link (resolve_train_device)
    train_device = resolve_train_device(
        cfg.algo.get("train_device", "auto"), params, fabric.world_size
    )
    if train_device is not None:
        params = put_tree(jax.device_get(params), train_device)
        player.update_params(params)
    # resume state stays host numpy until the ONE placement below — routing
    # it through jnp.asarray would upload the whole optimizer state to the
    # remote default backend only to fetch it straight back for host training
    # fresh init runs on the params' own device (host-committed when
    # train_device is set), so the moment tensors never touch the remote
    # backend just to be fetched back
    opt_state = state["opt_state"] if cfg.checkpoint.resume_from else tx.init(params)
    opt_state = (
        put_tree(opt_state, train_device) if train_device is not None else fabric.replicate(opt_state)
    )

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in AGGREGATOR_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    if cfg.buffer.size < rollout_steps:
        raise ValueError(
            f"The size of the buffer ({cfg.buffer.size}) cannot be lower "
            f"than the rollout steps ({rollout_steps})"
        )
    # the rollout is consumed in-place each update (on-policy); unlike the
    # reference there is no staging ReplayBuffer copy — host lists are the
    # only transient storage

    train_fn = make_train_fn(fabric, agent, tx, cfg, obs_keys, n_local, host_device=train_device)
    gae_fn = jax.jit(partial(gae, gamma=float(cfg.algo.gamma), gae_lambda=float(cfg.algo.gae_lambda)))

    # counters (reference ppo.py:214-231)
    start_update = (state["update"] + 1) if cfg.checkpoint.resume_from else 1
    policy_step = state["update"] * policy_steps_per_update if cfg.checkpoint.resume_from else 0
    last_log = state["last_log"] if cfg.checkpoint.resume_from else 0
    last_checkpoint = state["last_checkpoint"] if cfg.checkpoint.resume_from else 0
    train_step = 0
    last_train = 0

    key = jax.random.PRNGKey(int(cfg.seed))
    if cfg.checkpoint.resume_from and "rng_key" in state:
        # host numpy from the checkpoint; placed exactly once below
        key = np.asarray(state["rng_key"])
    if train_device is not None:
        # the train key chain lives on the train device: a mixed-device
        # committed-input set would error, and splitting on the remote chip
        # would re-insert a per-update round trip
        key = put_tree(key, train_device)
    elif cfg.checkpoint.resume_from and "rng_key" in state:
        key = jnp.asarray(key)
    # rollout action keys live on the player's device so a host-pinned
    # player never blocks on a chip round trip per env step
    player_key = put_tree(jax.random.fold_in(key, 1), player.device)
    if cfg.checkpoint.resume_from and "player_rng_key" in state:
        # continue the pre-resume action-sampling stream
        player_key = put_tree(jnp.asarray(state["player_rng_key"]), player.device)

    clip_coef = float(cfg.algo.clip_coef)
    ent_coef = float(cfg.algo.ent_coef)

    next_obs, _ = envs.reset(seed=cfg.seed)
    next_obs = prepare_obs(next_obs, cnn_keys=cnn_keys, num_envs=num_envs)

    # steady-state throughput probe (bench.py): updates 2..last, skipping the
    # compile-heavy first update — shared contract in utils.SteadyStateProbe
    from sheeprl_tpu.utils.utils import SteadyStateProbe

    def ckpt_state_fn(completed_update: int) -> Dict[str, Any]:
        # shared by the periodic save, the preemption drain's emergency save
        # and (structurally) the rollback restore — reads the loop's CURRENT
        # bindings at call time
        return {
            "agent": jax.device_get(params),
            "opt_state": jax.device_get(opt_state),
            "update": completed_update,
            "batch_size": int(cfg.algo.per_rank_batch_size) * world_size,
            "last_log": last_log,
            "last_checkpoint": last_checkpoint,
            "rng_key": jax.device_get(key),
            "player_rng_key": jax.device_get(player_key),
        }

    def ckpt_path_fn(step: int) -> str:
        return os.path.join(log_dir, "checkpoint", f"ckpt_{step}_{rank}.ckpt")

    # a crash anywhere in the loop gets the preemption treatment too: the
    # lambdas read the loop's CURRENT policy_step/update at crash time
    resil.arm_crash_guard(
        path_fn=lambda: ckpt_path_fn(policy_step),
        state_fn=lambda: ckpt_state_fn(update - 1),
    )
    preempted = False
    probe = SteadyStateProbe()
    for update in range(start_update, num_updates + 1):
        telemetry_advance(policy_step)
        if resil.preempt_requested():
            # update has NOT run yet: the emergency checkpoint records
            # update-1 so auto-resume replays from exactly this boundary
            last_checkpoint = policy_step
            resil.emergency_checkpoint(ckpt_path_fn(policy_step), ckpt_state_fn(update - 1))
            preempted = True
            break
        if update == start_update + 1:
            probe.mark(policy_step)
        rollout = {k: [] for k in (*obs_keys, "dones", "values", "actions", "logprobs", "rewards")}
        with timer("Time/env_interaction_time"):
            # one jitted dispatch + ONE device->host fetch per env step: key
            # folding, sampling and the one-hot->index conversion are fused
            # (agent.rollout_step); the base key crosses to the player device
            # once per update. Over a remote-attached TPU separate fetches
            # would cost ~100ms each; on the 1-core host the saved dispatches
            # are a measurable slice of the step budget.
            # fold the update index into the base key so action-stream
            # uniqueness holds even if policy_step bookkeeping ever repeats a
            # value across a resume (rollout_actions folds policy_step on top)
            update_key = jax.random.fold_in(player_key, update)
            for _ in range(rollout_steps):
                policy_step += num_envs * fabric.num_processes
                actions, real_actions, logprobs, values = player.rollout_actions(
                    next_obs, update_key, policy_step
                )
                actions_np, real_actions, logprobs_np, values_np = jax.device_get(
                    (actions, real_actions, logprobs, values)
                )
                if not is_continuous and real_actions.shape[-1] == 1 and not is_multidiscrete:
                    real_actions = real_actions[..., 0]

                obs, rewards, terminated, truncated, info = envs.step(
                    real_actions.reshape(envs.action_space.shape)
                )
                rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, 1)

                # truncation bootstrap (reference ppo.py:286-305)
                truncated_envs = np.nonzero(truncated)[0]
                if len(truncated_envs) > 0 and "final_obs" in info:
                    final_obs = {
                        k: np.stack([np.asarray(info["final_obs"][e][k]) for e in truncated_envs])
                        for k in obs_keys
                    }
                    final_obs = prepare_obs(final_obs, cnn_keys=cnn_keys, num_envs=len(truncated_envs))
                    vals = np.asarray(player.get_values(final_obs)).reshape(len(truncated_envs))
                    rewards[truncated_envs, 0] += float(cfg.algo.gamma) * vals

                dones = np.logical_or(terminated, truncated).reshape(num_envs, 1).astype(np.float32)
                for k in obs_keys:
                    rollout[k].append(next_obs[k])
                rollout["dones"].append(dones)
                rollout["values"].append(values_np)
                rollout["actions"].append(actions_np)
                rollout["logprobs"].append(logprobs_np)
                rollout["rewards"].append(rewards)

                next_obs = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=num_envs)

                if cfg.metric.log_level > 0 and "final_info" in info:
                    ep = info["final_info"].get("episode")
                    if ep is not None:
                        for i in np.nonzero(ep.get("_r", []))[0]:
                            aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                            aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                            print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

        local_data = {k: np.stack(v, axis=0) for k, v in rollout.items()}  # [T, E, ...]

        # GAE on the player's device (reference ppo.py:345-360) — rollout
        # arrays are host-side already, so with a host-pinned player the
        # whole advantage pass stays off the chip's round-trip path
        next_values = np.asarray(player.get_values(next_obs))  # [E, 1]
        returns, advantages = gae_fn(
            put_tree(local_data["rewards"], player.device),
            put_tree(local_data["values"], player.device),
            put_tree(local_data["dones"], player.device),
            put_tree(next_values, player.device),
        )
        local_data["returns"] = np.asarray(returns)
        local_data["advantages"] = np.asarray(advantages)

        # flatten [T, E, ...] -> [T*E, ...]; shard_map splits over devices;
        # multi-host runs assemble the per-process blocks into a global array
        flat = {k: v.reshape(v.shape[0] * v.shape[1], *v.shape[2:]) for k, v in local_data.items()}
        if fabric.num_processes > 1:
            flat = fabric.make_global(flat, (fabric.data_axis,))

        with timer("Time/train_time"):
            key, train_key = jax.random.split(key)
            params, opt_state, metrics = train_fn(
                params,
                opt_state,
                flat,
                train_key,
                # host numpy scalars: jnp.float32 would materialize them on
                # the DEFAULT backend every update — with a host-pinned train
                # device on a remote chip that is a blocking link fetch per
                # update, more than the round trips host-training saves
                np.float32(clip_coef),
                np.float32(ent_coef),
            )
            metrics = jax.block_until_ready(metrics)
        # one host fetch serves the NaN sentinel and the aggregator scalars
        # below — float(metrics[i]) on the device array would be a blocking
        # transfer per scalar per update
        metrics = np.asarray(metrics)
        if not resil.check_finite(metrics, update):
            # restore the newest committed checkpoint in place of the
            # poisoned params/opt state, fork the sample key away from the
            # stream that diverged, and move on to the next update — the
            # loop's counters keep advancing so the run still completes
            restored = resil.rollback(update=update)
            params = resil.place_like(restored["agent"], params)
            opt_state = resil.place_like(restored["opt_state"], opt_state)
            if "rng_key" in restored:
                key = resil.place_like(restored["rng_key"], key)
            key = resil.resalt_key(key)
            player.update_params(params)
            continue
        player.update_params(params)
        train_step += world_size
        if update == start_update:
            # shapes are fixed from here on; register the MFU flops source
            # off the first real invocation (resolved lazily at heartbeat)
            telemetry_register_flops(
                train_fn, params, opt_state, flat, train_key, np.float32(clip_coef), np.float32(ent_coef)
            )

        if cfg.metric.log_level > 0:
            aggregator.update("Loss/policy_loss", float(metrics[0]))
            aggregator.update("Loss/value_loss", float(metrics[1]))
            aggregator.update("Loss/entropy_loss", float(metrics[2]))

            if policy_step - last_log >= cfg.metric.log_every or update == num_updates:
                metrics_dict = aggregator.compute()
                logger.log_metrics(metrics_dict, policy_step)
                telemetry_run_metrics(metrics_dict)
                aggregator.reset()
                log_sps_and_heartbeat(
                    logger,
                    policy_step=policy_step,
                    env_steps=(policy_step - last_log) * cfg.env.action_repeat,
                    train_steps=train_step - last_train,
                    train_invocations=(train_step - last_train) // world_size,
                )
                last_log = policy_step
                last_train = train_step

        # anneal coefficients (reference ppo.py:414-424)
        if cfg.algo.anneal_clip_coef:
            clip_coef = polynomial_decay(
                update, initial=initial_clip_coef, final=0.0, max_decay_steps=num_updates, power=1.0
            )
        if cfg.algo.anneal_ent_coef:
            ent_coef = polynomial_decay(
                update, initial=initial_ent_coef, final=0.0, max_decay_steps=num_updates, power=1.0
            )

        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            update == num_updates and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            fabric.call("on_checkpoint_coupled", ckpt_path=ckpt_path_fn(policy_step), state=ckpt_state_fn(update))

    # the params fetch is a real device sync (everything dispatched before
    # it has executed once it materializes)
    probe.finish(policy_step, sync=lambda: jax.device_get(jax.tree.leaves(params)[0]))
    envs.close()
    if fabric.is_global_zero and cfg.algo.run_test and not preempted:
        test(player, fabric, cfg, log_dir)
    logger.finalize()
    resil.close()
    if preempted:
        resil.exit_preempted()
