"""PPO, coupled (reference: sheeprl/algos/ppo/ppo.py:30-452) — TPU-native.

Differences from the reference that are the point of the redesign:

- **One SPMD process per host, no launcher.** The reference spawns DDP ranks
  (cli.py:190); here the rollout data ``[T*E, ...]`` is sharded across the
  mesh's data axis and the whole optimization (epochs x minibatches) runs as
  a single jitted ``shard_map`` — the per-minibatch gradient ``pmean`` over
  ICI is the DDP all-reduce (ppo.py:93 ``fabric.backward``).
- **Whole-update fusion.** The reference's Python epoch/minibatch loops with
  per-batch optimizer steps become two nested ``lax.scan``s inside one XLA
  program: one dispatch per update instead of epochs*minibatches.
- **GAE on device** as a reverse ``lax.scan`` (reference utils.py:63-100 is
  a Python loop over T).
- **uint8 to the MXU.** Pixels cross PCIe as bytes; normalization happens
  inside the agent (agent.py CNNEncoder), not in ``normalize_obs``.
- Annealed coefficients (clip/entropy) are *dynamic scalars* fed to the
  jitted step — annealing never recompiles.
- **Fused on-policy collection** (``algo.fused_rollout``): when the env has a
  jittable twin (``envs/jittable.py``) the whole T-step rollout, truncation
  bootstrap, autoreset, GAE and the fused update run as ONE dispatch per
  update (``ops/rollout_scan.py``); infeasible configs fall back to the host
  loop with a ``fused_fallback`` telemetry breadcrumb.
- **Overlapped collection** (``algo.overlap_collection``): the host loop
  dispatches the update asynchronously and collects the next rollout with
  one-update-stale player params while it executes (the decoupled-PPO
  staleness contract; the PPO ratio corrects against stored logprobs).  The
  blocking metrics wait is attributed to ``Time/train_wait_time`` so the
  heartbeat reports the overlap fraction directly.
"""

from __future__ import annotations

import os
import warnings
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P
from sheeprl_tpu.parallel.shard_map import shard_map

from sheeprl_tpu.algos.ppo.agent import PPOPlayer, build_agent, evaluate_actions, rollout_step
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.config.compose import instantiate
from sheeprl_tpu.envs import build_vector_env, get_jittable_env
from sheeprl_tpu.envs.variants import (
    ScenarioFamily,
    compose_variant_env_id,
    make_scenario_family,
    sample_scenario_matrix,
)
from sheeprl_tpu.obs import (
    log_sps_and_heartbeat,
    telemetry_advance,
    telemetry_register_flops,
    telemetry_run_metrics,
    telemetry_train_window,
)
from sheeprl_tpu.ops.math import gae
from sheeprl_tpu.ops.rollout_scan import ENV_STREAM_SALT, init_env_carry, make_onpolicy_superstep_fn
from sheeprl_tpu.ops.superstep import fused_fallback, reset_fused_fallback_warnings
from sheeprl_tpu.parallel.fabric import put_tree, resolve_player_device, resolve_train_device
from sheeprl_tpu.resilience import RunResilience
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.prealloc import RolloutStore
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs


def make_local_train(fabric, agent, tx, cfg, obs_keys, n_local: int, *, use_mesh: bool):
    """The UNJITTED fused-update body: epochs x shuffled minibatches with the
    per-minibatch gradient ``pmean`` when ``use_mesh`` (replaces reference
    train(), ppo.py:30-102).  ``make_train_fn`` jits it standalone; the fused
    on-policy superstep (``ops/rollout_scan.py``) embeds it after the scanned
    rollout so collection+GAE+update compile into ONE dispatch."""
    batch_size = int(cfg.algo.per_rank_batch_size)
    update_epochs = int(cfg.algo.update_epochs)
    num_minibatches = n_local // batch_size
    if num_minibatches == 0:
        raise ValueError(
            f"per_rank_batch_size ({batch_size}) is larger than the per-device rollout ({n_local})"
        )
    dropped = n_local - num_minibatches * batch_size
    if dropped:
        warnings.warn(
            f"{dropped} of {n_local} per-device rollout samples are dropped each epoch because "
            f"per_rank_batch_size ({batch_size}) does not divide the per-device rollout; "
            "choose rollout_steps*num_envs divisible by (devices*batch_size) to use all data."
        )
    vf_coef = float(cfg.algo.vf_coef)
    clip_vloss = bool(cfg.algo.clip_vloss)
    normalize_adv = bool(cfg.algo.normalize_advantages)
    reduction = str(cfg.algo.loss_reduction)
    data_axis = fabric.data_axis

    def pmean(x):
        return lax.pmean(x, data_axis) if use_mesh else x

    def local_train(params, opt_state, data, key, clip_coef, ent_coef):
        if use_mesh:
            # distinct permutation stream per device (reference: per-rank sampler)
            key = jax.random.fold_in(key, lax.axis_index(data_axis))

        def minibatch_step(carry, batch):
            params, opt_state = carry

            def loss_fn(p):
                obs = {k: batch[k] for k in obs_keys}
                new_logprobs, entropy, new_values = evaluate_actions(agent, p, obs, batch["actions"])
                adv = batch["advantages"]
                if normalize_adv:
                    adv = (adv - adv.mean()) / (adv.std(ddof=1) + 1e-8)
                pg = policy_loss(new_logprobs, batch["logprobs"], adv, clip_coef, reduction)
                v = value_loss(new_values, batch["values"], batch["returns"], clip_coef, clip_vloss, reduction)
                ent = entropy_loss(entropy, reduction)
                return pg + vf_coef * v + ent_coef * ent, (pg, v, ent)

            (_, (pg, v, ent)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            grads = pmean(grads)  # the DDP all-reduce, over ICI
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), jnp.stack([pg, v, ent])

        def epoch_step(carry, _):
            params, opt_state, key = carry
            key, perm_key = jax.random.split(key)
            perm = jax.random.permutation(perm_key, n_local)[: num_minibatches * batch_size]
            minibatches = jax.tree.map(
                lambda x: x[perm].reshape(num_minibatches, batch_size, *x.shape[1:]), data
            )
            (params, opt_state), metrics = lax.scan(minibatch_step, (params, opt_state), minibatches)
            return (params, opt_state, key), metrics

        (params, opt_state, _), metrics = lax.scan(
            epoch_step, (params, opt_state, key), None, length=update_epochs
        )
        # [epochs, minibatches, 3] -> [3], identical on every device after pmean
        return params, opt_state, pmean(metrics.mean(axis=(0, 1)))

    return local_train


def make_train_fn(fabric, agent, tx, cfg, obs_keys, n_local: int, host_device=None, donate_params: bool = True):
    """Build the fused update: epochs x shuffled minibatches, grad-pmean'd
    over the data axis, one jit (replaces reference train(), ppo.py:30-102).

    ``host_device``: single-device escape hatch (``resolve_train_device``) —
    the same program without mesh collectives, jitted for the host CPU so a
    tiny model's update never touches a remote-attached accelerator.

    ``donate_params=False`` keeps the params buffers alive past the call: the
    overlap_collection loop dispatches update N and then lets the player keep
    sampling from one-update-stale params while N executes, so those buffers
    must survive the dispatch even when player and train share a device."""
    use_mesh = host_device is None
    local_train = make_local_train(fabric, agent, tx, cfg, obs_keys, n_local, use_mesh=use_mesh)
    if not use_mesh:
        # inputs are committed to the host device by the caller, so the jit
        # executes entirely on the host CPU backend. Donate ONLY opt_state:
        # the host-pinned player aliases the very params buffers passed in
        # here (update_params hands them over without a copy), so donating
        # them would leave the player holding deleted arrays.
        return jax.jit(local_train, donate_argnums=(1,))
    train_fn = shard_map(
        local_train,
        mesh=fabric.mesh,
        in_specs=(P(), P(), P(fabric.data_axis), P(), P(), P()),
        out_specs=(P(), P(), P()),
    )
    return jax.jit(train_fn, donate_argnums=(0, 1) if donate_params else (1,))


def scenario_variant_cfg(cfg):
    """Parse the ``env.variants.*`` node: ``(names, kwargs, ranges, seed)``.

    ``names`` is the enabled-variant tuple (empty when the node is absent or
    disabled), ``kwargs`` the static family knobs for
    :func:`make_scenario_family`."""
    node = cfg.env.get("variants", None) if hasattr(cfg.env, "get") else None
    if node is None:
        return (), {}, {}, None
    names = tuple(str(n) for n in (node.get("enabled", None) or ()))
    if not names:
        return (), {}, {}, None
    kwargs = {
        "distractor_dims": int(node.get("distractor_dims", 4)),
        "reward_max_delay": int(node.get("reward_max_delay", 4)),
    }
    ranges = {
        str(k): (float(v[0]), float(v[1])) for k, v in dict(node.get("ranges", None) or {}).items()
    }
    seed = node.get("seed", None)
    return names, kwargs, ranges, (None if seed is None else int(seed))


def resolve_scenario_family(cfg) -> ScenarioFamily | None:
    """The :class:`ScenarioFamily` for ``env.id`` + ``env.variants.enabled``,
    or ``None`` when no variants are enabled or the base env has no jittable
    twin (the fused feasibility gate then emits the breadcrumb)."""
    names, kwargs, _, _ = scenario_variant_cfg(cfg)
    if not names:
        return None
    return make_scenario_family(str(cfg.env.id), names, **kwargs)


def scenario_theta_matrix(cfg, family: ScenarioFamily, num_envs: int) -> jax.Array:
    """Sample the ``[num_envs, P]`` scenario matrix from ``env.variants``."""
    _, _, ranges, seed = scenario_variant_cfg(cfg)
    key = jax.random.PRNGKey(int(cfg.seed) if seed is None else seed)
    return sample_scenario_matrix(key, num_envs, family.variant_names, ranges)


def resolve_fused_rollout_spec(
    cfg, fabric, cnn_keys, mlp_keys, observation_space, is_continuous, is_multidiscrete, actions_dim
):
    """Feasibility gate for ``algo.fused_rollout``: return the jittable env
    spec (or :class:`ScenarioFamily` when ``env.variants`` are enabled) when
    the whole rollout can run in-graph, else emit one ``fused_fallback``
    telemetry event and return ``None`` (host loop)."""
    env_id = str(cfg.env.id)
    variant_names, family_kwargs, _, _ = scenario_variant_cfg(cfg)
    spec = get_jittable_env(env_id)
    if spec is None:
        # name the full variant-composed id so sweep triage can grep which
        # scenario (not just which base env) was skipped
        missing = compose_variant_env_id(env_id, variant_names) if variant_names else env_id
        fused_fallback("jittable_env", f"no jittable twin registered for env id '{missing}'")
        return None
    if variant_names:
        spec = make_scenario_family(env_id, variant_names, **family_kwargs)
    if fabric.num_processes > 1:
        fused_fallback("multi_process", "fused rollout is single-process (env state is process-local)")
        return None
    if fabric.model_axis is not None:
        fused_fallback("model_axis", "fused rollout shards envs over the data axis only")
        return None
    if cnn_keys or len(mlp_keys) != 1:
        fused_fallback(
            "obs_keys",
            f"fused rollout needs exactly one MLP obs key and no CNN keys, got cnn={cnn_keys} mlp={mlp_keys}",
        )
        return None
    obs_shape = tuple(observation_space[mlp_keys[0]].shape)
    if obs_shape != (spec.obs_dim,):
        fused_fallback(
            "obs_space",
            f"env obs {obs_shape} != jittable twin {(spec.obs_dim,)} — wrappers changed the observation",
        )
        return None
    if is_multidiscrete or bool(is_continuous) != bool(spec.is_continuous) or tuple(actions_dim) != (
        spec.action_dim,
    ):
        fused_fallback(
            "action_space",
            f"env actions {tuple(actions_dim)} (continuous={is_continuous}) != jittable twin "
            f"({spec.action_dim}, continuous={spec.is_continuous})",
        )
        return None
    if int(cfg.env.action_repeat) != 1:
        fused_fallback("action_repeat", "jittable twins model single-step dynamics only")
        return None
    return spec


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)

    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")

    # preemption watcher + non-finite sentinel + checkpoint rollback
    resil = RunResilience(fabric, cfg, log_dir)

    initial_clip_coef = float(cfg.algo.clip_coef)
    initial_ent_coef = float(cfg.algo.ent_coef)

    # environment setup (reference ppo.py:137-163); SAME_STEP autoreset keeps
    # the 0.29 semantics the algorithms were specified against
    rank = fabric.process_index
    envs = build_vector_env(cfg, rank, log_dir if rank == 0 else None, "train")
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    if not obs_keys:
        raise RuntimeError(
            "You should specify at least one CNN key or MLP key from the cli: "
            "`algo.cnn_keys.encoder=[rgb]` or `algo.mlp_keys.encoder=[state]`"
        )

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )

    # scenario variants (env.variants.*) run through the fused rollout only;
    # the `distractors` variant widens the observation, so the agent must be
    # built against the family's obs_dim, not the base vector env's
    # resolved unconditionally: enabled variants with the fused path off must
    # hit the loud RuntimeError below, never silently train the base env
    scenario_family = resolve_scenario_family(cfg)
    obs_widened = False
    if scenario_family is not None and not cnn_keys and len(mlp_keys) == 1:
        k0 = mlp_keys[0]
        if tuple(observation_space[k0].shape) != (scenario_family.obs_dim,):
            spaces_d = dict(observation_space.spaces)
            spaces_d[k0] = gym.spaces.Box(-np.inf, np.inf, (scenario_family.obs_dim,), np.float32)
            observation_space = gym.spaces.Dict(spaces_d)
            obs_widened = True

    agent, params = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["agent"] if cfg.checkpoint.resume_from else None,
    )
    player = PPOPlayer(
        agent, params, device=resolve_player_device(cfg.algo.get("player_device", "auto"))
    )

    num_envs = int(cfg.env.num_envs)
    rollout_steps = int(cfg.algo.rollout_steps)
    # batch split width = the DATA axis only: shard_map's P(data_axis)
    # in_spec delivers n_global/data_width rows per device, so on a 2-D
    # (data, model) mesh dividing by world_size would silently train on a
    # fraction of each shard
    world_size = fabric.data_parallel_size
    policy_steps_per_update = num_envs * rollout_steps * fabric.num_processes
    num_updates = int(cfg.algo.total_steps) // policy_steps_per_update if not cfg.dry_run else 1

    # global rollout spans every process's envs; shard over all devices
    n_global = rollout_steps * num_envs * fabric.num_processes
    if n_global % world_size != 0:
        raise ValueError(
            f"rollout_steps*num_envs*processes ({n_global}) must be divisible by the device count ({world_size})"
        )
    n_local = n_global // world_size
    num_minibatches = max(1, n_local // int(cfg.algo.per_rank_batch_size))
    update_epochs = int(cfg.algo.update_epochs)

    # optimizer; lr annealing is an optax schedule (reference PolynomialLR)
    opt_cfg = dict(cfg.algo.optimizer.to_dict() if hasattr(cfg.algo.optimizer, "to_dict") else cfg.algo.optimizer)
    if cfg.algo.max_grad_norm and float(cfg.algo.max_grad_norm) > 0:
        opt_cfg["max_grad_norm"] = float(cfg.algo.max_grad_norm)
    if cfg.algo.anneal_lr:
        steps_per_update = int(cfg.algo.update_epochs) * num_minibatches
        opt_cfg["schedule"] = optax.linear_schedule(
            float(opt_cfg.get("lr", 1e-3)), 0.0, num_updates * steps_per_update
        )
    tx = instantiate(opt_cfg)
    # remote-chip escape hatch: tiny models train on the host core, so the
    # env loop, player AND update never touch the link (resolve_train_device)
    train_device = resolve_train_device(
        cfg.algo.get("train_device", "auto"), params, fabric.world_size
    )
    if train_device is not None:
        params = put_tree(jax.device_get(params), train_device)
        player.update_params(params)
    # resume state stays host numpy until the ONE placement below — routing
    # it through jnp.asarray would upload the whole optimizer state to the
    # remote default backend only to fetch it straight back for host training
    # fresh init runs on the params' own device (host-committed when
    # train_device is set), so the moment tensors never touch the remote
    # backend just to be fetched back
    opt_state = state["opt_state"] if cfg.checkpoint.resume_from else tx.init(params)
    opt_state = (
        put_tree(opt_state, train_device) if train_device is not None else fabric.replicate(opt_state)
    )

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in AGGREGATOR_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    if cfg.buffer.size < rollout_steps:
        raise ValueError(
            f"The size of the buffer ({cfg.buffer.size}) cannot be lower "
            f"than the rollout steps ({rollout_steps})"
        )
    # the rollout is consumed in-place each update (on-policy); unlike the
    # reference there is no staging ReplayBuffer copy — host lists are the
    # only transient storage

    # fused on-policy collection (`algo.fused_rollout`): when the env has a
    # jittable twin the whole rollout+GAE+update runs as ONE dispatch; any
    # infeasibility falls back to the host loop with a telemetry breadcrumb
    fused_rollout = bool(cfg.algo.get("fused_rollout", False))
    overlap_collection = bool(cfg.algo.get("overlap_collection", False))
    reset_fused_fallback_warnings()
    fused_spec = None
    if fused_rollout:
        fused_spec = resolve_fused_rollout_spec(
            cfg, fabric, cnn_keys, mlp_keys, observation_space, is_continuous, is_multidiscrete, actions_dim
        )
        if fused_spec is not None and train_device is None and num_envs % world_size != 0:
            fused_fallback(
                "env_shard", f"env.num_envs ({num_envs}) must be divisible by the device count ({world_size})"
            )
            fused_spec = None
    if scenario_family is not None and fused_spec is None:
        # the agent may be built against the widened scenario obs and the host
        # loop cannot apply variants — fail loudly instead of silently
        # training the un-randomized base env
        raise RuntimeError(
            "env.variants requires the fused rollout path; set "
            "algo.fused_rollout=True (if it is set, the fused_fallback "
            "telemetry event names the gate that failed)"
        )
    # fused rollout subsumes overlap (there is no host collection to overlap)
    overlap_collection = overlap_collection and fused_spec is None

    train_fn = make_train_fn(
        fabric, agent, tx, cfg, obs_keys, n_local, host_device=train_device, donate_params=not overlap_collection
    )
    gae_fn = jax.jit(partial(gae, gamma=float(cfg.algo.gamma), gae_lambda=float(cfg.algo.gae_lambda)))
    superstep_fn = None
    if fused_spec is not None:
        use_mesh_fused = train_device is None
        superstep_fn = make_onpolicy_superstep_fn(
            fused_spec,
            policy_fn=partial(rollout_step, agent),
            value_fn=lambda p, o: agent.apply(p, o)[1],
            local_train=make_local_train(fabric, agent, tx, cfg, obs_keys, n_local, use_mesh=use_mesh_fused),
            obs_key=mlp_keys[0],
            rollout_steps=rollout_steps,
            step_increment=num_envs * fabric.num_processes,
            gamma=float(cfg.algo.gamma),
            gae_lambda=float(cfg.algo.gae_lambda),
            mesh=fabric.mesh if use_mesh_fused else None,
            data_axis=fabric.data_axis if use_mesh_fused else None,
        )

    # counters (reference ppo.py:214-231)
    start_update = (state["update"] + 1) if cfg.checkpoint.resume_from else 1
    policy_step = state["update"] * policy_steps_per_update if cfg.checkpoint.resume_from else 0
    last_log = state["last_log"] if cfg.checkpoint.resume_from else 0
    last_checkpoint = state["last_checkpoint"] if cfg.checkpoint.resume_from else 0
    train_step = 0
    last_train = 0

    key = jax.random.PRNGKey(int(cfg.seed))
    if cfg.checkpoint.resume_from and "rng_key" in state:
        # host numpy from the checkpoint; placed exactly once below
        key = np.asarray(state["rng_key"])
    if train_device is not None:
        # the train key chain lives on the train device: a mixed-device
        # committed-input set would error, and splitting on the remote chip
        # would re-insert a per-update round trip
        key = put_tree(key, train_device)
    elif cfg.checkpoint.resume_from and "rng_key" in state:
        key = jnp.asarray(key)
    # rollout action keys live on the player's device so a host-pinned
    # player never blocks on a chip round trip per env step
    player_key = put_tree(jax.random.fold_in(key, 1), player.device)
    if cfg.checkpoint.resume_from and "player_rng_key" in state:
        # continue the pre-resume action-sampling stream
        player_key = put_tree(jnp.asarray(state["player_rng_key"]), player.device)

    clip_coef = float(cfg.algo.clip_coef)
    ent_coef = float(cfg.algo.ent_coef)

    next_obs, _ = envs.reset(seed=cfg.seed)
    next_obs = prepare_obs(next_obs, cnn_keys=cnn_keys, num_envs=num_envs)

    # steady-state throughput probe (bench.py): updates 2..last, skipping the
    # compile-heavy first update — shared contract in utils.SteadyStateProbe
    from sheeprl_tpu.utils.utils import SteadyStateProbe

    def ckpt_state_fn(completed_update: int) -> Dict[str, Any]:
        # shared by the periodic save, the preemption drain's emergency save
        # and (structurally) the rollback restore — reads the loop's CURRENT
        # bindings at call time
        return {
            "agent": jax.device_get(params),
            "opt_state": jax.device_get(opt_state),
            "update": completed_update,
            "batch_size": int(cfg.algo.per_rank_batch_size) * world_size,
            "last_log": last_log,
            "last_checkpoint": last_checkpoint,
            "rng_key": jax.device_get(key),
            "player_rng_key": jax.device_get(player_key),
        }

    def ckpt_path_fn(step: int) -> str:
        return os.path.join(log_dir, "checkpoint", f"ckpt_{step}_{rank}.ckpt")

    # per-update blocks shared by the fused and host update loops; they read
    # the loop's CURRENT bindings at call time
    def rollback_state(at_update: int) -> None:
        # restore the newest committed checkpoint in place of the poisoned
        # params/opt state and fork the sample key away from the stream that
        # diverged — the loop's counters keep advancing so the run completes
        nonlocal params, opt_state, key
        restored = resil.rollback(update=at_update)
        params = resil.place_like(restored["agent"], params)
        opt_state = resil.place_like(restored["opt_state"], opt_state)
        if "rng_key" in restored:
            key = resil.place_like(restored["rng_key"], key)
        key = resil.resalt_key(key)
        player.update_params(params)

    def update_loss_metrics(metrics_np) -> None:
        if cfg.metric.log_level > 0:
            aggregator.update("Loss/policy_loss", float(metrics_np[0]))
            aggregator.update("Loss/value_loss", float(metrics_np[1]))
            aggregator.update("Loss/entropy_loss", float(metrics_np[2]))

    def maybe_heartbeat(final: bool) -> None:
        nonlocal last_log, last_train
        if cfg.metric.log_level > 0 and (policy_step - last_log >= cfg.metric.log_every or final):
            metrics_dict = aggregator.compute()
            logger.log_metrics(metrics_dict, policy_step)
            telemetry_run_metrics(metrics_dict)
            aggregator.reset()
            log_sps_and_heartbeat(
                logger,
                policy_step=policy_step,
                env_steps=(policy_step - last_log) * cfg.env.action_repeat,
                train_steps=train_step - last_train,
                train_invocations=(train_step - last_train) // world_size,
            )
            last_log = policy_step
            last_train = train_step

    def anneal_coefs() -> None:
        # anneal coefficients (reference ppo.py:414-424)
        nonlocal clip_coef, ent_coef
        if cfg.algo.anneal_clip_coef:
            clip_coef = polynomial_decay(
                update, initial=initial_clip_coef, final=0.0, max_decay_steps=num_updates, power=1.0
            )
        if cfg.algo.anneal_ent_coef:
            ent_coef = polynomial_decay(
                update, initial=initial_ent_coef, final=0.0, max_decay_steps=num_updates, power=1.0
            )

    def maybe_checkpoint() -> None:
        nonlocal last_checkpoint
        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            update == num_updates and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            fabric.call("on_checkpoint_coupled", ckpt_path=ckpt_path_fn(policy_step), state=ckpt_state_fn(update))

    # a crash anywhere in the loop gets the preemption treatment too: the
    # lambdas read the loop's CURRENT policy_step/update at crash time
    resil.arm_crash_guard(
        path_fn=lambda: ckpt_path_fn(policy_step),
        state_fn=lambda: ckpt_state_fn(update - 1),
    )
    preempted = False
    probe = SteadyStateProbe()
    if superstep_fn is not None:
        # ------------------------------------------------------------------
        # fused on-policy path: rollout + GAE + epochs x minibatches update
        # compile into ONE donated jit — the metrics fetch below is the only
        # host sync per update (the vector env above stays reset-only; it
        # provides spaces for the agent and the eval env at the end)
        # ------------------------------------------------------------------
        # env reset/transition stream is rooted off the run seed, salted away
        # from the action/train key streams (ops/rollout_scan.py discipline)
        if use_mesh_fused:
            # pin the inputs to the exact shardings the superstep outputs —
            # an uncommitted first-call carry/key would make call 2 (committed
            # jit outputs) re-lower the whole fused program, putting a second
            # multi-second compile inside the measured steady-state window
            def place_carry(carry):
                return jax.tree.map(lambda x: jax.device_put(x, fabric.batch_sharding), carry)

            key = jax.device_put(key, fabric.replicated)
        else:

            def place_carry(carry):
                return put_tree(carry, train_device)

        # one scenario row per env for the run's lifetime: domain
        # randomization persists across autoresets and update boundaries
        thetas = (
            scenario_theta_matrix(cfg, fused_spec, num_envs)
            if isinstance(fused_spec, ScenarioFamily)
            else None
        )
        env_carry = place_carry(
            init_env_carry(
                fused_spec,
                num_envs,
                jax.random.fold_in(jax.random.PRNGKey(int(cfg.seed)), ENV_STREAM_SALT),
                thetas=thetas,
            )
        )
        steps_per_dispatch = update_epochs * num_minibatches
        for update in range(start_update, num_updates + 1):
            telemetry_advance(policy_step)
            if resil.preempt_requested():
                last_checkpoint = policy_step
                resil.emergency_checkpoint(ckpt_path_fn(policy_step), ckpt_state_fn(update - 1))
                preempted = True
                break
            if update == start_update + 1:
                probe.mark(policy_step)
            # same fold schedule as the host player: rollout_actions folds
            # policy_step on top of the per-update key inside the superstep
            update_key = jax.random.fold_in(player_key, update)
            step_before = policy_step
            with timer("Time/env_interaction_time"):
                params, opt_state, env_carry, key, metrics, ep_stats = superstep_fn(
                    params,
                    opt_state,
                    env_carry,
                    update_key,
                    key,
                    np.uint32(step_before),
                    np.float32(clip_coef),
                    np.float32(ent_coef),
                )
                policy_step += policy_steps_per_update
                metrics = np.asarray(metrics)
            telemetry_train_window(1, steps_per_dispatch)
            if not resil.check_finite(metrics, update):
                rollback_state(update)
                # fresh episodes: poisoned params may have driven the carried
                # env state non-finite too
                env_carry = place_carry(
                    init_env_carry(fused_spec, num_envs, jax.random.fold_in(key, update), thetas=thetas)
                )
                continue
            train_step += world_size
            if update == start_update:
                # one dispatch covers collection AND all gradient steps, so
                # scale the program flops down to per-gradient-step for MFU
                telemetry_register_flops(
                    superstep_fn,
                    params,
                    opt_state,
                    env_carry,
                    update_key,
                    key,
                    np.uint32(step_before),
                    np.float32(clip_coef),
                    np.float32(ent_coef),
                    scale=1.0 / steps_per_dispatch,
                )
            if cfg.metric.log_level > 0:
                # one fetch of the per-step episode flags replaces the host
                # loop's final_info plumbing
                ep_done = np.asarray(ep_stats["done"])
                finished = np.nonzero(ep_done)
                if finished[0].size:
                    finished_rets = np.asarray(ep_stats["ret"])[finished]
                    for r in finished_rets:
                        aggregator.update("Rewards/rew_avg", float(r))
                    for length in np.asarray(ep_stats["len"])[finished]:
                        aggregator.update("Game/ep_len_avg", float(length))
                    # same per-episode evidence lines as the host loop — the
                    # learning-check recipes (benchmarks/learning_checks.sh,
                    # tools/sweep.py) grep these for the reward trend
                    for i, r in zip(finished[-1], finished_rets):
                        print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={float(r)}")
            update_loss_metrics(metrics)
            maybe_heartbeat(update == num_updates)
            anneal_coefs()
            maybe_checkpoint()
        # the player sampled nothing during the fused loop; publish the final
        # params once for the eval rollout below
        player.update_params(params)
    else:
        # ------------------------------------------------------------------
        # host loop: jitted player per env step + fused update per window
        # ------------------------------------------------------------------
        pending = None  # overlap_collection: (device metrics, update index) in flight
        # double-buffer under overlap: the async dispatch may still read
        # update N's arrays (jax can alias host numpy zero-copy on CPU) while
        # the loop writes N+1
        store = RolloutStore(rollout_steps, slots=2 if overlap_collection else 1)
        # host-synchronized dispatches per update: T player steps + the
        # next-values critic call + GAE + the fused train step — the contrast
        # the fused path's 1-per-update counter is measured against
        host_dispatches_per_update = rollout_steps + 3

        def finalize_pending() -> bool:
            # the overlap path's ONE sync point: wait for the in-flight
            # update's metrics (attributed to train-wait, not collection),
            # run the NaN sentinel, then hand the already-dispatched params
            # to the player — collection keeps running one update stale and
            # the PPO ratio corrects against the stored logprobs
            nonlocal pending, train_step
            if pending is None:
                return True
            pending_metrics, pending_update = pending
            pending = None
            with timer("Time/train_wait_time"):
                metrics_np = np.asarray(pending_metrics)
            if not resil.check_finite(metrics_np, pending_update):
                rollback_state(pending_update)
                return False
            player.update_params(params)
            train_step += world_size
            update_loss_metrics(metrics_np)
            return True

        for update in range(start_update, num_updates + 1):
            telemetry_advance(policy_step)
            if resil.preempt_requested():
                # update has NOT run yet: the emergency checkpoint records
                # update-1 so auto-resume replays from exactly this boundary
                last_checkpoint = policy_step
                resil.emergency_checkpoint(ckpt_path_fn(policy_step), ckpt_state_fn(update - 1))
                preempted = True
                break
            if update == start_update + 1:
                probe.mark(policy_step)
            buf = store.begin(update)
            with timer("Time/env_interaction_time"):
                # one jitted dispatch + ONE device->host fetch per env step: key
                # folding, sampling and the one-hot->index conversion are fused
                # (agent.rollout_step); the base key crosses to the player device
                # once per update. Over a remote-attached TPU separate fetches
                # would cost ~100ms each; on the 1-core host the saved dispatches
                # are a measurable slice of the step budget.
                # fold the update index into the base key so action-stream
                # uniqueness holds even if policy_step bookkeeping ever repeats a
                # value across a resume (rollout_actions folds policy_step on top)
                update_key = jax.random.fold_in(player_key, update)
                for t in range(rollout_steps):
                    policy_step += num_envs * fabric.num_processes
                    actions, real_actions, logprobs, values = player.rollout_actions(
                        next_obs, update_key, policy_step
                    )
                    actions_np, real_actions, logprobs_np, values_np = jax.device_get(
                        (actions, real_actions, logprobs, values)
                    )
                    if not is_continuous and real_actions.shape[-1] == 1 and not is_multidiscrete:
                        real_actions = real_actions[..., 0]

                    obs, rewards, terminated, truncated, info = envs.step(
                        real_actions.reshape(envs.action_space.shape)
                    )
                    rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, 1)

                    # truncation bootstrap (reference ppo.py:286-305)
                    truncated_envs = np.nonzero(truncated)[0]
                    if len(truncated_envs) > 0 and "final_obs" in info:
                        final_obs = {
                            k: np.stack([np.asarray(info["final_obs"][e][k]) for e in truncated_envs])
                            for k in obs_keys
                        }
                        final_obs = prepare_obs(final_obs, cnn_keys=cnn_keys, num_envs=len(truncated_envs))
                        vals = np.asarray(player.get_values(final_obs)).reshape(len(truncated_envs))
                        rewards[truncated_envs, 0] += float(cfg.algo.gamma) * vals

                    dones = np.logical_or(terminated, truncated).reshape(num_envs, 1).astype(np.float32)
                    # in-place writes into the preallocated [T, ...] arrays —
                    # the write is the copy; no list-append + np.stack pass
                    step_values = {k: next_obs[k] for k in obs_keys}
                    step_values["dones"] = dones
                    step_values["values"] = values_np
                    step_values["actions"] = actions_np
                    step_values["logprobs"] = logprobs_np
                    step_values["rewards"] = rewards
                    buf.put(t, step_values)

                    next_obs = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=num_envs)

                    if cfg.metric.log_level > 0 and "final_info" in info:
                        ep = info["final_info"].get("episode")
                        if ep is not None:
                            for i in np.nonzero(ep.get("_r", []))[0]:
                                aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                                aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                                print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

            local_data = buf.arrays()  # [T, E, ...]

            # GAE on the player's device (reference ppo.py:345-360) — rollout
            # arrays are host-side already, so with a host-pinned player the
            # whole advantage pass stays off the chip's round-trip path
            next_values = np.asarray(player.get_values(next_obs))  # [E, 1]
            returns, advantages = gae_fn(
                put_tree(local_data["rewards"], player.device),
                put_tree(local_data["values"], player.device),
                put_tree(local_data["dones"], player.device),
                put_tree(next_values, player.device),
            )
            local_data["returns"] = np.asarray(returns)
            local_data["advantages"] = np.asarray(advantages)

            # flatten [T, E, ...] -> [T*E, ...]; shard_map splits over devices;
            # multi-host runs assemble the per-process blocks into a global array
            flat = {k: v.reshape(v.shape[0] * v.shape[1], *v.shape[2:]) for k, v in local_data.items()}
            if fabric.num_processes > 1:
                flat = fabric.make_global(flat, (fabric.data_axis,))

            telemetry_train_window(host_dispatches_per_update, update_epochs * num_minibatches)
            if overlap_collection and not finalize_pending():
                # the in-flight update rolled back; this rollout was collected
                # against the poisoned stream, drop it too
                continue

            with timer("Time/train_time"):
                key, train_key = jax.random.split(key)
                params, opt_state, metrics = train_fn(
                    params,
                    opt_state,
                    flat,
                    train_key,
                    # host numpy scalars: jnp.float32 would materialize them on
                    # the DEFAULT backend every update — with a host-pinned train
                    # device on a remote chip that is a blocking link fetch per
                    # update, more than the round trips host-training saves
                    np.float32(clip_coef),
                    np.float32(ent_coef),
                )
                if not overlap_collection:
                    # ONE fetch syncs the dispatch and serves both the NaN
                    # sentinel and the aggregator scalars below (the old
                    # block_until_ready + asarray pair was two device syncs)
                    metrics = np.asarray(metrics)
            if update == start_update:
                # shapes are fixed from here on; register the MFU flops source
                # off the first real invocation (resolved lazily at heartbeat)
                telemetry_register_flops(
                    train_fn, params, opt_state, flat, train_key, np.float32(clip_coef), np.float32(ent_coef)
                )
            if overlap_collection:
                # do NOT wait: the next collection overlaps this update's
                # device execution; the player keeps the stale params
                pending = (metrics, update)
            else:
                if not resil.check_finite(metrics, update):
                    rollback_state(update)
                    continue
                player.update_params(params)
                train_step += world_size
                update_loss_metrics(metrics)

            maybe_heartbeat(update == num_updates)
            anneal_coefs()
            maybe_checkpoint()

        # drain the last in-flight update so its params/metrics are committed
        # before eval and the final checkpointed state
        finalize_pending()

    # the params fetch is a real device sync (everything dispatched before
    # it has executed once it materializes)
    probe.finish(policy_step, sync=lambda: jax.device_get(jax.tree.leaves(params)[0]))
    envs.close()
    if fabric.is_global_zero and cfg.algo.run_test and not preempted:
        if obs_widened:
            # the agent expects the scenario family's widened observation; the
            # host eval env emits the base one — there is nothing to evaluate
            warnings.warn("skipping run_test: env.variants widened the observation past the host env's")
        else:
            test(player, fabric, cfg, log_dir)
    logger.finalize()
    resil.close()
    if preempted:
        resil.exit_preempted()
