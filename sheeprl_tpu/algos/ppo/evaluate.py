"""PPO evaluation entrypoint (reference: sheeprl/algos/ppo/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym

from sheeprl_tpu.algos.ppo.agent import PPOPlayer, build_agent
from sheeprl_tpu.algos.ppo.utils import test
from sheeprl_tpu.envs import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["ppo", "ppo_decoupled"])
def evaluate(fabric, cfg: Dict[str, Any], state: Dict[str, Any]) -> None:
    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    observation_space = env.observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    action_space = env.action_space
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    env.close()

    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, observation_space, state["agent"])
    player = PPOPlayer(agent, params)
    test(player, fabric, cfg, log_dir)
    logger.finalize()
