"""PPO agent (reference: sheeprl/algos/ppo/agent.py:19-298).

flax re-design: one ``PPOAgent`` module whose params are a single pytree.
The reference's separate DDP-wrapped trainer and single-device player
(agent.py:254-298, weight tying at :292-297) collapse into "the same params
used by two jitted functions" — replication across the mesh *is* the weight
tying. Pixel inputs are NHWC uint8 and are normalized to [-0.5, 0.5] inside
the module, so only bytes cross PCIe.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.models import MLP, NatureCNN
from sheeprl_tpu.ops.distributions import Categorical, Independent, Normal
from sheeprl_tpu.parallel.fabric import HostPlayerParams, put_tree

Array = jax.Array


class CNNEncoder(nn.Module):
    """Concat pixel keys on channels -> NatureCNN (reference agent.py:19-35)."""

    keys: Tuple[str, ...]
    features_dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, Array]) -> Array:
        imgs = [obs[k].astype(self.dtype) / 255.0 - 0.5 for k in self.keys]
        x = jnp.concatenate(imgs, axis=-1)
        return NatureCNN(features_dim=self.features_dim, dtype=self.dtype)(x)


class MLPEncoder(nn.Module):
    """Concat vector keys -> MLP (reference agent.py:38-64)."""

    keys: Tuple[str, ...]
    features_dim: Optional[int]
    dense_units: int = 64
    mlp_layers: int = 2
    dense_act: str = "relu"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, Array]) -> Array:
        x = jnp.concatenate([obs[k].astype(self.dtype) for k in self.keys], axis=-1)
        return MLP(
            hidden_sizes=(self.dense_units,) * self.mlp_layers,
            output_dim=self.features_dim,
            activation=self.dense_act,
            norm_layer="layer_norm" if self.layer_norm else None,
            dtype=self.dtype,
        )(x)


class PPOAgent(nn.Module):
    """Shared encoder, actor backbone + per-space heads, critic
    (reference agent.py:79-152). ``__call__`` returns raw head outputs; the
    sampling/log-prob math lives in :func:`evaluate_actions` /
    :func:`sample_actions` so the same module serves training and play."""

    actions_dim: Tuple[int, ...]
    is_continuous: bool
    cnn_keys: Tuple[str, ...]
    mlp_keys: Tuple[str, ...]
    cnn_features_dim: int = 512
    mlp_features_dim: Optional[int] = 64
    encoder_units: int = 64
    encoder_layers: int = 2
    actor_units: int = 64
    actor_layers: int = 2
    critic_units: int = 64
    critic_layers: int = 2
    dense_act: str = "tanh"
    layer_norm: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs: Dict[str, Array]) -> Tuple[List[Array], Array]:
        feats = []
        if self.cnn_keys:
            feats.append(CNNEncoder(self.cnn_keys, self.cnn_features_dim, dtype=self.dtype)(obs))
        if self.mlp_keys:
            feats.append(
                MLPEncoder(
                    self.mlp_keys,
                    self.mlp_features_dim,
                    self.encoder_units,
                    self.encoder_layers,
                    self.dense_act,
                    self.layer_norm,
                    dtype=self.dtype,
                )(obs)
            )
        feat = feats[0] if len(feats) == 1 else jnp.concatenate(feats, axis=-1)

        critic = MLP(
            hidden_sizes=(self.critic_units,) * self.critic_layers,
            output_dim=1,
            activation=self.dense_act,
            norm_layer="layer_norm" if self.layer_norm else None,
            dtype=self.dtype,
            name="critic",
        )(feat)

        x = MLP(
            hidden_sizes=(self.actor_units,) * self.actor_layers,
            output_dim=None,
            activation=self.dense_act,
            norm_layer="layer_norm" if self.layer_norm else None,
            dtype=self.dtype,
            name="actor_backbone",
        )(feat)
        if self.is_continuous:
            # single head emitting mean ++ log_std (reference agent.py:148-149)
            heads = [nn.Dense(sum(self.actions_dim) * 2, dtype=self.dtype, name="actor_head_0")(x)]
        else:
            heads = [
                nn.Dense(d, dtype=self.dtype, name=f"actor_head_{i}")(x) for i, d in enumerate(self.actions_dim)
            ]
        return heads, critic.astype(jnp.float32)


def _dists(agent: PPOAgent, actor_out: List[Array]):
    if agent.is_continuous:
        mean, log_std = jnp.split(actor_out[0].astype(jnp.float32), 2, axis=-1)
        return [Independent(Normal(mean, jnp.exp(log_std)), 1)]
    return [Categorical(logits=h.astype(jnp.float32)) for h in actor_out]


def sample_actions(
    agent: PPOAgent,
    params: Any,
    obs: Dict[str, Array],
    key: Array,
    greedy: bool = False,
) -> Tuple[Array, Array, Array]:
    """Rollout-time policy (reference PPOPlayer.forward, agent.py:201-224).

    Returns ``(actions, logprobs[B,1], values[B,1])`` where ``actions`` is
    the concatenated one-hot (discrete) or raw (continuous) action vector —
    the buffer layout the reference stores.
    """
    actor_out, values = agent.apply(params, obs)
    dists = _dists(agent, actor_out)
    keys = jax.random.split(key, len(dists))
    if agent.is_continuous:
        d = dists[0]
        act = d.mode if greedy else d.sample(seed=keys[0])
        logprob = d.log_prob(act)[..., None]
        return act, logprob, values
    samples = [
        (d.mode if greedy else d.sample(seed=k)) for d, k in zip(dists, keys)
    ]  # integer class indices per sub-space
    logprob = sum(d.log_prob(s) for d, s in zip(dists, samples))[..., None]
    onehots = [jax.nn.one_hot(s, dim, dtype=jnp.float32) for s, dim in zip(samples, agent.actions_dim)]
    return jnp.concatenate(onehots, axis=-1), logprob, values


def evaluate_actions(
    agent: PPOAgent,
    params: Any,
    obs: Dict[str, Array],
    actions: Array,
) -> Tuple[Array, Array, Array]:
    """Train-time re-evaluation of stored actions (reference
    PPOAgent.forward with actions, agent.py:154-191). Returns
    ``(logprobs[B,1], entropy[B,1], values[B,1])``."""
    actor_out, values = agent.apply(params, obs)
    dists = _dists(agent, actor_out)
    if agent.is_continuous:
        d = dists[0]
        return d.log_prob(actions)[..., None], d.entropy()[..., None], values
    splits = np.cumsum(agent.actions_dim)[:-1]
    onehot_parts = jnp.split(actions, splits, axis=-1)
    idx_parts = [jnp.argmax(p, axis=-1) for p in onehot_parts]
    logprob = sum(d.log_prob(i) for d, i in zip(dists, idx_parts))[..., None]
    entropy = sum(d.entropy() for d in dists)[..., None]
    return logprob, entropy, values


def real_actions_from_onehot(actions_dim: Sequence[int], is_continuous: bool, actions: Array) -> Array:
    """Concatenated one-hot action vector → per-part env indices (identity
    for continuous) — the in-graph twin of the host-side conversion every
    rollout used to pay in numpy."""
    if is_continuous:
        return actions
    splits = np.cumsum(np.asarray(actions_dim))[:-1].tolist()
    parts = jnp.split(actions, splits, axis=-1)
    return jnp.stack([p.argmax(-1) for p in parts], axis=-1)


def rollout_step(agent: PPOAgent, params: Any, obs: Dict[str, Array], key: Array):
    """One fused rollout-time policy call: sample + the one-hot→index
    conversion the env needs, in a single XLA program. On a 1-core host the
    per-step budget is milliseconds, so the separate dispatches the naive
    loop pays (key split, sample, numpy argmax/split per action part) are a
    measurable fraction of the whole rollout — this folds them into one."""
    actions, logprob, values = sample_actions(agent, params, obs, key)
    real_actions = real_actions_from_onehot(agent.actions_dim, agent.is_continuous, actions)
    return actions, real_actions, logprob, values


class PPOPlayer(HostPlayerParams):
    """Host-side convenience handle for rollout/eval: module + params with
    jitted action/value functions (reference PPOPlayer, agent.py:194-251).

    ``device`` optionally pins inference to the host CPU backend so env
    stepping never waits on a remote-chip round trip; ``update_params``
    streams learner params across (see ``parallel.fabric.resolve_player_device``)."""

    _placed_attrs = ("params",)

    def __init__(self, agent: PPOAgent, params: Any, device: Optional[Any] = None) -> None:
        self.agent = agent
        self.device = device  # must precede the params assignment
        self.params = params
        self._sample = jax.jit(
            lambda p, o, k, greedy: sample_actions(agent, p, o, k, greedy), static_argnames="greedy"
        )
        self._values = jax.jit(lambda p, o: agent.apply(p, o)[1])
        # fused rollout step: key folding (counter -> fresh stream, no host
        # split dispatch) + sample + real-action conversion in one program
        self._rollout = jax.jit(
            lambda p, o, k, c: rollout_step(agent, p, o, jax.random.fold_in(k, c))
        )

    def update_params(self, params: Any) -> None:
        self.params = params

    def get_actions(self, obs: Dict[str, Array], key: Array, greedy: bool = False):
        return self._sample(self.params, obs, put_tree(key, self.device), greedy)

    def rollout_actions(self, obs: Dict[str, Array], key: Array, counter) -> Any:
        """(actions, real_actions, logprobs, values) for one env step; the
        per-step stream is ``fold_in(key, counter)`` so the base key crosses
        to the player device once per update, not once per step."""
        return self._rollout(self.params, obs, key, counter)

    def get_values(self, obs: Dict[str, Array]) -> Array:
        return self._values(self.params, obs)


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    agent_state: Optional[Any] = None,
) -> Tuple[PPOAgent, Any]:
    """Construct the module and init/replicate its params
    (reference build_agent, agent.py:254-298). Returns ``(agent, params)``;
    the caller wraps params in a train state and/or a PPOPlayer — both see
    the same pytree, which is the weight tying of agent.py:292-297."""
    algo = cfg["algo"]
    agent = PPOAgent(
        actions_dim=tuple(int(d) for d in actions_dim),
        is_continuous=bool(is_continuous),
        cnn_keys=tuple(algo["cnn_keys"]["encoder"]),
        mlp_keys=tuple(algo["mlp_keys"]["encoder"]),
        cnn_features_dim=int(algo["encoder"]["cnn_features_dim"]),
        mlp_features_dim=algo["encoder"]["mlp_features_dim"],
        encoder_units=int(algo["encoder"]["dense_units"]),
        encoder_layers=int(algo["encoder"]["mlp_layers"]),
        actor_units=int(algo["actor"]["dense_units"]),
        actor_layers=int(algo["actor"]["mlp_layers"]),
        critic_units=int(algo["critic"]["dense_units"]),
        critic_layers=int(algo["critic"]["mlp_layers"]),
        dense_act=str(algo["dense_act"]),
        layer_norm=bool(algo["layer_norm"]),
        dtype=fabric.precision.compute_dtype,
    )
    if agent_state is not None:
        params = jax.tree.map(jnp.asarray, agent_state)
    else:
        dummy_obs = {}
        for k in agent.cnn_keys:
            shape = obs_space[k].shape  # [S,H,W,C] (stacked) or [H,W,C]
            if len(shape) == 4:
                s, h, w, c = shape
                shape = (h, w, s * c)
            dummy_obs[k] = jnp.zeros((1, *shape), dtype=jnp.uint8)
        for k in agent.mlp_keys:
            dummy_obs[k] = jnp.zeros((1, *obs_space[k].shape), dtype=jnp.float32)
        params = agent.init(jax.random.PRNGKey(int(cfg["seed"])), dummy_obs)
    params = jax.tree.map(lambda x: x.astype(fabric.precision.param_dtype), params)
    return agent, fabric.replicate(params)
