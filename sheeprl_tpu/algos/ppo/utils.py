"""PPO helpers (reference: sheeprl/algos/ppo/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

from sheeprl_tpu.obs.telemetry import telemetry_deliberate_compiles
import jax
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
}
MODELS_TO_REGISTER = {"agent"}


def prepare_obs(
    obs: Dict[str, np.ndarray], cnn_keys: Sequence[str] = (), num_envs: int = 1
) -> Dict[str, np.ndarray]:
    """Shape env observations for the agent (reference utils.py prepare_obs):
    fold a frame-stack axis into channels (``[E,S,H,W,C] -> [E,H,W,S*C]``)
    and ensure a leading batch axis. Pixel dtype stays uint8 — the agent
    normalizes on device."""
    out: Dict[str, np.ndarray] = {}
    for k, v in obs.items():
        v = np.asarray(v)
        if k in cnn_keys:
            if v.ndim == 3:  # single env, unstacked [H,W,C]
                v = v[None]
            if v.ndim == 4 and v.shape[0] != num_envs:  # [S,H,W,C] single env stack
                v = v[None]
            if v.ndim == 5:  # [E,S,H,W,C] -> [E,H,W,S*C]
                e, s, h, w, c = v.shape
                v = np.moveaxis(v, 1, 3).reshape(e, h, w, s * c)
        else:
            if v.ndim == 1:
                v = v[None]
            v = v.astype(np.float32)
        out[k] = v
    return out


# the eval rollout compiles fresh programs (eval batch shapes) after the
# loop's warm point; that is a deliberate one-time compile, not a retrace
@telemetry_deliberate_compiles("eval_rollout")
def test(player: Any, fabric: Any, cfg: Dict[str, Any], log_dir: str) -> None:
    """Greedy evaluation episode (reference utils.py test): runs one episode
    and logs Test/cumulative_reward."""
    from sheeprl_tpu.envs import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    done = False
    cumulative_rew = 0.0
    key = jax.random.PRNGKey(cfg.seed)
    obs, _ = env.reset(seed=cfg.seed)
    while not done:
        key, sub = jax.random.split(key)
        torch_obs = prepare_obs(obs, cnn_keys=cfg.algo.cnn_keys.encoder)
        actions, _, _ = player.get_actions(torch_obs, sub, greedy=True)
        actions = np.asarray(actions)
        if player.agent.is_continuous:
            real_actions = actions[0]
        else:
            splits = np.cumsum(player.agent.actions_dim)[:-1]
            real_actions = np.array([p.argmax(-1) for p in np.split(actions[0], splits, axis=-1)])
            if len(real_actions) == 1:
                real_actions = real_actions[0]
        obs, reward, terminated, truncated, _ = env.step(real_actions)
        done = terminated or truncated or cfg.dry_run
        cumulative_rew += float(reward)
    fabric_print = getattr(fabric, "print", print)
    fabric_print(f"Test - Reward: {cumulative_rew}")
    if cfg.metric.log_level > 0 and getattr(fabric, "logger", None) is not None:
        fabric.logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    env.close()


def normalize_obs(
    obs: Dict[str, Any], cnn_keys: Sequence[str], obs_keys: Sequence[str]
) -> Dict[str, Any]:
    """Reference utils.py normalize_obs — here a passthrough selector: pixel
    normalization happens inside the agent module (agent.py CNNEncoder)."""
    return {k: obs[k] for k in obs_keys}


def log_models_from_checkpoint(fabric, cfg, state, artifacts_dir):
    """Pickle this algorithm's registered sub-models from a checkpoint
    (reference per-algo log_models_from_checkpoint; shared body in
    utils/model_manager.py)."""
    from sheeprl_tpu.utils.model_manager import log_models_from_checkpoint as _log

    return _log(state, sorted(MODELS_TO_REGISTER), artifacts_dir)
