"""PPO, decoupled player/trainer topology (reference:
sheeprl/algos/ppo/ppo_decoupled.py:33-669) — TPU-native.

Role split (reference :645-669): process 0 is the PLAYER — it owns the
environments, rolls out, computes GAE, and ships the rollout; processes
1..N-1 are TRAINERS — they form their own ``jax.sharding.Mesh``
(``parallel.submesh``) and run the same fused epochs x minibatches update as
coupled PPO with gradient ``pmean`` over the trainer mesh (the reference's
DDP over ``optimization_pg``, :581-584).

Exchanges ride the host-object plane (``parallel.collectives``), replacing
the reference's TorchCollective scatter/broadcast (:297-308):

- rollout:  ``broadcast_object(flat_data, src=0)`` — each trainer slices its
  device-share (the reference's chunk scatter, :297-302),
- params:   ``broadcast_object((params, metrics[, opt_state]), src=1)`` —
  the flat-vector broadcast of :304-308, plus trainer metrics and, on
  checkpoint updates, the optimizer state for the player-side save
  (reference on_checkpoint_player, callback.py:58-78).

Both roles derive the number of updates and the checkpoint schedule from the
same config, so no stop sentinel is needed (the reference scatters ``-1``,
:463-484). Initial params are identical by construction — every process
seeds the same ``PRNGKey`` — replacing the startup broadcast (:126-130).

**Single-process dispatch:** without a ``jax.distributed`` process group the
entrypoint decouples within the host instead — supervised actor
subprocesses (CPU jax) stream trajectory slabs over a torn-write-safe
shared-memory ring while this process trains continuously with
staleness-bounded admission and a versioned param broadcast back
(``sheeprl_tpu.actor_learner``, ``howto/actor_learner.md``).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.ppo.agent import PPOPlayer, build_agent
from sheeprl_tpu.algos.ppo.ppo import make_train_fn
from sheeprl_tpu.algos.ppo.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.config.compose import instantiate
from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.ops.math import gae
from sheeprl_tpu.parallel.collectives import broadcast_object
from sheeprl_tpu.parallel.submesh import LocalFabric, SubMeshFabric, probe_spaces
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import polynomial_decay, save_configs


def _trainer_devices():
    devs = [d for d in jax.devices() if d.process_index >= 1]
    if not devs:
        raise RuntimeError(
            "ppo_decoupled needs at least 2 processes (player + trainers); "
            "launch with jax.distributed (SHEEPRL_TPU_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID)"
        )
    return devs


def _ckpt_schedule(cfg, num_updates, policy_steps_per_update, start_update=1, last_checkpoint=0):
    """The (deterministic) set of updates that checkpoint — shared by both
    roles so the opt-state shipping lines up. On resume the walk restarts
    from the checkpointed update with the saved step accounting."""
    do = set()
    last = last_checkpoint
    step = (start_update - 1) * policy_steps_per_update
    for update in range(start_update, num_updates + 1):
        step += policy_steps_per_update
        if (cfg.checkpoint.every > 0 and step - last >= cfg.checkpoint.every) or (
            update == num_updates and cfg.checkpoint.save_last
        ):
            last = step
            do.add(update)
    return do


@register_algorithm(decoupled=True)
def main(fabric, cfg: Dict[str, Any]):
    # every process reads the checkpoint itself (reference
    # ppo_decoupled.py:45-46,104-116: both roles restore from the same file)
    state = fabric.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    if jax.process_count() < 2:
        # no jax.distributed process group: decouple WITHIN the host instead —
        # supervised actor subprocesses (CPU jax) stream trajectory slabs over
        # a shared-memory ring while this process trains continuously
        # (actor_learner package; lazy import keeps the multi-process roles
        # free of the transport's dependencies)
        from sheeprl_tpu.actor_learner.learner import run_actor_learner

        return run_actor_learner(fabric, cfg, state)
    if jax.process_index() == 0:
        _player(fabric, cfg, state)
    else:
        _trainer(fabric, cfg, state)


def _common_setup(fabric, cfg):
    num_envs = int(cfg.env.num_envs)
    rollout_steps = int(cfg.algo.rollout_steps)
    trainer_devs = _trainer_devices()
    n_global = rollout_steps * num_envs
    if n_global % len(trainer_devs) != 0:
        raise ValueError(
            f"rollout_steps*num_envs ({n_global}) must be divisible by the trainer device count "
            f"({len(trainer_devs)})"
        )
    policy_steps_per_update = num_envs * rollout_steps
    num_updates = int(cfg.algo.total_steps) // policy_steps_per_update if not cfg.dry_run else 1
    return num_envs, rollout_steps, trainer_devs, n_global, policy_steps_per_update, num_updates


def _player(fabric, cfg, state=None):
    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")

    num_envs, rollout_steps, trainer_devs, n_global, policy_steps_per_update, num_updates = _common_setup(
        fabric, cfg
    )
    start_update = state["update"] + 1 if state else 1
    ckpt_updates = _ckpt_schedule(
        cfg,
        num_updates,
        policy_steps_per_update,
        start_update=start_update,
        last_checkpoint=state["last_checkpoint"] if state else 0,
    )

    envs = build_vector_env(cfg, 0, log_dir, "train")
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )

    # identical deterministic init on every process replaces the reference's
    # startup param broadcast (:126-130); on resume all roles restore the
    # same checkpointed params instead
    agent, params = build_agent(
        LocalFabric(fabric), actions_dim, is_continuous, cfg, observation_space, state["agent"] if state else None
    )
    from sheeprl_tpu.parallel.fabric import _ParamStreamer, resolve_player_device

    player = PPOPlayer(
        agent, params, device=resolve_player_device(cfg.algo.get("player_device", "auto"))
    )
    # flat-vector receive lane: the trainer ships ONE uint8 array; the split
    # back into the param tree runs on the player's own device
    unpack_lane = _ParamStreamer(jax.device_get(params), player.device or jax.devices()[0])

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in AGGREGATOR_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    gae_fn = jax.jit(partial(gae, gamma=float(cfg.algo.gamma), gae_lambda=float(cfg.algo.gae_lambda)))

    policy_step = (start_update - 1) * policy_steps_per_update
    last_log = state["last_log"] if state else 0
    key = jax.random.PRNGKey(int(cfg.seed))
    if state and "rng_key" in state:
        key = jnp.asarray(state["rng_key"])
    # action keys live on the player's device so a host-pinned player
    # never blocks on a chip round trip per env step
    from sheeprl_tpu.parallel.fabric import put_tree as _put_tree

    player_key = _put_tree(jax.random.fold_in(key, 1), player.device)
    if state and "player_rng_key" in state:
        # continue the pre-resume action-sampling stream
        player_key = _put_tree(jnp.asarray(state["player_rng_key"]), player.device)
    next_obs, _ = envs.reset(seed=cfg.seed)
    next_obs = prepare_obs(next_obs, cnn_keys=cnn_keys, num_envs=num_envs)

    for update in range(start_update, num_updates + 1):
        rollout = {k: [] for k in (*obs_keys, "dones", "values", "actions", "logprobs", "rewards")}
        with timer("Time/env_interaction_time"):
            # fused rollout step (agent.rollout_step): one jitted dispatch +
            # one device->host fetch per env step, keys folded in-graph
            update_key = player_key
            for _ in range(rollout_steps):
                policy_step += num_envs
                actions, real_actions, logprobs, values = player.rollout_actions(
                    next_obs, update_key, policy_step
                )
                actions_np, real_actions, logprobs_np, values_np = jax.device_get(
                    (actions, real_actions, logprobs, values)
                )
                if not is_continuous and real_actions.shape[-1] == 1 and not is_multidiscrete:
                    real_actions = real_actions[..., 0]

                obs, rewards, terminated, truncated, info = envs.step(
                    real_actions.reshape(envs.action_space.shape)
                )
                rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, 1)
                truncated_envs = np.nonzero(truncated)[0]
                if len(truncated_envs) > 0 and "final_obs" in info:
                    final_obs = {
                        k: np.stack([np.asarray(info["final_obs"][e][k]) for e in truncated_envs])
                        for k in obs_keys
                    }
                    final_obs = prepare_obs(final_obs, cnn_keys=cnn_keys, num_envs=len(truncated_envs))
                    vals = np.asarray(player.get_values(final_obs)).reshape(len(truncated_envs))
                    rewards[truncated_envs, 0] += float(cfg.algo.gamma) * vals
                dones = np.logical_or(terminated, truncated).reshape(num_envs, 1).astype(np.float32)

                for k in obs_keys:
                    rollout[k].append(next_obs[k])
                rollout["dones"].append(dones)
                rollout["values"].append(values_np)
                rollout["actions"].append(actions_np)
                rollout["logprobs"].append(logprobs_np)
                rollout["rewards"].append(rewards)
                next_obs = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=num_envs)

                if cfg.metric.log_level > 0 and "final_info" in info:
                    ep = info["final_info"].get("episode")
                    if ep is not None:
                        for i in np.nonzero(ep.get("_r", []))[0]:
                            aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                            aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                            print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

        local_data = {k: np.stack(v, axis=0) for k, v in rollout.items()}
        next_values = np.asarray(player.get_values(next_obs))
        returns, advantages = gae_fn(
            jnp.asarray(local_data["rewards"]),
            jnp.asarray(local_data["values"]),
            jnp.asarray(local_data["dones"]),
            jnp.asarray(next_values),
        )
        local_data["returns"] = np.asarray(returns)
        local_data["advantages"] = np.asarray(advantages)
        flat = {k: v.reshape(v.shape[0] * v.shape[1], *v.shape[2:]) for k, v in local_data.items()}

        # ship the rollout to the trainers (reference scatter, :297-302)
        broadcast_object(flat, src=0)
        # receive the updated params (+ metrics, + opt state when
        # checkpointing) back from trainer rank 1 (reference :304-308). The
        # params ride as ONE flat byte vector — one device transfer on each
        # side instead of one per leaf (parallel.fabric._ParamStreamer)
        payload = broadcast_object(None, src=1)
        new_params = unpack_lane.finish(payload["params_flat"])
        player.params = new_params

        if cfg.metric.log_level > 0:
            aggregator.update("Loss/policy_loss", float(payload["metrics"][0]))
            aggregator.update("Loss/value_loss", float(payload["metrics"][1]))
            aggregator.update("Loss/entropy_loss", float(payload["metrics"][2]))
            if policy_step - last_log >= cfg.metric.log_every or update == num_updates:
                logger.log_metrics(aggregator.compute(), policy_step)
                aggregator.reset()
                timer.reset()
                last_log = policy_step

        if update in ckpt_updates:
            ckpt_state = {
                "agent": jax.device_get(new_params),
                "opt_state": payload["opt_state"],
                "update": update,
                "batch_size": int(cfg.algo.per_rank_batch_size) * len(trainer_devs),
                "last_log": last_log,
                "last_checkpoint": policy_step,
                "rng_key": jax.device_get(key),
                "player_rng_key": jax.device_get(player_key),
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step}_0.ckpt")
            fabric.call("on_checkpoint_player", ckpt_path=ckpt_path, state=ckpt_state)

    envs.close()
    if cfg.algo.run_test:
        test(player, fabric, cfg, log_dir)
    logger.finalize()


def _trainer(fabric, cfg, state=None):
    # join the player's log-dir broadcast (utils/logger.py get_log_dir is a
    # collective over every process — the reference's rank-wide log-dir
    # broadcast, logger.py:83-88)
    get_log_dir(cfg)
    num_envs, rollout_steps, trainer_devs, n_global, policy_steps_per_update, num_updates = _common_setup(
        fabric, cfg
    )
    start_update = state["update"] + 1 if state else 1
    ckpt_updates = _ckpt_schedule(
        cfg,
        num_updates,
        policy_steps_per_update,
        start_update=start_update,
        last_checkpoint=state["last_checkpoint"] if state else 0,
    )
    tfabric = SubMeshFabric(fabric, trainer_devs)
    n_local = n_global // tfabric.world_size

    observation_space, action_space = probe_spaces(cfg)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )

    agent, params = build_agent(
        tfabric, actions_dim, is_continuous, cfg, observation_space, state["agent"] if state else None
    )
    from sheeprl_tpu.parallel.fabric import _ParamStreamer

    # flat-vector send lane: one on-device pack + ONE device->host fetch per
    # update replaces a per-leaf device_get of the whole tree
    pack_lane = _ParamStreamer(jax.device_get(params), trainer_devs[0])

    num_minibatches = max(1, n_local // int(cfg.algo.per_rank_batch_size))
    opt_cfg = dict(cfg.algo.optimizer.to_dict() if hasattr(cfg.algo.optimizer, "to_dict") else cfg.algo.optimizer)
    if cfg.algo.max_grad_norm and float(cfg.algo.max_grad_norm) > 0:
        opt_cfg["max_grad_norm"] = float(cfg.algo.max_grad_norm)
    if cfg.algo.anneal_lr:
        steps_per_update = int(cfg.algo.update_epochs) * num_minibatches
        opt_cfg["schedule"] = optax.linear_schedule(
            float(opt_cfg.get("lr", 1e-3)), 0.0, num_updates * steps_per_update
        )
    tx = instantiate(opt_cfg)
    if state and state.get("opt_state") is not None:
        opt_state = tfabric.replicate(jax.tree.map(jnp.asarray, state["opt_state"]))
    else:
        opt_state = tfabric.replicate(tx.init(jax.device_get(params)))

    train_fn = make_train_fn(tfabric, agent, tx, cfg, obs_keys, n_local)

    clip_coef = float(cfg.algo.clip_coef)
    ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef, initial_ent_coef = clip_coef, ent_coef
    key = jax.random.PRNGKey(int(cfg.seed) + jax.process_index())

    # this trainer process's slice of the global rollout: the blocks of the
    # devices it hosts (reference chunk scatter, :297-302)
    my_dev_idx = [i for i, d in enumerate(trainer_devs) if d.process_index == jax.process_index()]

    for update in range(start_update, num_updates + 1):
        flat = broadcast_object(None, src=0)
        local_rows = np.concatenate([np.arange(i * n_local, (i + 1) * n_local) for i in my_dev_idx])
        local_flat = {k: v[local_rows] for k, v in flat.items()}
        data = tfabric.make_global(local_flat, (tfabric.data_axis,))

        with timer("Time/train_time"):
            key, train_key = jax.random.split(key)
            params, opt_state, metrics = train_fn(
                params,
                opt_state,
                data,
                train_key,
                jnp.float32(clip_coef),
                jnp.float32(ent_coef),
            )
            metrics = np.asarray(jax.device_get(metrics))

        payload = None
        if jax.process_index() == 1:
            flat_params = np.asarray(pack_lane.begin(params))  # one fetch
            payload = {"params_flat": flat_params, "metrics": metrics, "opt_state": None}
            if update in ckpt_updates:
                payload["opt_state"] = jax.device_get(opt_state)
        broadcast_object(payload, src=1)

        if cfg.algo.anneal_clip_coef:
            clip_coef = polynomial_decay(
                update, initial=initial_clip_coef, final=0.0, max_decay_steps=num_updates, power=1.0
            )
        if cfg.algo.anneal_ent_coef:
            ent_coef = polynomial_decay(
                update, initial=initial_ent_coef, final=0.0, max_decay_steps=num_updates, power=1.0
            )
