"""PPO losses (reference: sheeprl/algos/ppo/loss.py:6-72) as pure jnp
functions; the reduction is applied by the caller's mean over the minibatch."""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def _reduce(x: Array, reduction: str) -> Array:
    reduction = reduction.lower()
    if reduction == "none":
        return x
    if reduction == "mean":
        return x.mean()
    if reduction == "sum":
        return x.sum()
    raise ValueError(f"Unrecognized reduction: {reduction}")


def policy_loss(
    new_logprobs: Array,
    logprobs: Array,
    advantages: Array,
    clip_coef: Array,
    reduction: str = "mean",
) -> Array:
    """Clipped surrogate objective, eq. (7) of the PPO paper."""
    ratio = jnp.exp(new_logprobs - logprobs)
    pg_loss1 = advantages * ratio
    pg_loss2 = advantages * jnp.clip(ratio, 1 - clip_coef, 1 + clip_coef)
    return _reduce(-jnp.minimum(pg_loss1, pg_loss2), reduction)


def value_loss(
    new_values: Array,
    old_values: Array,
    returns: Array,
    clip_coef: Array,
    clip_vloss: bool,
    reduction: str = "mean",
) -> Array:
    if clip_vloss:
        values_pred = old_values + jnp.clip(new_values - old_values, -clip_coef, clip_coef)
    else:
        values_pred = new_values
    return _reduce(jnp.square(values_pred - returns), reduction)


def entropy_loss(entropy: Array, reduction: str = "mean") -> Array:
    return _reduce(-entropy, reduction)
