from sheeprl_tpu.algos.ppo import ppo  # noqa: F401  (registers the algorithm)
from sheeprl_tpu.algos.ppo import ppo_decoupled  # noqa: F401
from sheeprl_tpu.algos.ppo import evaluate  # noqa: F401  (registers the evaluation)
