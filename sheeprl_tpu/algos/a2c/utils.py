"""A2C helpers (reference: sheeprl/algos/a2c/utils.py)."""

from __future__ import annotations

AGGREGATOR_KEYS = {"Rewards/rew_avg", "Game/ep_len_avg", "Loss/value_loss", "Loss/policy_loss"}
MODELS_TO_REGISTER = {"agent"}

# vector-only observation prep and greedy test episode are identical to PPO's
from sheeprl_tpu.algos.ppo.utils import prepare_obs, test  # noqa: E402,F401
