"""A2C agent (reference: sheeprl/algos/a2c/agent.py:19-230).

Structurally the PPO agent restricted to vector observations; the module,
sampling and evaluation helpers are shared with
``sheeprl_tpu.algos.ppo.agent`` (the reference duplicates them)."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import gymnasium

from sheeprl_tpu.algos.ppo.agent import (  # noqa: F401  (re-exported API)
    PPOAgent as A2CAgent,
    PPOPlayer as A2CPlayer,
    evaluate_actions,
    sample_actions,
)


def build_agent(
    fabric: Any,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg: Dict[str, Any],
    obs_space: gymnasium.spaces.Dict,
    agent_state: Optional[Any] = None,
) -> Tuple[A2CAgent, Any]:
    """A2C is MLP-only (reference a2c.py:99-101 drops cnn keys)."""

    algo = cfg["algo"]
    agent = A2CAgent(
        actions_dim=tuple(int(d) for d in actions_dim),
        is_continuous=bool(is_continuous),
        cnn_keys=(),
        mlp_keys=tuple(algo["mlp_keys"]["encoder"]),
        mlp_features_dim=algo["encoder"]["mlp_features_dim"],
        encoder_units=int(algo["encoder"]["dense_units"]),
        encoder_layers=int(algo["encoder"]["mlp_layers"]),
        actor_units=int(algo["actor"]["dense_units"]),
        actor_layers=int(algo["actor"]["mlp_layers"]),
        critic_units=int(algo["critic"]["dense_units"]),
        critic_layers=int(algo["critic"]["mlp_layers"]),
        dense_act=str(algo["dense_act"]),
        layer_norm=bool(algo["layer_norm"]),
        dtype=fabric.precision.compute_dtype,
    )
    import jax
    import jax.numpy as jnp

    if agent_state is not None:
        params = jax.tree.map(jnp.asarray, agent_state)
    else:
        dummy_obs = {
            k: jnp.zeros((1, *obs_space[k].shape), jnp.float32) for k in agent.mlp_keys
        }
        params = agent.init(jax.random.PRNGKey(int(cfg["seed"])), dummy_obs)
    params = jax.tree.map(lambda x: x.astype(fabric.precision.param_dtype), params)
    return agent, fabric.replicate(params)
