"""A2C (reference: sheeprl/algos/a2c/a2c.py:25-361) — TPU-native.

The PPO skeleton without clipping: one gradient step per update over the
whole rollout. The reference emulates a full-batch gradient by accumulating
minibatch backward passes with ``no_backward_sync`` (a2c.py:62-96); here the
sum/mean reduction over the sharded rollout inside one jitted shard_map step
IS that accumulation — a gradient ``pmean`` replaces the final DDP sync.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any, Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from sheeprl_tpu.parallel.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.a2c.agent import build_agent
from sheeprl_tpu.algos.a2c.loss import policy_loss, value_loss
from sheeprl_tpu.algos.a2c.utils import AGGREGATOR_KEYS, prepare_obs, test
from sheeprl_tpu.algos.ppo.agent import PPOPlayer, evaluate_actions, rollout_step
from sheeprl_tpu.algos.ppo.ppo import (
    resolve_fused_rollout_spec,
    resolve_scenario_family,
    scenario_theta_matrix,
)
from sheeprl_tpu.config.compose import instantiate
from sheeprl_tpu.envs.variants import ScenarioFamily
from sheeprl_tpu.parallel.fabric import put_tree, resolve_player_device, resolve_train_device
from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.obs import (
    log_sps_and_heartbeat,
    telemetry_advance,
    telemetry_mark_warm,
    telemetry_register_flops,
    telemetry_run_metrics,
    telemetry_train_window,
)
from sheeprl_tpu.ops.math import gae
from sheeprl_tpu.ops.rollout_scan import ENV_STREAM_SALT, init_env_carry, make_onpolicy_superstep_fn
from sheeprl_tpu.ops.superstep import fused_fallback, reset_fused_fallback_warnings
from sheeprl_tpu.resilience import RunResilience
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.prealloc import RolloutStore
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import save_configs


def make_local_train(fabric, agent, tx, cfg, obs_keys, *, use_mesh: bool):
    """The UNJITTED one-gradient-step update body (A2C has no epochs or
    minibatches — the whole-rollout mean IS the reference's accumulated
    full-batch gradient).  ``use_mesh`` guards the collectives so the same
    body serves the shard_map'd update and the single-device escape hatch."""
    reduction = str(cfg.algo.loss_reduction)
    data_axis = fabric.data_axis

    def local_train(params, opt_state, data):
        def loss_fn(p):
            obs = {k: data[k] for k in obs_keys}
            logprobs, _, values = evaluate_actions(agent, p, obs, data["actions"])
            pg = policy_loss(logprobs, data["advantages"], reduction)
            v = value_loss(values, data["returns"], reduction)
            return pg + v, (pg, v)

        (_, (pg, v)), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        if use_mesh:
            grads = lax.pmean(grads, data_axis)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = jnp.stack([pg, v])
        if use_mesh:
            metrics = lax.pmean(metrics, data_axis)
        return params, opt_state, metrics

    return local_train


def make_fused_local_train(fabric, agent, tx, cfg, obs_keys, *, use_mesh: bool):
    """Adapt the A2C update body to the fused superstep's ``local_train``
    contract (``ops/rollout_scan.py``): A2C's single full-batch gradient step
    needs neither the train key nor the clip/entropy coefficients, so they
    are accepted and dropped."""
    local_train = make_local_train(fabric, agent, tx, cfg, obs_keys, use_mesh=use_mesh)

    def fused_local_train(params, opt_state, data, key, clip_coef, ent_coef):
        del key, clip_coef, ent_coef
        return local_train(params, opt_state, data)

    return fused_local_train


def make_train_fn(fabric, agent, tx, cfg, obs_keys):
    multi_device = fabric.world_size > 1
    local_train = make_local_train(fabric, agent, tx, cfg, obs_keys, use_mesh=multi_device)
    if multi_device:
        train_fn = shard_map(
            local_train,
            mesh=fabric.mesh,
            in_specs=(P(), P(), P(fabric.data_axis)),
            out_specs=(P(), P(), P()),
        )
    else:
        train_fn = local_train
    return jax.jit(train_fn, donate_argnums=(0, 1))


@register_algorithm()
def main(fabric, cfg: Dict[str, Any]):
    if cfg.checkpoint.resume_from:
        state = fabric.load(cfg.checkpoint.resume_from)

    if len(cfg.algo.cnn_keys.encoder) > 0:
        import warnings

        warnings.warn("A2C is vector-only; the CNN keys will be ignored")
        cfg.algo.cnn_keys.encoder = []

    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")
    resil = RunResilience(fabric, cfg, log_dir)

    rank = fabric.process_index
    num_envs = int(cfg.env.num_envs)
    world_size = fabric.data_parallel_size  # batch-split width: the data axis (= device count on a 1-D mesh)
    num_processes = fabric.num_processes

    envs = build_vector_env(cfg, rank, log_dir if rank == 0 else None, "train")
    observation_space = envs.single_observation_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = mlp_keys
    if not obs_keys:
        raise RuntimeError("You should specify at least one MLP key for the encoder: `mlp_keys.encoder=[state]`")

    is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        envs.single_action_space.shape
        if is_continuous
        else (envs.single_action_space.nvec.tolist() if is_multidiscrete else [envs.single_action_space.n])
    )

    # scenario variants ride the fused rollout only (same contract as PPO);
    # `distractors` widens the observation the agent is built against
    # resolved unconditionally: enabled variants with the fused path off must
    # hit the loud RuntimeError below, never silently train the base env
    scenario_family = resolve_scenario_family(cfg)
    obs_widened = False
    if scenario_family is not None and len(mlp_keys) == 1:
        k0 = mlp_keys[0]
        if tuple(observation_space[k0].shape) != (scenario_family.obs_dim,):
            spaces_d = dict(observation_space.spaces)
            spaces_d[k0] = gym.spaces.Box(-np.inf, np.inf, (scenario_family.obs_dim,), np.float32)
            observation_space = gym.spaces.Dict(spaces_d)
            obs_widened = True

    agent, params = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["agent"] if cfg.checkpoint.resume_from else None,
    )
    player = PPOPlayer(
        agent, params, device=resolve_player_device(cfg.algo.get("player_device", "auto"))
    )

    rollout_steps = int(cfg.algo.rollout_steps)
    policy_steps_per_update = num_envs * rollout_steps * num_processes
    num_updates = int(cfg.algo.total_steps) // policy_steps_per_update if not cfg.dry_run else 1

    opt_cfg = dict(cfg.algo.optimizer.to_dict() if hasattr(cfg.algo.optimizer, "to_dict") else cfg.algo.optimizer)
    if cfg.algo.max_grad_norm and float(cfg.algo.max_grad_norm) > 0:
        opt_cfg["max_grad_norm"] = float(cfg.algo.max_grad_norm)
    tx = instantiate(opt_cfg)
    # remote-chip escape hatch (same as plain PPO): a tiny model's update
    # runs on the host core so nothing in the A2C loop touches the link —
    # the single-device train program has no mesh collectives, so committing
    # params/opt/batch to the host is all it takes
    train_device = resolve_train_device(
        cfg.algo.get("train_device", "auto"), params, fabric.world_size
    )
    if train_device is not None:
        params = put_tree(jax.device_get(params), train_device)
        player.update_params(params)
    opt_state = state["opt_state"] if cfg.checkpoint.resume_from else tx.init(params)
    opt_state = (
        put_tree(opt_state, train_device) if train_device is not None else fabric.replicate(opt_state)
    )

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in AGGREGATOR_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    n_global = rollout_steps * num_envs * num_processes
    if n_global % world_size != 0:
        raise ValueError(
            f"rollout_steps*num_envs*processes ({n_global}) must be divisible by the device count ({world_size})"
        )
    train_fn = make_train_fn(fabric, agent, tx, cfg, obs_keys)
    gae_fn = jax.jit(partial(gae, gamma=float(cfg.algo.gamma), gae_lambda=float(cfg.algo.gae_lambda)))

    # fused on-policy collection (`algo.fused_rollout`, ported from PPO): the
    # T-step rollout, GAE and A2C's single full-batch gradient step compile
    # into ONE donated jit — one dispatch per update instead of T+3
    fused_rollout = bool(cfg.algo.get("fused_rollout", False))
    reset_fused_fallback_warnings()
    fused_spec = None
    if fused_rollout:
        fused_spec = resolve_fused_rollout_spec(
            cfg, fabric, [], mlp_keys, observation_space, is_continuous, is_multidiscrete, actions_dim
        )
        if fused_spec is not None and train_device is None and num_envs % world_size != 0:
            fused_fallback(
                "env_shard", f"env.num_envs ({num_envs}) must be divisible by the device count ({world_size})"
            )
            fused_spec = None
    if scenario_family is not None and fused_spec is None:
        raise RuntimeError(
            "env.variants requires the fused rollout path; set "
            "algo.fused_rollout=True (if it is set, the fused_fallback "
            "telemetry event names the gate that failed)"
        )
    superstep_fn = None
    if fused_spec is not None:
        use_mesh_fused = train_device is None
        superstep_fn = make_onpolicy_superstep_fn(
            fused_spec,
            policy_fn=partial(rollout_step, agent),
            value_fn=lambda p, o: agent.apply(p, o)[1],
            local_train=make_fused_local_train(fabric, agent, tx, cfg, obs_keys, use_mesh=use_mesh_fused),
            obs_key=mlp_keys[0],
            rollout_steps=rollout_steps,
            step_increment=num_envs * num_processes,
            gamma=float(cfg.algo.gamma),
            gae_lambda=float(cfg.algo.gae_lambda),
            mesh=fabric.mesh if use_mesh_fused else None,
            data_axis=fabric.data_axis if use_mesh_fused else None,
        )

    start_update = (state["update"] + 1) if cfg.checkpoint.resume_from else 1
    policy_step = state["update"] * policy_steps_per_update if cfg.checkpoint.resume_from else 0
    last_log = state["last_log"] if cfg.checkpoint.resume_from else 0
    last_checkpoint = state["last_checkpoint"] if cfg.checkpoint.resume_from else 0
    train_step = 0
    last_train = 0

    key = jax.random.PRNGKey(int(cfg.seed))
    # action keys live on the player's device so a host-pinned player
    # never blocks on a chip round trip per env step
    player_key = put_tree(jax.random.fold_in(key, 1), player.device)
    next_obs, _ = envs.reset(seed=cfg.seed)
    next_obs = prepare_obs(next_obs, num_envs=num_envs)

    def ckpt_state_fn(completed_update: int) -> Dict[str, Any]:
        return {
            "agent": jax.device_get(params),
            "opt_state": jax.device_get(opt_state),
            "update": completed_update,
            "batch_size": int(cfg.algo.per_rank_batch_size) * world_size,
            "last_log": last_log,
            "last_checkpoint": last_checkpoint,
        }

    def ckpt_path_fn(step: int) -> str:
        return os.path.join(log_dir, "checkpoint", f"ckpt_{step}_{rank}.ckpt")

    # a crash anywhere in the loop gets the preemption treatment too: the
    # lambdas read the loop's CURRENT policy_step/update at crash time
    resil.arm_crash_guard(
        path_fn=lambda: ckpt_path_fn(policy_step),
        state_fn=lambda: ckpt_state_fn(update - 1),
    )
    preempted = False
    if superstep_fn is not None:
        # ------------------------------------------------------------------
        # fused on-policy path: rollout + GAE + the single gradient step are
        # ONE donated jit; the metrics fetch is the only host sync per update
        # ------------------------------------------------------------------
        if use_mesh_fused:
            def place_carry(carry):
                return jax.tree.map(lambda x: jax.device_put(x, fabric.batch_sharding), carry)

            key = jax.device_put(key, fabric.replicated)
        else:

            def place_carry(carry):
                return put_tree(carry, train_device)

            key = put_tree(key, train_device)
        # one scenario row per env for the run's lifetime (PPO's contract)
        thetas = (
            scenario_theta_matrix(cfg, fused_spec, num_envs)
            if isinstance(fused_spec, ScenarioFamily)
            else None
        )
        env_carry = place_carry(
            init_env_carry(
                fused_spec,
                num_envs,
                jax.random.fold_in(jax.random.PRNGKey(int(cfg.seed)), ENV_STREAM_SALT),
                thetas=thetas,
            )
        )
        for update in range(start_update, num_updates + 1):
            telemetry_advance(policy_step)
            if resil.preempt_requested():
                last_checkpoint = policy_step
                resil.emergency_checkpoint(ckpt_path_fn(policy_step), ckpt_state_fn(update - 1))
                preempted = True
                break
            if update == start_update + 1:
                telemetry_mark_warm()
            # rollout_actions' fold schedule on top of a per-update key — the
            # same in-graph discipline as the fused PPO loop
            update_key = jax.random.fold_in(player_key, update)
            step_before = policy_step
            with timer("Time/env_interaction_time"):
                params, opt_state, env_carry, key, metrics, ep_stats = superstep_fn(
                    params,
                    opt_state,
                    env_carry,
                    update_key,
                    key,
                    np.uint32(step_before),
                    # A2C has no clip/entropy coefficients; the superstep's
                    # scalar slots are inert for its local_train
                    np.float32(0.0),
                    np.float32(0.0),
                )
                policy_step += policy_steps_per_update
                metrics = np.asarray(metrics)
            telemetry_train_window(1, 1)
            if not resil.check_finite(metrics, update):
                restored = resil.rollback(update=update)
                params = resil.place_like(restored["agent"], params)
                opt_state = resil.place_like(restored["opt_state"], opt_state)
                player_key = resil.resalt_key(player_key)
                player.update_params(params)
                # fresh episodes: poisoned params may have driven the carried
                # env state non-finite too
                env_carry = place_carry(
                    init_env_carry(
                        fused_spec,
                        num_envs,
                        jax.random.fold_in(jax.random.PRNGKey(int(cfg.seed)), update),
                        thetas=thetas,
                    )
                )
                continue
            train_step += num_processes
            if update == start_update:
                telemetry_register_flops(
                    superstep_fn,
                    params,
                    opt_state,
                    env_carry,
                    update_key,
                    key,
                    np.uint32(step_before),
                    np.float32(0.0),
                    np.float32(0.0),
                )
            if cfg.metric.log_level > 0:
                # one fetch of the per-step episode flags replaces the host
                # loop's final_info plumbing
                ep_done = np.asarray(ep_stats["done"])
                finished = np.nonzero(ep_done)
                if finished[0].size:
                    finished_rets = np.asarray(ep_stats["ret"])[finished]
                    for r in finished_rets:
                        aggregator.update("Rewards/rew_avg", float(r))
                    for length in np.asarray(ep_stats["len"])[finished]:
                        aggregator.update("Game/ep_len_avg", float(length))
                    # same per-episode evidence lines as the host loop — the
                    # learning-check recipes (benchmarks/learning_checks.sh,
                    # tools/sweep.py) grep these for the reward trend
                    for i, r in zip(finished[-1], finished_rets):
                        print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={float(r)}")
                aggregator.update("Loss/policy_loss", float(metrics[0]))
                aggregator.update("Loss/value_loss", float(metrics[1]))
                if policy_step - last_log >= cfg.metric.log_every or update == num_updates:
                    metrics_dict = aggregator.compute()
                    logger.log_metrics(metrics_dict, policy_step)
                    telemetry_run_metrics(metrics_dict)
                    aggregator.reset()
                    log_sps_and_heartbeat(
                        logger,
                        policy_step=policy_step,
                        env_steps=(policy_step - last_log) * cfg.env.action_repeat,
                        train_steps=train_step - last_train,
                        train_invocations=(train_step - last_train) // num_processes,
                    )
                    last_log = policy_step
                    last_train = train_step
            if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
                update == num_updates and cfg.checkpoint.save_last
            ):
                last_checkpoint = policy_step
                fabric.call(
                    "on_checkpoint_coupled", ckpt_path=ckpt_path_fn(policy_step), state=ckpt_state_fn(update)
                )
        # the player sampled nothing during the fused loop; publish the final
        # params once for the eval rollout below
        player.update_params(params)
    else:
        # rollout arrays preallocated once and written in place — no per-step
        # list appends, no end-of-window np.stack copy
        store = RolloutStore(rollout_steps)
        for update in range(start_update, num_updates + 1):
            telemetry_advance(policy_step)
            if resil.preempt_requested():
                last_checkpoint = policy_step
                resil.emergency_checkpoint(ckpt_path_fn(policy_step), ckpt_state_fn(update - 1))
                preempted = True
                break
            if update == start_update + 1:
                # no bench probe in this loop — warm the recompile watchdog here
                telemetry_mark_warm()
            buf = store.begin(update)
            with timer("Time/env_interaction_time"):
                for t in range(rollout_steps):
                    policy_step += num_envs * num_processes
                    player_key, action_key = jax.random.split(player_key)
                    actions, logprobs, values = player.get_actions(next_obs, action_key)
                    actions_np, logprobs_np, values_np = jax.device_get((actions, logprobs, values))
                    if is_continuous:
                        real_actions = actions_np
                    else:
                        splits = np.cumsum(actions_dim)[:-1]
                        real_actions = np.stack(
                            [p.argmax(-1) for p in np.split(actions_np, splits, axis=-1)], axis=-1
                        )
                        if real_actions.shape[-1] == 1 and not is_multidiscrete:
                            real_actions = real_actions[..., 0]

                    obs, rewards, terminated, truncated, info = envs.step(
                        real_actions.reshape(envs.action_space.shape)
                    )
                    rewards = np.asarray(rewards, np.float32).reshape(num_envs, 1)
                    dones = np.logical_or(terminated, truncated).reshape(num_envs, 1).astype(np.float32)
                    step_values = {k: next_obs[k] for k in obs_keys}
                    step_values["dones"] = dones
                    step_values["values"] = values_np
                    step_values["actions"] = actions_np
                    step_values["logprobs"] = logprobs_np
                    step_values["rewards"] = rewards
                    buf.put(t, step_values)
                    next_obs = prepare_obs(obs, num_envs=num_envs)

                    if cfg.metric.log_level > 0 and "final_info" in info:
                        ep = info["final_info"].get("episode")
                        if ep is not None:
                            for i in np.nonzero(ep.get("_r", []))[0]:
                                aggregator.update("Rewards/rew_avg", float(ep["r"][i]))
                                aggregator.update("Game/ep_len_avg", float(ep["l"][i]))
                                print(f"Rank-0: policy_step={policy_step}, reward_env_{i}={ep['r'][i]}")

            local_data = buf.arrays()
            next_values = np.asarray(player.get_values(next_obs))
            # GAE on the player's device (host when the chip is remote-attached):
            # rollout arrays are already host-side, so the advantage pass never
            # pays a link round trip (same routing as plain PPO)
            returns, advantages = gae_fn(
                put_tree(local_data["rewards"], player.device),
                put_tree(local_data["values"], player.device),
                put_tree(local_data["dones"], player.device),
                put_tree(next_values, player.device),
            )
            local_data["returns"] = np.asarray(returns)
            local_data["advantages"] = np.asarray(advantages)
            flat = {k: v.reshape(v.shape[0] * v.shape[1], *v.shape[2:]) for k, v in local_data.items()}
            if num_processes > 1:
                flat = fabric.make_global(flat, (fabric.data_axis,))

            with timer("Time/train_time"):
                params, opt_state, metrics = train_fn(params, opt_state, flat)
                # one host fetch serves the sync point, the NaN sentinel and the
                # aggregator scalars below — block_until_ready + a second asarray
                # (or float(metrics[i]) per scalar) would each be an extra
                # blocking transfer per update
                metrics = np.asarray(metrics)
            if not resil.check_finite(metrics, update):
                # restore the newest committed checkpoint and fork the action key
                # away from the stream that diverged; the loop keeps advancing
                restored = resil.rollback(update=update)
                params = resil.place_like(restored["agent"], params)
                opt_state = resil.place_like(restored["opt_state"], opt_state)
                player_key = resil.resalt_key(player_key)
                player.update_params(params)
                continue
            player.params = params
            train_step += num_processes
            if update == start_update:
                telemetry_register_flops(train_fn, params, opt_state, flat)

            if cfg.metric.log_level > 0:
                aggregator.update("Loss/policy_loss", float(metrics[0]))
                aggregator.update("Loss/value_loss", float(metrics[1]))
                if policy_step - last_log >= cfg.metric.log_every or update == num_updates:
                    metrics_dict = aggregator.compute()
                    logger.log_metrics(metrics_dict, policy_step)
                    telemetry_run_metrics(metrics_dict)
                    aggregator.reset()
                    log_sps_and_heartbeat(
                        logger,
                        policy_step=policy_step,
                        env_steps=(policy_step - last_log) * cfg.env.action_repeat,
                        train_steps=train_step - last_train,
                        train_invocations=(train_step - last_train) // num_processes,
                    )
                    last_log = policy_step
                    last_train = train_step

            if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
                update == num_updates and cfg.checkpoint.save_last
            ):
                last_checkpoint = policy_step
                fabric.call("on_checkpoint_coupled", ckpt_path=ckpt_path_fn(policy_step), state=ckpt_state_fn(update))

    envs.close()
    if fabric.is_global_zero and cfg.algo.run_test and not preempted:
        if obs_widened:
            # the agent expects the scenario family's widened observation; the
            # host eval env emits the base one — there is nothing to evaluate
            import warnings

            warnings.warn("skipping run_test: env.variants widened the observation past the host env's")
        else:
            test(player, fabric, cfg, log_dir)
    logger.finalize()
    resil.close()
    if preempted:
        resil.exit_preempted()
