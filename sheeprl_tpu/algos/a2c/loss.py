"""A2C losses (reference: sheeprl/algos/a2c/loss.py:5-54)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def _reduce(x: Array, reduction: str) -> Array:
    reduction = reduction.lower()
    if reduction == "none":
        return x
    if reduction == "mean":
        return x.mean()
    if reduction == "sum":
        return x.sum()
    raise ValueError(f"Unrecognized reduction: {reduction}")


def policy_loss(logprobs: Array, advantages: Array, reduction: str = "sum") -> Array:
    """Vanilla policy gradient: -logpi(a|s) * A."""
    return _reduce(-logprobs * advantages, reduction)


def value_loss(values: Array, returns: Array, reduction: str = "sum") -> Array:
    return _reduce(jnp.square(values - returns), reduction)
