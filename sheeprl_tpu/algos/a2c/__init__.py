from sheeprl_tpu.algos.a2c import a2c  # noqa: F401  (registers the algorithm)
from sheeprl_tpu.algos.a2c import evaluate  # noqa: F401  (registers the evaluation)
