"""Shared evaluation scaffold for the Dreamer family.

Each of the six Dreamer/P2E evaluation entrypoints (reference:
``sheeprl/algos/{dreamer_v1,dreamer_v2,dreamer_v3,p2e_dv1,p2e_dv2,p2e_dv3}/evaluate.py``)
does the same dance — open one env to read the spaces, rebuild the agent from
the checkpointed model states, run the shared ``test`` rollout, finalize the
logger — differing only in which ``build_agent`` to call and which checkpoint
keys hold the (task) models. This module holds the dance once; the per-algo
``evaluate.py`` files reduce to a registration plus that pair.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence, Tuple

import gymnasium as gym

from sheeprl_tpu.envs import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger


def action_dims(action_space: gym.Space) -> Tuple[Tuple[int, ...], bool]:
    """``(actions_dim, is_continuous)`` for a Box/Discrete/MultiDiscrete
    action space — the tuple every ``build_agent`` consumes."""
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    return actions_dim, is_continuous


def dreamer_family_evaluate(
    fabric: Any,
    cfg: Dict[str, Any],
    state: Dict[str, Any],
    build_agent: Callable[..., Any],
    test_fn: Callable[..., None],
    state_keys: Sequence[str],
) -> None:
    """Rebuild a Dreamer-family agent from checkpoint ``state[state_keys]``
    and run the algo's ``test`` rollout. ``build_agent`` must accept
    ``(fabric, actions_dim, is_continuous, cfg, observation_space, *states)``
    and return the player last — the contract all six agents share."""
    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger

    env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    actions_dim, is_continuous = action_dims(action_space)
    env.close()

    *_, player = build_agent(
        fabric,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        *[state[k] for k in state_keys],
    )
    test_fn(player, fabric, cfg, log_dir)
    logger.finalize()
