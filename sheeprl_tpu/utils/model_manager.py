"""Model registry / manager (reference: sheeprl/utils/mlflow.py:35-427 and
cli.py:394-436).

Two backends behind the reference's ``AbstractModelManager`` interface:

- :class:`LocalModelManager` — a file-backed registry (``registry.json`` +
  per-version artifact copies). TPU pods usually run with zero external
  services, so this is the default backend and what the tests exercise.
- :class:`MlflowModelManager` — the reference's MLflow registry, import-gated
  (models are logged as pickled param-tree artifacts instead of
  ``mlflow.pytorch`` modules — the framework's models ARE pytrees).

"Logging a model" = pickling one checkpoint sub-tree (params + metadata) to
an artifact file; ``log_models_from_checkpoint`` is the shared per-algo hook
(the reference defines one per algorithm over ``MODELS_TO_REGISTER``).
"""

from __future__ import annotations

import getpass
import json
import os
import pickle
import shutil
from abc import ABC, abstractmethod
from datetime import datetime
from typing import Any, Dict, Iterable, Literal, Optional

from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

VERSION_MD_TEMPLATE = "## **Version {}**\n"
DESCRIPTION_MD_TEMPLATE = "### Description: \n{}\n"


class AbstractModelManager(ABC):
    """The reference's model-manager interface (mlflow.py:35-72)."""

    def __init__(self, fabric: Any) -> None:
        self.fabric = fabric

    @abstractmethod
    def register_model(
        self, model_location: str, model_name: str, description: Optional[str] = None, tags: Optional[Dict] = None
    ) -> Any:
        """Register a model artifact in the registry."""

    @abstractmethod
    def get_latest_version(self, model_name: str) -> Any:
        """Get the latest registered version of a model."""

    @abstractmethod
    def transition_model(
        self, model_name: str, version: int, stage: str, description: Optional[str] = None
    ) -> Any:
        """Move a model version to a new stage."""

    @abstractmethod
    def delete_model(self, model_name: str, version: int, description: Optional[str] = None) -> None:
        """Delete a model version."""

    @abstractmethod
    def download_model(self, model_name: str, version: int, output_path: str) -> None:
        """Copy a model version's artifact to ``output_path``."""


def _author_and_date() -> str:
    try:
        author = getpass.getuser()
    except Exception:
        author = "unknown"
    return f"**Author**: {author}\n**Date**: {datetime.now().strftime('%d/%m/%Y %H:%M:%S')}\n"


class LocalModelManager(AbstractModelManager):
    """File-backed registry: ``<registry_dir>/registry.json`` holds the
    version metadata; artifacts are copied to
    ``<registry_dir>/<model_name>/v<version>/``."""

    def __init__(self, fabric: Any, registry_dir: str) -> None:
        super().__init__(fabric)
        self.registry_dir = registry_dir
        os.makedirs(registry_dir, exist_ok=True)
        self._index_path = os.path.join(registry_dir, "registry.json")

    def _load_index(self) -> Dict[str, Any]:
        if os.path.isfile(self._index_path):
            with open(self._index_path) as f:
                return json.load(f)
        return {}

    def _save_index(self, index: Dict[str, Any]) -> None:
        with open(self._index_path, "w") as f:
            json.dump(index, f, indent=2)

    def register_model(
        self, model_location: str, model_name: str, description: Optional[str] = None, tags: Optional[Dict] = None
    ) -> Dict[str, Any]:
        index = self._load_index()
        versions = index.setdefault(model_name, [])
        version = len(versions) + 1
        dst_dir = os.path.join(self.registry_dir, model_name, f"v{version}")
        os.makedirs(dst_dir, exist_ok=True)
        dst = os.path.join(dst_dir, os.path.basename(model_location))
        shutil.copy2(model_location, dst)
        changelog = (
            VERSION_MD_TEMPLATE.format(version)
            + _author_and_date()
            + DESCRIPTION_MD_TEMPLATE.format(description or "")
        )
        record = {
            "version": version,
            "artifact": dst,
            "stage": "None",
            "description": description or "",
            "tags": tags or {},
            "changelog": changelog,
        }
        versions.append(record)
        self._save_index(index)
        print(f"Registered model {model_name} with version {version}")
        return record

    def get_latest_version(self, model_name: str) -> Dict[str, Any]:
        versions = self._load_index().get(model_name, [])
        if not versions:
            raise KeyError(f"no registered versions for model {model_name!r}")
        return versions[-1]

    def transition_model(
        self, model_name: str, version: int, stage: str, description: Optional[str] = None
    ) -> Dict[str, Any]:
        index = self._load_index()
        record = index[model_name][version - 1]
        record["stage"] = stage
        if description:
            record["changelog"] += DESCRIPTION_MD_TEMPLATE.format(description)
        self._save_index(index)
        return record

    def delete_model(self, model_name: str, version: int, description: Optional[str] = None) -> None:
        index = self._load_index()
        record = index[model_name][version - 1]
        artifact_dir = os.path.dirname(record["artifact"])
        if os.path.isdir(artifact_dir):
            shutil.rmtree(artifact_dir)
        record["stage"] = "Deleted"
        record["artifact"] = None
        self._save_index(index)

    def download_model(self, model_name: str, version: int, output_path: str) -> None:
        record = self._load_index()[model_name][version - 1]
        if not record["artifact"]:
            raise FileNotFoundError(f"model {model_name} v{version} was deleted")
        os.makedirs(output_path, exist_ok=True)
        shutil.copy2(record["artifact"], output_path)


class MlflowModelManager(AbstractModelManager):
    """MLflow-backed registry (reference MlflowModelManager,
    mlflow.py:75-327). Artifacts are pickled param trees logged with
    ``mlflow.log_artifact``."""

    def __init__(self, fabric: Any, tracking_uri: str) -> None:
        if not _IS_MLFLOW_AVAILABLE:
            raise ModuleNotFoundError("mlflow is not installed; use the 'local' model-manager backend instead")
        super().__init__(fabric)
        import mlflow
        from mlflow.tracking import MlflowClient

        self.tracking_uri = tracking_uri
        mlflow.set_tracking_uri(tracking_uri)
        self._mlflow = mlflow
        self.client = MlflowClient()

    def register_model(
        self, model_location: str, model_name: str, description: Optional[str] = None, tags: Optional[Dict] = None
    ):
        model_version = self._mlflow.register_model(model_uri=model_location, name=model_name, tags=tags)
        registered_description = self.client.get_registered_model(model_name).description or ""
        header = "# MODEL CHANGELOG\n" if model_version.version == "1" else ""
        new_description = (
            VERSION_MD_TEMPLATE.format(model_version.version)
            + _author_and_date()
            + DESCRIPTION_MD_TEMPLATE.format(description or "")
        )
        self.client.update_registered_model(model_name, header + registered_description + new_description)
        self.client.update_model_version(
            model_name, model_version.version, "# MODEL CHANGELOG\n" + new_description
        )
        return model_version

    def get_latest_version(self, model_name: str):
        latest = max(int(x.version) for x in self.client.get_latest_versions(model_name))
        return self.client.get_model_version(model_name, latest)

    def transition_model(
        self, model_name: str, version: int, stage: str, description: Optional[str] = None
    ):
        self.client.transition_model_version_stage(model_name, str(version), stage)
        if description:
            self.client.update_model_version(
                model_name, str(version), DESCRIPTION_MD_TEMPLATE.format(description)
            )
        return self.client.get_model_version(model_name, str(version))

    def delete_model(self, model_name: str, version: int, description: Optional[str] = None) -> None:
        self.client.delete_model_version(model_name, str(version))

    def download_model(self, model_name: str, version: int, output_path: str) -> None:
        from mlflow.artifacts import download_artifacts

        version_info = self.client.get_model_version(model_name, str(version))
        download_artifacts(artifact_uri=version_info.source, dst_path=output_path)


def make_model_manager(fabric: Any, cfg: Dict[str, Any]) -> AbstractModelManager:
    """Build the configured backend (``model_manager.backend``)."""
    mm = cfg["model_manager"]
    backend = str(mm.get("backend", "local")).lower()
    if backend == "mlflow":
        tracking_uri = mm.get("tracking_uri") or os.getenv("MLFLOW_TRACKING_URI")
        if not tracking_uri:
            raise ValueError(
                "model_manager.backend=mlflow needs model_manager.tracking_uri or MLFLOW_TRACKING_URI"
            )
        return MlflowModelManager(fabric, tracking_uri)
    if backend == "local":
        return LocalModelManager(fabric, mm.get("registry_dir") or "models_registry")
    raise ValueError(f"unknown model_manager backend {backend!r} (choose 'local' or 'mlflow')")


def log_models_from_checkpoint(
    state: Dict[str, Any], keys: Iterable[str], artifacts_dir: str
) -> Dict[str, str]:
    """Pickle each registered sub-model's checkpoint tree into
    ``artifacts_dir`` (the shared body of every per-algo
    ``log_models_from_checkpoint``; reference e.g.
    dreamer_v3/utils.py:189-235). Keys nested under a top-level ``agent``
    dict (ppo/sac-style checkpoints) are resolved there."""
    os.makedirs(artifacts_dir, exist_ok=True)
    out: Dict[str, str] = {}
    for k in keys:
        if k in state:
            tree = state[k]
        elif isinstance(state.get("agent"), dict) and k in state["agent"]:
            tree = state["agent"][k]
        else:
            # a phase may checkpoint fewer sub-models than the algo registers
            # (e.g. P2E finetuning has no ensembles); the registration-time
            # subset check surfaces genuinely missing models
            continue
        path = os.path.join(artifacts_dir, f"{k}.pkl")
        with open(path, "wb") as f:
            pickle.dump(tree, f, protocol=pickle.HIGHEST_PROTOCOL)
        out[k] = path
    return out


def register_model_from_checkpoint(
    fabric: Any,
    cfg: Dict[str, Any],
    state: Dict[str, Any],
    log_models_fn: Any,
) -> Dict[str, Any]:
    """Log the checkpoint's sub-models and register the configured subset
    (reference register_model_from_checkpoint, mlflow.py:330-382)."""
    artifacts_dir = os.path.join(
        cfg["model_manager"].get("registry_dir") or "models_registry", "_artifacts", cfg["exp_name"]
    )
    models_info = log_models_fn(fabric, cfg, state, artifacts_dir)
    manager = make_model_manager(fabric, cfg)
    wanted = set(cfg["model_manager"]["models"].keys())
    if not wanted.issubset(models_info.keys()):
        raise RuntimeError(
            f"The models you want to register must be a subset of the models of the {cfg['algo']['name']} "
            f"agent.\nModels specified in the configs: {sorted(wanted)}."
            f"\nModels of the agent: {sorted(models_info)}."
        )
    registered = {}
    for k, cfg_model in cfg["model_manager"]["models"].items():
        registered[k] = manager.register_model(
            models_info[k], cfg_model["model_name"], cfg_model.get("description"), cfg_model.get("tags")
        )
    return registered
