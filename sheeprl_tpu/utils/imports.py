"""Optional-dependency gates (reference: sheeprl/utils/imports.py:1-17).

The reference uses lightning's ``RequirementCache``; here a plain importlib
probe keeps the framework free of heavyweight optional deps at import time.
"""

from __future__ import annotations

import importlib.util


def module_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


_IS_GYMNASIUM_AVAILABLE = module_available("gymnasium")
_IS_DMC_AVAILABLE = module_available("dm_control")
_IS_CV2_AVAILABLE = module_available("cv2")
_IS_MLFLOW_AVAILABLE = module_available("mlflow")
_IS_TENSORBOARD_AVAILABLE = module_available("tensorboard") or module_available("tensorboardX")
_IS_CRAFTER_AVAILABLE = module_available("crafter")
_IS_MINERL_AVAILABLE = module_available("minerl")
_IS_MINEDOJO_AVAILABLE = module_available("minedojo")
_IS_DIAMBRA_AVAILABLE = module_available("diambra")
_IS_SUPER_MARIO_AVAILABLE = module_available("gym_super_mario_bros")
_IS_ALE_AVAILABLE = module_available("ale_py")

try:
    import numpy as _np

    _IS_NUMPY_2 = int(_np.__version__.split(".")[0]) >= 2
except Exception:  # pragma: no cover
    _IS_NUMPY_2 = False
