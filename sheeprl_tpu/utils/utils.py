"""Host-side utilities (reference: sheeprl/utils/utils.py — dotdict :34,
polynomial_decay :133, save_configs :257, print_config :208, Ratio :261).

Numeric transforms (symlog, two-hot, GAE) live in ``sheeprl_tpu.ops.math`` as
jittable functions; this module is pure-Python host logic.
"""

from __future__ import annotations

import os
import warnings
from typing import Any, Dict, Mapping, Sequence

import yaml


class dotdict(dict):
    """Attribute-access dict with recursive conversion.

    Mirrors reference ``utils/utils.py:34-60`` semantics: nested mappings become
    dotdicts; attribute get/set/del proxy to the dict.
    """

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__()
        src: Dict[str, Any] = dict(*args, **kwargs)
        for k, v in src.items():
            self[k] = self._wrap(v)

    @classmethod
    def _wrap(cls, v: Any) -> Any:
        if isinstance(v, dotdict):
            return v
        if isinstance(v, Mapping):
            return cls(v)
        if isinstance(v, (list, tuple)):
            return type(v)(cls._wrap(x) for x in v)
        return v

    def __setitem__(self, key: str, value: Any) -> None:
        super().__setitem__(key, self._wrap(value))

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __setattr__(self, name: str, value: Any) -> None:
        self[name] = value

    def __delattr__(self, name: str) -> None:
        try:
            del self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def to_dict(self) -> Dict[str, Any]:
        def unwrap(v: Any) -> Any:
            if isinstance(v, dict):
                return {k: unwrap(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [unwrap(x) for x in v]
            return v

        return unwrap(self)

    def get_nested(self, dotted: str, default: Any = None) -> Any:
        node: Any = self
        for part in dotted.split("."):
            if not isinstance(node, Mapping) or part not in node:
                return default
            node = node[part]
        return node


def set_nested(d: dict, dotted: str, value: Any, create: bool = True) -> None:
    """Set a dotted key, creating missing intermediate dicts. An intermediate
    that exists but is NOT a dict is an error — silently clobbering a scalar
    with a dict would corrupt the config on a typo'd key."""
    parts = dotted.split(".")
    node = d
    for p in parts[:-1]:
        if p not in node:
            if not create:
                raise KeyError(f"missing intermediate key {p!r} in {dotted!r}")
            node[p] = {}
        elif not isinstance(node[p], dict):
            raise KeyError(
                f"cannot set {dotted!r}: intermediate key {p!r} holds a non-dict value ({node[p]!r})"
            )
        node = node[p]
    node[parts[-1]] = value


def del_nested(d: dict, dotted: str) -> None:
    parts = dotted.split(".")
    node = d
    for p in parts[:-1]:
        node = node[p]
    del node[parts[-1]]


def polynomial_decay(
    current_step: int,
    *,
    initial: float = 1.0,
    final: float = 0.0,
    max_decay_steps: int = 100,
    power: float = 1.0,
) -> float:
    """Reference ``utils/utils.py:133-145``."""
    if current_step > max_decay_steps or initial == final:
        return final
    return (initial - final) * ((1 - current_step / max_decay_steps) ** power) + final


class Ratio:
    """Replay-ratio controller (reference ``utils/utils.py:261-302``, Hafner's when.py).

    Converts the delta in policy steps since the last call into a number of
    gradient-step repeats so that ``gradient_steps / policy_steps ~= ratio``.
    The fractional residue is carried by keeping ``_prev`` as a float policy
    step. Stateful and checkpointable via ``state_dict``/``load_state_dict``
    (same keys as the reference so resumes are interchangeable).
    """

    def __init__(self, ratio: float, pretrain_steps: int = 0) -> None:
        if pretrain_steps < 0:
            raise ValueError(f"'pretrain_steps' must be non-negative, got {pretrain_steps}")
        if ratio < 0:
            raise ValueError(f"'ratio' must be non-negative, got {ratio}")
        self._pretrain_steps = pretrain_steps
        self._ratio = ratio
        self._prev: float | None = None

    def __call__(self, step: int) -> int:
        if self._ratio == 0:
            return 0
        if self._prev is None:
            self._prev = step
            if self._pretrain_steps > 0:
                if step < self._pretrain_steps:
                    warnings.warn(
                        "The number of pretrain steps is greater than the number of current steps: "
                        "capping 'pretrain_steps' to the current step to keep the requested ratio."
                    )
                    self._pretrain_steps = step
                return int(self._pretrain_steps * self._ratio)
            return 1
        repeats = int((step - self._prev) * self._ratio)
        self._prev += repeats / self._ratio
        return repeats

    def state_dict(self) -> Dict[str, Any]:
        return {"_ratio": self._ratio, "_prev": self._prev, "_pretrain_steps": self._pretrain_steps}

    def load_state_dict(self, state_dict: Mapping[str, Any]) -> "Ratio":
        self._ratio = state_dict["_ratio"]
        self._prev = state_dict["_prev"]
        self._pretrain_steps = state_dict["_pretrain_steps"]
        return self


def save_configs(cfg: Mapping[str, Any], log_dir: str) -> None:
    """Persist the resolved run config (reference ``utils/utils.py:257-259``)."""
    os.makedirs(log_dir, exist_ok=True)
    raw = cfg.to_dict() if isinstance(cfg, dotdict) else dict(cfg)
    with open(os.path.join(log_dir, "config.yaml"), "w") as f:
        yaml.safe_dump(raw, f, sort_keys=False)


def print_config(
    cfg: Mapping[str, Any],
    fields: Sequence[str] = ("algo", "buffer", "checkpoint", "env", "fabric", "metric"),
) -> None:
    """Pretty-print the config tree (reference ``utils/utils.py:208-237``)."""
    try:
        from rich.syntax import Syntax
        from rich.tree import Tree
        import rich

        tree = Tree("CONFIG")
        raw = cfg.to_dict() if isinstance(cfg, dotdict) else dict(cfg)
        for field in fields:
            if field in raw:
                branch = tree.add(field)
                branch.add(Syntax(yaml.safe_dump(raw[field], sort_keys=False), "yaml"))
        rest = {k: v for k, v in raw.items() if k not in fields and not isinstance(v, dict)}
        if rest:
            tree.add(Syntax(yaml.safe_dump(rest, sort_keys=False), "yaml"))
        rich.print(tree)
    except Exception:
        print(yaml.safe_dump(cfg.to_dict() if isinstance(cfg, dotdict) else dict(cfg), sort_keys=False))


class SteadyStateProbe:
    """The ``SHEEPRL_TPU_BENCH_JSON`` steady-state throughput contract, in
    one place (consumed by ``bench.py``; producers are the training loops).

    A loop constructs one probe, calls :meth:`mark` once it considers itself
    warm (compiles done — each loop picks its own rule), and :meth:`finish`
    after its final update with a zero-arg ``sync`` callable that genuinely
    waits for the device (a materializing fetch — ``block_until_ready`` is
    advisory on remote-attached chips)."""

    def __init__(self) -> None:
        import os

        self.path = os.environ.get("SHEEPRL_TPU_BENCH_JSON")
        self._t0: float | None = None
        self._step0 = 0
        self._first_update: int | None = None

    @property
    def active(self) -> bool:
        return self.path is not None

    #: updates past the first train event before the window opens — enough
    #: for every gradient-path compile (incl. the chunked-scan variants) to
    #: have happened, shared by all off-policy loops
    WARMUP_UPDATES = 64

    def mark_warm(self, update: int, learning_starts: int, step: int, work: int = 0) -> None:
        """Open the window once ``update`` reaches the shared warm point —
        the one probe convention of the off-policy/Dreamer loops, kept here
        so it cannot drift. Two conditions, both required:

        - ``learning_starts + WARMUP_UPDATES``: past the first train event's
          compiles (the fresh-run rule);
        - ``first observed update + WARMUP_UPDATES``: a RESUMED run whose
          start update is already beyond the fresh-run warm point still does
          its gradient-path compiles on its first update — opening there
          would put minutes of compile time inside the measured window.
        """
        if self._first_update is None:
            self._first_update = update
        if update >= learning_starts + self.WARMUP_UPDATES and update >= self._first_update + self.WARMUP_UPDATES:
            self.mark(step, work=work)

    def mark(self, step: int, work: int = 0) -> None:
        """``work`` is the loop's cumulative gradient-step counter at the
        mark, so the window's training work can be reported alongside its
        env steps (the MFU numerator needs gradient steps, not env steps)."""
        # every loop's steady-state point doubles as the recompile watchdog's
        # warm point — anything traced past here is a genuine recompile
        from sheeprl_tpu.obs.telemetry import telemetry_mark_warm

        telemetry_mark_warm()
        if self.path is None or self._t0 is not None:
            return
        import time

        self._t0, self._step0, self._work0 = time.perf_counter(), step, work

    def finish(self, step: int, sync=None, work: int = 0, extra=None) -> None:
        """``extra``: optional dict (or zero-arg callable returning one)
        merged into the record AFTER the clock stops — expensive bookkeeping
        like an AOT cost-analysis compile goes here without polluting the
        measured window."""
        if self.path is None:
            return
        import json
        import time

        import jax

        if self._t0 is None:
            # The run ended before the warmup gate opened the window. That is
            # NOT an outage — the workload was simply shorter than
            # learning_starts/WARMUP_UPDATES — so say exactly that, both to
            # bench.py (which raises a targeted error instead of the outage
            # path) and to the telemetry stream.
            detail = (
                f"run ended at step {step} before the steady-state window opened "
                f"(first update {self._first_update}, warmup {self.WARMUP_UPDATES} updates); "
                "raise total_steps or lower learning_starts for this bench"
            )
            from sheeprl_tpu.obs.telemetry import get_telemetry

            tel = get_telemetry()
            if tel is not None:
                tel.emit("bench_probe", error="window_never_opened", detail=detail)
            if jax.process_index() == 0:
                with open(self.path, "w") as f:
                    json.dump({"error": "window_never_opened", "detail": detail}, f)
            return

        if sync is not None:
            sync()
        seconds = time.perf_counter() - self._t0
        if jax.process_index() != 0:  # one writer on multi-process runs
            return
        rec = {"steps": step - self._step0, "seconds": seconds}
        if work:
            rec["train_steps"] = work - getattr(self, "_work0", 0)
        if callable(extra):
            extra = extra()
        if extra:
            rec.update(extra)
        with open(self.path, "w") as f:
            json.dump(rec, f)


def gradient_step_chunks(n_steps: int, algo_cfg: Mapping[str, Any]) -> list:
    """Split a variable gradient-step count into jit-shape-stable pieces.

    The SAC-family loops fuse all G gradient steps of an update into one
    scanned jit whose length is G — but ``Ratio`` varies G (most brutally on
    the first post-warmup update, which repays the whole warmup debt: G in
    the hundreds), and every distinct G compiles a fresh executable (the
    observed 20-minute stall on the remote chip). Chunking caps the set of
    compiled lengths at {chunk} ∪ {possible remainders}: full chunks are
    shape-identical, the scan math is unchanged (scans compose), and only
    the remainder varies. The chunk size comes from
    ``algo.gradient_steps_chunk`` (the SAC-family yamls declare it)."""
    if n_steps <= 0:
        return []
    chunk = int(algo_cfg.get("gradient_steps_chunk", 16) or 16)
    out = [chunk] * (int(n_steps) // chunk)
    rem = int(n_steps) % chunk
    if rem:
        out.append(rem)
    return out


def weighted_chunk_metrics(chunk_metrics: list) -> Any:
    """Gradient-step-weighted mean over ``(chunk_steps, device_metrics)``
    pairs — fetched in ONE host round trip and identical to the
    pre-chunking all-G mean. Companion of :func:`gradient_step_chunks`."""
    import jax
    import numpy as np

    weights = np.array([w for w, _ in chunk_metrics], np.float64)
    stacked = np.asarray(jax.device_get([m for _, m in chunk_metrics]))
    return np.average(stacked, axis=0, weights=weights)
