"""Wall-clock section timers (reference: sheeprl/utils/timer.py:16-84).

Context-decorator accumulating per-key elapsed seconds; algorithms time
``Time/env_interaction_time`` and ``Time/train_time`` and convert them to
steps/sec rates at log time (dreamer_v3.py:710-725). For device work wrap the
timed block's results in ``jax.block_until_ready`` before exiting, or the
async dispatch makes the measurement meaningless.
"""

from __future__ import annotations

import time
from contextlib import ContextDecorator
from typing import Dict, Optional

from sheeprl_tpu.utils.metric import Metric, SumMetric, make_metric


class TimerError(Exception):
    pass


class timer(ContextDecorator):
    disabled: bool = False
    timers: Dict[str, Metric] = {}

    def __init__(self, name: str, metric: Optional[object] = None) -> None:
        self.name = name
        self._start_time: Optional[float] = None
        if not timer.disabled and name is not None and name not in timer.timers:
            timer.timers[name] = make_metric(metric) if metric is not None else SumMetric()

    def start(self) -> None:
        if self._start_time is not None:
            raise TimerError("timer is running. Use .stop() to stop it")
        self._start_time = time.perf_counter()

    def stop(self) -> float:
        if self._start_time is None:
            raise TimerError("timer is not running. Use .start() to start it")
        elapsed = time.perf_counter() - self._start_time
        self._start_time = None
        if self.name:
            timer.timers[self.name].update(elapsed)
        return elapsed

    @classmethod
    def reset(cls) -> None:
        for m in cls.timers.values():
            m.reset()

    @classmethod
    def compute(cls) -> Dict[str, float]:
        return {k: v.compute() for k, v in cls.timers.items()}

    def __enter__(self) -> "timer":
        if not timer.disabled:
            self.start()
        return self

    def __exit__(self, *exc_info) -> None:
        if not timer.disabled:
            self.stop()
