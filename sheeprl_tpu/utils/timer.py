"""Wall-clock section timers — thin shim over the telemetry span.

The implementation moved to :mod:`sheeprl_tpu.obs.span`: ``timer`` IS the
``span`` class, so the class-level ``disabled`` flag and ``timers`` registry
that the CLI and the loops poke keep working, and every timed section
automatically becomes an XLA trace annotation + ``telemetry.jsonl`` event
when ``metric.telemetry.enabled=True``.
"""

from sheeprl_tpu.obs.span import TimerError, span as timer

__all__ = ["TimerError", "timer"]
