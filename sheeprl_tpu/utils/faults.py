"""Shared deterministic fault-injection engine.

Three subsystems run scheduled chaos drills — the env-worker pool
(``rollout.fault_injection``), the serving tier (``serve.fault_injection``)
and the disaggregated actor–learner (``actor_learner.fault_injection``).
They share one doctrine: faults are *scheduled by the owner of a monotone
counter* (pool steps, per-replica batches, admitted slabs, routed requests)
and *executed by the component the counter addresses*, so a crashed and
restarted executor can never lose the record of which faults already fired.
This module is that doctrine, factored once:

- :func:`parse_fault_entries` — the config-list parser all three domains run
  their ``fault_injection.faults`` nodes through (mapping check, required
  keys, typed coercion) before constructing their domain dataclass. The
  domain keeps its own field names (``worker``/``at_step``,
  ``replica``/``at_batch``, ``actor``/``at_slab`` …) — those config keys are
  aliases into the same machinery, not three parsers.
- :class:`DeterministicSchedule` — the fire-once-with-catch-up pending set.
  A fault whose trigger the counter already passed (scheduled while its
  target was restarting) fires on the next query instead of being silently
  dropped; *windowed* faults (e.g. ``slow_inference`` over ``for_batches``)
  stay due for their whole window and then expire. Thread-safe: replica
  threads, the router and swap watchers query concurrently.
- :func:`register_fault_domain` / :func:`fault_domains` — the domain
  registry. Every domain module declares its fault-kind vocabulary here at
  import, so drill-coverage tooling (``bench.py --drills`` →
  ``tools/drills.py``) audits which fault keys the test suite exercises
  against one authoritative list instead of folklore.

The domain modules stay the public surface (their specs, kinds and config
shapes are unchanged); they are thin adapters over this engine.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

# (field name, coercion, default) — None default means the field is required
# when listed in ``required``; coercions run with ``or``-style zero fallback
# for floats so YAML ``null`` composes to 0.0 like the historical parsers.
FieldSpec = Tuple[str, Callable[[Any], Any], Any]

# domain name -> ordered fault-kind vocabulary. Populated by the domain
# modules at import (rollout/serve/actor_learner/online); read by the drill
# auditor. A plain module dict: registration is import-time only.
_FAULT_DOMAINS: Dict[str, Tuple[str, ...]] = {}


def register_fault_domain(domain: str, kinds: Sequence[str]) -> None:
    """Declare ``domain``'s fault-kind vocabulary (idempotent; a re-import
    re-registering identical kinds is a no-op, a conflicting registration
    is a programming error surfaced immediately)."""
    entry = tuple(str(k) for k in kinds)
    existing = _FAULT_DOMAINS.get(domain)
    if existing is not None and existing != entry:
        raise ValueError(
            f"fault domain {domain!r} re-registered with different kinds: {existing} != {entry}"
        )
    _FAULT_DOMAINS[domain] = entry


def fault_domains() -> Dict[str, Tuple[str, ...]]:
    """Snapshot of every registered domain's kinds (import the domain
    modules first — registration happens at import)."""
    return dict(_FAULT_DOMAINS)


def parse_fault_entries(
    node: Sequence[Mapping[str, Any]],
    *,
    domain: str,
    required: Sequence[str] = ("kind",),
    fields: Sequence[FieldSpec] = (),
) -> List[Dict[str, Any]]:
    """Normalize one ``fault_injection.faults`` config list.

    Returns one plain dict per entry: ``kind`` (always) plus every field in
    ``fields`` coerced to its declared type (entry value, else default).
    Raises ``ValueError`` with the ``domain``-prefixed messages the three
    historical parsers raised; kind membership and range checks stay with
    the domain dataclasses, which remain the validation authority.
    """
    out: List[Dict[str, Any]] = []
    for i, entry in enumerate(node):
        if not hasattr(entry, "get"):
            raise ValueError(f"{domain}.faults[{i}] must be a mapping, got {entry!r}")
        missing = [k for k in required if k not in entry]
        if missing:
            need = "/".join(required)
            raise ValueError(f"{domain}.faults[{i}] needs {need}, got {dict(entry)!r}")
        parsed: Dict[str, Any] = {"kind": entry["kind"]}
        for name, coerce, default in fields:
            raw = entry.get(name, default)
            if coerce is float:
                parsed[name] = float(raw or 0.0)
            else:
                parsed[name] = coerce(raw)
        out.append(parsed)
    return out


class DeterministicSchedule:
    """Fire-once (with catch-up) pending set over a monotone counter.

    ``at(item)`` reads an item's trigger value, ``index(item)`` its target
    index (``None`` = untargeted), ``window(item)`` its due-window length
    (1 = instant). All three are captured at construction so domain specs
    keep their own field names.
    """

    def __init__(
        self,
        items: Sequence[Any],
        *,
        at: Callable[[Any], int],
        index: Optional[Callable[[Any], Optional[int]]] = None,
        window: Optional[Callable[[Any], int]] = None,
    ) -> None:
        self._lock = threading.Lock()
        self._at = at
        self._index = index or (lambda item: None)
        self._window = window or (lambda item: 1)
        self._pending: List[Any] = sorted(items, key=at)

    def __bool__(self) -> bool:
        with self._lock:
            return bool(self._pending)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)

    def pop_due(self, counter: int, index: Optional[int] = None) -> List[Any]:
        """Items due at (or before — catch-up, nothing is silently dropped)
        ``counter``. With ``index`` given, only items targeting that index
        are considered; others stay pending for their own target's counter.
        Instant items are marked fired; windowed items stay scheduled until
        their window passes, then expire without firing again."""
        due: List[Any] = []
        with self._lock:
            remaining: List[Any] = []
            for item in self._pending:
                target = self._index(item)
                if index is not None and target is not None and target != index:
                    remaining.append(item)
                    continue
                at, win = self._at(item), self._window(item)
                if win > 1:
                    if at <= counter < at + win:
                        due.append(item)
                        remaining.append(item)  # stays due for its window
                    elif counter < at:
                        remaining.append(item)
                    # else: window over — expire silently
                elif at <= counter:
                    due.append(item)
                else:
                    remaining.append(item)
            self._pending = remaining
        return due

    def pop_first(self, counter: int) -> Optional[Any]:
        """Remove and return the earliest-scheduled item due at ``counter``
        (``None`` when nothing is due) — at most one fires per query, the
        swap-attempt semantics."""
        with self._lock:
            for item in self._pending:
                if self._at(item) <= counter:
                    self._pending.remove(item)
                    return item
        return None

    def pop_due_by_index(self, counter: int) -> Dict[int, List[Any]]:
        """All due items grouped by target index (the pool-step shape: one
        query serves every worker)."""
        grouped: Dict[int, List[Any]] = {}
        for item in self.pop_due(counter):
            grouped.setdefault(int(self._index(item) or 0), []).append(item)
        return grouped
