"""Run loggers (reference: sheeprl/utils/logger.py:12-89).

TensorBoard writer built on process 0 only; the versioned log dir is chosen
on process 0 and broadcast so every host agrees (the reference broadcasts it
over gloo, logger.py:83-88 — here it rides ``broadcast_object``).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Mapping, Optional

from sheeprl_tpu.parallel.collectives import broadcast_object


class TensorBoardLogger:
    """Thin SummaryWriter wrapper with the subset of the lightning logger API
    the algorithms use (log_metrics / log_hyperparams / finalize)."""

    def __init__(self, log_dir: str) -> None:
        from torch.utils.tensorboard import SummaryWriter

        os.makedirs(log_dir, exist_ok=True)
        self.log_dir = log_dir
        self._writer = SummaryWriter(log_dir=log_dir)

    def log_metrics(self, metrics: Mapping[str, float], step: int) -> None:
        for k, v in metrics.items():
            self._writer.add_scalar(k, v, global_step=step)

    def log_hyperparams(self, params: Mapping[str, Any]) -> None:
        try:
            import yaml

            self._writer.add_text("hparams", f"```\n{yaml.safe_dump(dict(params), sort_keys=False)}\n```")
        except Exception:
            pass

    def finalize(self) -> None:
        self._writer.flush()
        self._writer.close()


class MlflowLogger:
    """MLflow run logger with the same log_metrics / log_hyperparams /
    finalize surface (reference: lightning MLFlowLogger selected by
    sheeprl/configs/logger/mlflow.yaml). Import-gated — building it without
    mlflow installed raises at construction, not at framework import."""

    def __init__(
        self,
        tracking_uri: Optional[str] = None,
        experiment_name: str = "sheeprl_tpu",
        run_name: Optional[str] = None,
        tags: Optional[Dict[str, Any]] = None,
        log_dir: Optional[str] = None,
    ) -> None:
        from sheeprl_tpu.utils.imports import _IS_MLFLOW_AVAILABLE

        if not _IS_MLFLOW_AVAILABLE:
            raise ModuleNotFoundError(
                "logger.name=mlflow requires the 'mlflow' package (pip install mlflow)"
            )
        import mlflow

        self._mlflow = mlflow
        self.log_dir = log_dir
        if tracking_uri is None:
            tracking_uri = os.environ.get("MLFLOW_TRACKING_URI")
        if tracking_uri:
            mlflow.set_tracking_uri(tracking_uri)
        mlflow.set_experiment(experiment_name)
        self._run = mlflow.start_run(run_name=run_name, tags=tags)
        self.run_id = self._run.info.run_id

    def log_metrics(self, metrics: Mapping[str, float], step: int) -> None:
        import math

        # drop non-finite values: SQL-backed mlflow stores reject NaN/inf
        clean = {k: float(v) for k, v in metrics.items() if math.isfinite(v)}
        if clean:
            self._mlflow.log_metrics(clean, step=step)

    def log_hyperparams(self, params: Mapping[str, Any]) -> None:
        def _flatten(d: Mapping[str, Any], prefix: str = "") -> Dict[str, Any]:
            out: Dict[str, Any] = {}
            for k, v in d.items():
                key = f"{prefix}{k}"
                if isinstance(v, Mapping):
                    out.update(_flatten(v, key + "."))
                else:
                    out[key] = v
            return out

        flat = _flatten(dict(params))
        # mlflow caps params per batch; chunk defensively
        items = list(flat.items())
        for i in range(0, len(items), 100):
            self._mlflow.log_params(dict(items[i : i + 100]))

    def finalize(self) -> None:
        self._mlflow.end_run()


class NoOpLogger:
    """Used on non-zero processes and when logging is disabled."""

    log_dir: Optional[str] = None

    def log_metrics(self, metrics: Mapping[str, float], step: int) -> None:
        pass

    def log_hyperparams(self, params: Mapping[str, Any]) -> None:
        pass

    def finalize(self) -> None:
        pass


def run_base_dir(cfg: Mapping[str, Any], root_dir: Optional[str] = None, run_name: Optional[str] = None) -> str:
    """The run's TB root ``<log_base_dir>/<root_dir>/<run_name>`` — the parent
    of the versioned dirs; also where profiler traces land."""
    root_dir = root_dir or cfg["root_dir"]
    run_name = run_name or cfg["run_name"]
    base_dir = cfg.get("log_base_dir") or os.path.join("logs", "runs")
    return os.path.join(base_dir, root_dir, run_name)


def get_log_dir(cfg: Mapping[str, Any], root_dir: Optional[str] = None, run_name: Optional[str] = None) -> str:
    """Versioned run directory ``<root>/<run_name>/version_N``, chosen once on
    process 0 and broadcast (reference logger.py:39-89)."""
    import jax

    base = run_base_dir(cfg, root_dir, run_name)
    if jax.process_index() == 0:
        version = 0
        while os.path.isdir(os.path.join(base, f"version_{version}")):
            version += 1
        log_dir = os.path.join(base, f"version_{version}")
        os.makedirs(log_dir, exist_ok=True)
    else:
        log_dir = None
    return broadcast_object(log_dir, src=0)


def get_logger(cfg: Mapping[str, Any], log_dir: str):
    """Build the process-0 logger (reference logger.py:12-36). Returns a
    NoOpLogger on other processes or when ``metric.log_level`` is 0."""
    import jax

    metric_cfg: Dict[str, Any] = cfg.get("metric", {})
    if jax.process_index() != 0 or int(metric_cfg.get("log_level", 1)) <= 0:
        return NoOpLogger()
    logger_cfg = cfg.get("logger", {}) or {}
    kind = str(logger_cfg.get("name", "tensorboard")).lower()
    if kind == "tensorboard":
        return TensorBoardLogger(log_dir)
    if kind == "mlflow":
        return MlflowLogger(
            tracking_uri=logger_cfg.get("tracking_uri"),
            experiment_name=str(logger_cfg.get("experiment_name", cfg.get("exp_name", "sheeprl_tpu"))),
            run_name=logger_cfg.get("mlflow_run_name") or cfg.get("run_name"),
            tags=logger_cfg.get("tags"),
            log_dir=log_dir,
        )
    raise ValueError(f"unknown logger {kind!r}; available: ['tensorboard', 'mlflow']")
