"""Checkpoint serialization (reference: fabric.save/load via lightning).

Two backends:

- ``pickle`` — one atomically-written file. Fine for single-host runs.
- ``orbax`` — the pod-grade path: the checkpoint becomes a DIRECTORY in which
  every array leaf is written through orbax's parallel OCDBT store.
  ``jax.Array`` leaves keep their shardings — on multi-host runs each process
  writes only the shards it owns (no host-dense gather) — while non-array
  state (Ratio dicts, counters) rides a shared pickle sidecar and per-process
  state (replay buffers) rides one ``objects_rank_{i}.pkl`` per process. This
  replaces the reference's gloo-gather + single torch.save with storage that
  scales to pod-sized param trees.

Restore materializes arrays to host numpy so checkpoints reload across
process counts; the loading run re-places them under its own mesh.
"""

from __future__ import annotations

import os
import pickle
import shutil
import tempfile
from typing import Any, Dict, List, Tuple, Union

import jax
import numpy as np

_ARRAY_SENTINEL = "__sheeprl_tpu_array__"


def _to_host(tree: Any) -> Any:
    def leaf(x: Any) -> Any:
        if isinstance(x, jax.Array):
            return np.asarray(jax.device_get(x))
        return x

    return jax.tree.map(leaf, tree)


def _split_arrays(tree: Any) -> Tuple[Any, Dict[str, Any]]:
    """Replace every array leaf with a sentinel key and collect the arrays
    into one flat dict for the orbax store. ``jax.Array`` leaves are kept AS
    IS — sharded device arrays ride orbax's distributed write path without a
    host-dense copy; numpy leaves pass through unchanged."""
    arrays: Dict[str, Any] = {}

    def walk(node: Any) -> Any:
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            out = [walk(v) for v in node]
            return type(node)(*out) if hasattr(node, "_fields") else type(node)(out)
        if isinstance(node, (np.ndarray, jax.Array)):
            key = f"k{len(arrays)}"
            arrays[key] = node
            return _ARRAY_SENTINEL + key
        return node

    return walk(tree), arrays


def _join_arrays(tree: Any, arrays: Dict[str, np.ndarray]) -> Any:
    def walk(node: Any) -> Any:
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            out = [walk(v) for v in node]
            return type(node)(*out) if hasattr(node, "_fields") else type(node)(out)
        if isinstance(node, str) and node.startswith(_ARRAY_SENTINEL):
            return arrays[node[len(_ARRAY_SENTINEL) :]]
        return node

    return walk(tree)


def _host_barrier() -> None:
    """Sync every process over the host-object plane (free single-process)."""
    if jax.process_count() > 1:
        from sheeprl_tpu.parallel.collectives import host_allreduce_sum

        host_allreduce_sum(1.0)


def save_checkpoint(
    path: str,
    state: Dict[str, Any],
    backend: str = "pickle",
    per_process_state: Dict[str, Any] | None = None,
    manifest: Dict[str, Any] | None = None,
) -> None:
    """Write ``state`` to ``path``. Both backends are crash-atomic: the
    payload is fully staged under a temp name and promoted by rename, and
    when ``manifest`` is given it lands strictly AFTER the payload as the
    commit marker (``sheeprl_tpu.resilience.manifest``) — a crash at any
    point leaves either the previous committed checkpoint or a torn staging
    entry that pruning garbage-collects, never a half-written checkpoint
    under the final name.

    Orbax path: ``path`` becomes a directory. ``jax.Array`` leaves are handed
    to the OCDBT store with their shardings intact — on multi-host runs every
    process writes only the shards it owns (no host-dense gather).
    ``per_process_state`` (e.g. this process's replay buffer) is written as
    ``objects_rank_{i}.pkl`` by every process; all sidecars land before the
    manifest and the directory promote, so a visible directory is always
    complete. :func:`load_checkpoint` reassembles the per-rank values into
    lists for :func:`select_buffer`."""
    if backend == "orbax":
        import orbax.checkpoint as ocp

        from sheeprl_tpu.resilience.manifest import TMP_PREFIX, write_manifest

        skeleton, arrays = _split_arrays(state)
        # Stage EVERYTHING in a hidden temp dir next to the destination and
        # promote with one rename at the end. The temp name is deterministic
        # (no pid) because on multi-host runs every process must write into
        # the same directory; process 0 owns creation and the promote.
        parent = os.path.dirname(os.path.abspath(path)) or "."
        tmp_dir = os.path.join(parent, TMP_PREFIX + os.path.basename(path))
        if jax.process_index() == 0:
            if os.path.isdir(tmp_dir):
                shutil.rmtree(tmp_dir)
            os.makedirs(tmp_dir, exist_ok=True)
        # every process must reach the orbax save (it runs its own process
        # barriers on multi-host); only process 0 touches the shared sidecar
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(os.path.join(tmp_dir, "arrays")), arrays or {"__empty__": np.zeros(1)})
        ckptr.wait_until_finished()
        if jax.process_index() == 0:
            with open(os.path.join(tmp_dir, "objects.pkl"), "wb") as f:
                pickle.dump(skeleton, f, protocol=pickle.HIGHEST_PROTOCOL)
        if per_process_state is not None:
            rank_path = os.path.join(tmp_dir, f"objects_rank_{jax.process_index()}.pkl")
            with open(rank_path, "wb") as f:
                pickle.dump(_to_host(per_process_state), f, protocol=pickle.HIGHEST_PROTOCOL)
        # all sidecars must land before the commit marker and the promote
        _host_barrier()
        if jax.process_index() == 0:
            if manifest is not None:
                write_manifest(tmp_dir, manifest)
            if os.path.isdir(path):
                # re-saving the same step: move the old dir aside first so a
                # crash between delete and promote cannot lose both copies
                trash = os.path.join(parent, TMP_PREFIX + "trash-" + os.path.basename(path))
                if os.path.isdir(trash):
                    shutil.rmtree(trash)
                os.replace(path, trash)
                os.replace(tmp_dir, path)
                shutil.rmtree(trash, ignore_errors=True)
            else:
                os.replace(tmp_dir, path)
        _host_barrier()
        return
    if backend != "pickle":
        raise ValueError(f"unknown checkpoint backend {backend!r} (choose 'pickle' or 'orbax')")
    host_state = _to_host(state)
    if per_process_state is not None:
        host_state = {**host_state, **_to_host(per_process_state)}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise
    if manifest is not None:
        from sheeprl_tpu.resilience.manifest import write_manifest

        write_manifest(path, manifest)


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Load either backend (directories are orbax checkpoints). Per-rank
    sidecars (``objects_rank_{i}.pkl``) are reassembled into lists keyed like
    the original ``per_process_state`` — :func:`select_buffer` then picks the
    restoring process's entry."""
    if os.path.isdir(path):
        import glob as _glob

        import orbax.checkpoint as ocp

        with open(os.path.join(path, "objects.pkl"), "rb") as f:
            skeleton = pickle.load(f)
        ckptr = ocp.StandardCheckpointer()
        arrays = ckptr.restore(os.path.abspath(os.path.join(path, "arrays")))
        state = _join_arrays(skeleton, dict(arrays))
        rank_files = sorted(
            _glob.glob(os.path.join(path, "objects_rank_*.pkl")),
            key=lambda p: int(p.rsplit("_", 1)[1].split(".")[0]),
        )
        if rank_files:
            per_rank = []
            for rf in rank_files:
                with open(rf, "rb") as f:
                    per_rank.append(pickle.load(f))
            for key in per_rank[0]:
                state[key] = [p[key] for p in per_rank]
        return state
    with open(path, "rb") as f:
        return pickle.load(f)


def select_buffer(rb_state: Union[Any, List[Any]], process_index: int, num_processes: int) -> Any:
    """Pick this process's replay buffer from a checkpoint (reference
    dreamer_v1.py:487-494): multi-host checkpoints store one buffer per
    process (gathered by the checkpoint callback); single-host ones store the
    buffer directly."""
    if isinstance(rb_state, list):
        if len(rb_state) == num_processes:
            return rb_state[process_index]
        if num_processes == 1:
            return rb_state[0]
        raise RuntimeError(
            f"checkpoint holds {len(rb_state)} replay buffers but {num_processes} processes are running"
        )
    return rb_state


def elastic_per_rank_batch_size(global_batch: int, world_size: int) -> int:
    """Re-split a checkpoint's stored GLOBAL batch over the resuming run's
    data-parallel width. Fails fast when it doesn't divide (or divides to
    zero): an elastic resume changed the mesh, and silently flooring would
    shrink the global batch and compound on every subsequent resume."""
    if world_size <= 0 or global_batch % world_size != 0 or global_batch // world_size == 0:
        raise ValueError(
            f"cannot resume: the checkpoint's global batch size ({global_batch}) does not split "
            f"evenly over {world_size} data-parallel devices — resume on a mesh whose data axis "
            f"divides {global_batch}, or start a fresh run"
        )
    return global_batch // world_size
