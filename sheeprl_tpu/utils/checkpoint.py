"""Checkpoint serialization (reference: fabric.save/load via lightning;
callback.py:87-142 buffer fixup semantics live in the algorithms).

State trees mix jax array pytrees (params, optimizer state), plain Python
state dicts (Ratio, counters) and optionally replay-buffer numpy arrays.
Everything is pulled to host (``jax.device_get``) and pickled atomically —
single-file checkpoints that restore across process counts (sharded arrays
are saved dense; on load the trainer re-places them under its own mesh).
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Dict

import jax
import numpy as np


def _to_host(tree: Any) -> Any:
    def leaf(x: Any) -> Any:
        if isinstance(x, jax.Array):
            return np.asarray(jax.device_get(x))
        return x

    return jax.tree.map(leaf, tree)


def save_checkpoint(path: str, state: Dict[str, Any]) -> None:
    """Atomic single-file checkpoint write (tmp + rename)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    host_state = _to_host(state)
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            pickle.dump(host_state, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise


def load_checkpoint(path: str) -> Dict[str, Any]:
    with open(path, "rb") as f:
        return pickle.load(f)
