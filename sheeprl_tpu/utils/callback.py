"""Checkpoint callback (reference: sheeprl/utils/callback.py:14-148).

Invoked by algorithms through ``fabric.call("on_checkpoint_coupled", ...)``;
serialization goes through ``sheeprl_tpu.utils.checkpoint`` (pickle or
orbax backend) and old checkpoints are pruned with ``keep_last``.

Every save commits a manifest (``sheeprl_tpu.resilience.manifest``) as its
last write, so pruning / auto-resume / NaN-rollback only ever see complete
checkpoints. With ``checkpoint.async_save=True`` (single-process runs) the
hook blocks only for the host snapshot — a ``ckpt/snapshot`` span — and the
serialization + commit + prune run on the resilience background writer under
``ckpt/write``; at most one save is in flight and an overlapping request is
dropped with a ``ckpt_skipped`` event. Multi-process saves stay synchronous:
both the orbax store's commit barriers and the pickle buffer gather are
collectives every rank must enter, which a background thread cannot
guarantee. ``emergency=True`` (the preemption drain) also forces sync.

When a replay buffer rides the checkpoint, the stored copy must be
self-consistent without the live env state: the last stored step of every
env is flagged TRUNCATED for the save and restored right after (reference
``_ckpt_rb`` / ``_experiment_consistent_rb``, callback.py:87-142); open
episodes of an ``EpisodeBuffer`` are dropped the same way. For async saves
the buffer is deep-snapshotted (pickle round-trip) inside the snapshot span
so the env loop can keep writing while the background thread serializes. On
multi-host runs the pickle backend gathers every process's buffer over the
host-object plane into a one-per-process list (reference gloo
``gather_object``, callback.py:40-51); the orbax backend skips the gather —
each process writes its own buffer sidecar next to the sharded array store.
Both restore through ``checkpoint.select_buffer``.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, List, Optional

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, EpisodeBuffer, ReplayBuffer


class CheckpointCallback:
    def __init__(
        self,
        keep_last: Optional[int] = None,
        backend: str = "pickle",
        async_save: bool = False,
    ) -> None:
        self.keep_last = keep_last
        self.backend = backend
        self.async_save = bool(async_save)

    def _use_async(self, fabric: Any, emergency: bool) -> bool:
        return self.async_save and not emergency and fabric.num_processes == 1

    def on_checkpoint_coupled(
        self,
        fabric: Any,
        ckpt_path: str,
        state: Dict[str, Any],
        replay_buffer: Any = None,
        gather_buffers: bool = True,
        backend: str = None,
        emergency: bool = False,
    ) -> None:
        backend = backend or self.backend
        from sheeprl_tpu.obs import span, telemetry_ckpt_commit
        from sheeprl_tpu.resilience.manifest import build_manifest, checkpoint_step
        from sheeprl_tpu.utils.checkpoint import save_checkpoint

        step = checkpoint_step(ckpt_path)
        step = 0 if step is None else step
        extra = {"emergency": True} if emergency else None

        if backend == "orbax":
            # the orbax store coordinates its own multi-process write
            # barriers, so EVERY process must enter save_checkpoint with the
            # SAME directory (per-rank paths would break the collective
            # commit). Buffers skip the object-plane gather entirely: each
            # process writes its own objects_rank_{i}.pkl sidecar
            path = ckpt_path
            if fabric.num_processes > 1:
                import re

                path = re.sub(r"_\d+(\.ckpt)$", r"_0\1", ckpt_path)
            if self._use_async(fabric, emergency):
                writer = self._writer()
                if writer.busy:
                    writer.record_skip(path=path, step=step)
                    return
                with span("ckpt/snapshot", path=path, ckpt_step=step):
                    rb_flags = self._ckpt_rb(replay_buffer) if replay_buffer is not None else None
                    host_state = self._snapshot_tree(state)
                    per_proc = (
                        {"rb": self._snapshot_buffer(replay_buffer)}
                        if replay_buffer is not None
                        else None
                    )
                    if replay_buffer is not None:
                        self._experiment_consistent_rb(replay_buffer, rb_flags)
                manifest = build_manifest(
                    step=step, backend="orbax", world_size=fabric.world_size, state=host_state, extra=extra
                )
                self._submit(writer, path, step, host_state, "orbax", per_proc, manifest)
                return
            rb_flags = self._ckpt_rb(replay_buffer) if replay_buffer is not None else None
            per_proc = {"rb": replay_buffer} if replay_buffer is not None else None
            manifest = build_manifest(
                step=step, backend="orbax", world_size=fabric.world_size, state=state, extra=extra
            )
            with span("ckpt/write", path=path, ckpt_step=step, sync=True):
                save_checkpoint(path, state, backend=backend, per_process_state=per_proc, manifest=manifest)
            if fabric.is_global_zero:
                telemetry_ckpt_commit(path, step, "orbax", emergency)
            if replay_buffer is not None:
                self._experiment_consistent_rb(replay_buffer, rb_flags)
            if fabric.is_global_zero and self.keep_last:
                self._prune(os.path.dirname(path))
            return

        # pickle backend
        if self._use_async(fabric, emergency):
            writer = self._writer()
            if writer.busy:
                writer.record_skip(path=ckpt_path, step=step)
                return
            with span("ckpt/snapshot", path=ckpt_path, ckpt_step=step):
                rb_flags = self._ckpt_rb(replay_buffer) if replay_buffer is not None else None
                host_state = self._snapshot_tree(state)
                if replay_buffer is not None:
                    host_state = {**host_state, "rb": self._snapshot_buffer(replay_buffer)}
                    self._experiment_consistent_rb(replay_buffer, rb_flags)
            manifest = build_manifest(
                step=step, backend="pickle", world_size=fabric.world_size, state=host_state, extra=extra
            )
            self._submit(writer, ckpt_path, step, host_state, "pickle", None, manifest)
            return
        rb_state = self._ckpt_rb(replay_buffer) if replay_buffer is not None else None
        if replay_buffer is not None:
            rb_to_save: Any = replay_buffer
            if gather_buffers and fabric.num_processes > 1:
                from sheeprl_tpu.parallel.collectives import gather_object

                gathered = gather_object(replay_buffer, dst=0)
                rb_to_save = gathered if fabric.is_global_zero else replay_buffer
            state = {**state, "rb": rb_to_save}
        if fabric.is_global_zero:
            manifest = build_manifest(
                step=step, backend="pickle", world_size=fabric.world_size, state=state, extra=extra
            )
            with span("ckpt/write", path=ckpt_path, ckpt_step=step, sync=True):
                save_checkpoint(ckpt_path, state, backend=backend, manifest=manifest)
            telemetry_ckpt_commit(ckpt_path, step, "pickle", emergency)
        if replay_buffer is not None:
            self._experiment_consistent_rb(replay_buffer, rb_state)
        if fabric.is_global_zero and self.keep_last:
            self._prune(os.path.dirname(ckpt_path))

    # Decoupled topologies save from the player with trainer-provided state
    # (reference callback.py:58-78). Only the player enters this hook, so no
    # buffer gather must run — it would be a collective the trainer processes
    # never join (and the player owns the only buffer in this topology).
    def on_checkpoint_player(
        self, fabric: Any, ckpt_path: str, state: Dict[str, Any], replay_buffer: Any = None
    ) -> None:
        backend = self.backend
        if backend == "orbax" and fabric.num_processes > 1:
            import warnings

            warnings.warn(
                "the orbax backend needs every process at its save barrier, but only the "
                "decoupled player checkpoints — falling back to pickle for this save"
            )
            backend = "pickle"
        self.on_checkpoint_coupled(
            fabric, ckpt_path, state, replay_buffer, gather_buffers=False, backend=backend
        )

    # ------------------------------------------------------------------ #
    # async plumbing
    # ------------------------------------------------------------------ #
    @staticmethod
    def _writer() -> Any:
        from sheeprl_tpu.resilience.async_writer import get_async_writer

        return get_async_writer()

    def _submit(
        self,
        writer: Any,
        path: str,
        step: int,
        state: Dict[str, Any],
        backend: str,
        per_proc: Optional[Dict[str, Any]],
        manifest: Dict[str, Any],
    ) -> None:
        from sheeprl_tpu.obs import telemetry_ckpt_commit
        from sheeprl_tpu.utils.checkpoint import save_checkpoint

        def write() -> None:
            save_checkpoint(path, state, backend=backend, per_process_state=per_proc, manifest=manifest)
            telemetry_ckpt_commit(path, step, backend, bool(manifest.get("emergency", False)))
            if self.keep_last:
                self._prune(os.path.dirname(path))

        writer.submit(write, path=path, step=step)

    @staticmethod
    def _snapshot_tree(tree: Any) -> Any:
        """Deep host copy of every array leaf: device arrays come to host,
        numpy leaves are copied so the background pickle cannot race the env
        loop mutating them in place."""
        import jax
        import numpy as np

        def leaf(x: Any) -> Any:
            if isinstance(x, jax.Array):
                return np.asarray(jax.device_get(x))
            if isinstance(x, np.ndarray):
                return x.copy()
            return x

        return jax.tree.map(leaf, tree)

    @staticmethod
    def _snapshot_buffer(rb: Any) -> Any:
        """Detached deep copy of a replay buffer (pickle round-trip — every
        buffer type already defines checkpoint pickling semantics)."""
        import pickle

        return pickle.loads(pickle.dumps(rb, protocol=pickle.HIGHEST_PROTOCOL))

    # ------------------------------------------------------------------ #
    # buffer consistency (reference callback.py:87-142)
    # ------------------------------------------------------------------ #
    @staticmethod
    def _ckpt_rb(rb: Any) -> Any:
        """Make the stored buffer self-consistent: the env state is not
        checkpointed, so the last stored step must end its episode. Returns
        the clobbered values for the undo."""
        if hasattr(rb, "flag_last_truncated"):  # DeviceReplayBuffer (HBM ring)
            return rb.flag_last_truncated()
        if isinstance(rb, EnvIndependentReplayBuffer):
            saved: List[Any] = []
            for b in rb.buffer:
                saved.append(b["truncated"][(b._pos - 1) % b.buffer_size, :].copy())
                b["truncated"][(b._pos - 1) % b.buffer_size, :] = 1
            return saved
        if isinstance(rb, ReplayBuffer):
            saved = rb["truncated"][(rb._pos - 1) % rb.buffer_size, :].copy()
            rb["truncated"][(rb._pos - 1) % rb.buffer_size, :] = 1
            return saved
        if isinstance(rb, EpisodeBuffer):
            saved = rb._open_episodes
            rb._open_episodes = [[] for _ in range(rb.n_envs)]
            return saved
        return None

    @staticmethod
    def _experiment_consistent_rb(rb: Any, saved: Any) -> None:
        """Undo :meth:`_ckpt_rb` so the live run continues unchanged."""
        if hasattr(rb, "restore_last_truncated"):  # DeviceReplayBuffer
            rb.restore_last_truncated(saved)
        elif isinstance(rb, EnvIndependentReplayBuffer):
            for b, s in zip(rb.buffer, saved):
                b["truncated"][(b._pos - 1) % b.buffer_size, :] = s
        elif isinstance(rb, ReplayBuffer):
            rb["truncated"][(rb._pos - 1) % rb.buffer_size, :] = saved
        elif isinstance(rb, EpisodeBuffer):
            rb._open_episodes = saved

    def _prune(self, ckpt_dir: str) -> None:
        """Prune by MANIFEST STEP, not mtime: only committed checkpoints
        count against ``keep_last`` (a torn write or a foreign file must not
        evict a good checkpoint, and clock skew must not delete the newest),
        unrecognized entries are left alone, and torn writes matching our
        naming scheme are garbage-collected. Runs where no save can be in
        flight: after a sync commit, or on the background writer thread
        after its own commit."""
        if not os.path.isdir(ckpt_dir):
            return
        from sheeprl_tpu.resilience.manifest import MANIFEST_SUFFIX, committed_checkpoints, gc_torn

        gc_torn(ckpt_dir)
        committed = committed_checkpoints(ckpt_dir)  # oldest step first
        stale = committed[: -self.keep_last] if len(committed) > self.keep_last else []
        for ckpt in stale:
            try:
                if os.path.isdir(ckpt.path):
                    shutil.rmtree(ckpt.path)
                else:
                    os.remove(ckpt.path)
                    sidecar = ckpt.path + MANIFEST_SUFFIX
                    if os.path.isfile(sidecar):
                        os.remove(sidecar)
            except OSError:
                pass
