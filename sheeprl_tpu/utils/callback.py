"""Checkpoint callback (reference: sheeprl/utils/callback.py:14-148).

Invoked by algorithms through ``fabric.call("on_checkpoint_coupled", ...)``;
serialization goes through ``sheeprl_tpu.utils.checkpoint`` (pickle or
orbax backend) and old checkpoints are pruned with ``keep_last``.

When a replay buffer rides the checkpoint, the stored copy must be
self-consistent without the live env state: the last stored step of every
env is flagged TRUNCATED for the save and restored right after (reference
``_ckpt_rb`` / ``_experiment_consistent_rb``, callback.py:87-142); open
episodes of an ``EpisodeBuffer`` are dropped the same way. On multi-host
runs the pickle backend gathers every process's buffer over the host-object
plane into a one-per-process list (reference gloo ``gather_object``,
callback.py:40-51); the orbax backend skips the gather — each process writes
its own buffer sidecar next to the sharded array store. Both restore through
``checkpoint.select_buffer``.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, List, Optional

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, EpisodeBuffer, ReplayBuffer


class CheckpointCallback:
    def __init__(self, keep_last: Optional[int] = None, backend: str = "pickle") -> None:
        self.keep_last = keep_last
        self.backend = backend

    def on_checkpoint_coupled(
        self,
        fabric: Any,
        ckpt_path: str,
        state: Dict[str, Any],
        replay_buffer: Any = None,
        gather_buffers: bool = True,
        backend: str = None,
    ) -> None:
        backend = backend or self.backend
        rb_state = None
        if replay_buffer is not None:
            rb_state = self._ckpt_rb(replay_buffer)
        from sheeprl_tpu.utils.checkpoint import save_checkpoint

        if backend == "orbax":
            # the orbax store coordinates its own multi-process write
            # barriers, so EVERY process must enter save_checkpoint with the
            # SAME directory (per-rank paths would break the collective
            # commit). Buffers skip the object-plane gather entirely: each
            # process writes its own objects_rank_{i}.pkl sidecar
            path = ckpt_path
            if fabric.num_processes > 1:
                import re

                path = re.sub(r"_\d+(\.ckpt)$", r"_0\1", ckpt_path)
            per_proc = {"rb": replay_buffer} if replay_buffer is not None else None
            save_checkpoint(path, state, backend=backend, per_process_state=per_proc)
        else:
            if replay_buffer is not None:
                rb_to_save: Any = replay_buffer
                if gather_buffers and fabric.num_processes > 1:
                    from sheeprl_tpu.parallel.collectives import gather_object

                    gathered = gather_object(replay_buffer, dst=0)
                    rb_to_save = gathered if fabric.is_global_zero else replay_buffer
                state = {**state, "rb": rb_to_save}
            if fabric.is_global_zero:
                save_checkpoint(ckpt_path, state, backend=backend)
        if replay_buffer is not None:
            self._experiment_consistent_rb(replay_buffer, rb_state)
        if fabric.is_global_zero and self.keep_last:
            self._prune(os.path.dirname(ckpt_path))

    # Decoupled topologies save from the player with trainer-provided state
    # (reference callback.py:58-78). Only the player enters this hook, so no
    # buffer gather must run — it would be a collective the trainer processes
    # never join (and the player owns the only buffer in this topology).
    def on_checkpoint_player(
        self, fabric: Any, ckpt_path: str, state: Dict[str, Any], replay_buffer: Any = None
    ) -> None:
        backend = self.backend
        if backend == "orbax" and fabric.num_processes > 1:
            import warnings

            warnings.warn(
                "the orbax backend needs every process at its save barrier, but only the "
                "decoupled player checkpoints — falling back to pickle for this save"
            )
            backend = "pickle"
        self.on_checkpoint_coupled(
            fabric, ckpt_path, state, replay_buffer, gather_buffers=False, backend=backend
        )

    # ------------------------------------------------------------------ #
    # buffer consistency (reference callback.py:87-142)
    # ------------------------------------------------------------------ #
    @staticmethod
    def _ckpt_rb(rb: Any) -> Any:
        """Make the stored buffer self-consistent: the env state is not
        checkpointed, so the last stored step must end its episode. Returns
        the clobbered values for the undo."""
        if hasattr(rb, "flag_last_truncated"):  # DeviceReplayBuffer (HBM ring)
            return rb.flag_last_truncated()
        if isinstance(rb, EnvIndependentReplayBuffer):
            saved: List[Any] = []
            for b in rb.buffer:
                saved.append(b["truncated"][(b._pos - 1) % b.buffer_size, :].copy())
                b["truncated"][(b._pos - 1) % b.buffer_size, :] = 1
            return saved
        if isinstance(rb, ReplayBuffer):
            saved = rb["truncated"][(rb._pos - 1) % rb.buffer_size, :].copy()
            rb["truncated"][(rb._pos - 1) % rb.buffer_size, :] = 1
            return saved
        if isinstance(rb, EpisodeBuffer):
            saved = rb._open_episodes
            rb._open_episodes = [[] for _ in range(rb.n_envs)]
            return saved
        return None

    @staticmethod
    def _experiment_consistent_rb(rb: Any, saved: Any) -> None:
        """Undo :meth:`_ckpt_rb` so the live run continues unchanged."""
        if hasattr(rb, "restore_last_truncated"):  # DeviceReplayBuffer
            rb.restore_last_truncated(saved)
        elif isinstance(rb, EnvIndependentReplayBuffer):
            for b, s in zip(rb.buffer, saved):
                b["truncated"][(b._pos - 1) % b.buffer_size, :] = s
        elif isinstance(rb, ReplayBuffer):
            rb["truncated"][(rb._pos - 1) % rb.buffer_size, :] = saved
        elif isinstance(rb, EpisodeBuffer):
            rb._open_episodes = saved

    def _prune(self, ckpt_dir: str) -> None:
        if not os.path.isdir(ckpt_dir):
            return
        entries = sorted(
            (e for e in os.listdir(ckpt_dir) if not e.startswith(".")),
            key=lambda e: os.path.getmtime(os.path.join(ckpt_dir, e)),
        )
        for stale in entries[: -self.keep_last] if len(entries) > self.keep_last else []:
            path = os.path.join(ckpt_dir, stale)
            shutil.rmtree(path) if os.path.isdir(path) else os.remove(path)
