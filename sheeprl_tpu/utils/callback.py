"""Checkpoint callback (reference: sheeprl/utils/callback.py:14-148).

Invoked by algorithms through ``fabric.call("on_checkpoint_coupled", ...)``;
delegates serialization to ``sheeprl_tpu.core.checkpoint`` (orbax) and prunes
old checkpoints with ``keep_last``.
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, Optional


class CheckpointCallback:
    def __init__(self, keep_last: Optional[int] = None) -> None:
        self.keep_last = keep_last

    def on_checkpoint_coupled(
        self,
        fabric: Any,
        ckpt_path: str,
        state: Dict[str, Any],
        replay_buffer: Any = None,
    ) -> None:
        if replay_buffer is not None:
            state = {**state, "rb": replay_buffer}
        fabric.save(ckpt_path, state)
        if self.keep_last:
            self._prune(os.path.dirname(ckpt_path))

    # Decoupled topologies save from the player with trainer-provided state
    # (reference callback.py:58-78).
    def on_checkpoint_player(self, fabric: Any, ckpt_path: str, state: Dict[str, Any], replay_buffer: Any = None) -> None:
        self.on_checkpoint_coupled(fabric, ckpt_path, state, replay_buffer)

    def _prune(self, ckpt_dir: str) -> None:
        if not os.path.isdir(ckpt_dir):
            return
        entries = sorted(
            (e for e in os.listdir(ckpt_dir) if not e.startswith(".")),
            key=lambda e: os.path.getmtime(os.path.join(ckpt_dir, e)),
        )
        for stale in entries[: -self.keep_last] if len(entries) > self.keep_last else []:
            path = os.path.join(ckpt_dir, stale)
            shutil.rmtree(path) if os.path.isdir(path) else os.remove(path)
