"""Host-side metric aggregation (reference: sheeprl/utils/metric.py:17-195).

torchmetrics is replaced with tiny pure-Python accumulators — metric state
lives on the host (device values are pulled with ``float()`` at update time,
which also acts as the block-until-ready sync point at log boundaries).
Cross-replica reduction of *device* metrics is unnecessary here: jitted train
steps return already-psum'd scalars (the XLA-native counterpart of
``sync_on_compute``).
"""

from __future__ import annotations

import warnings
from math import isnan
from typing import Any, Dict, Iterator, Optional

import numpy as np


class MetricAggregatorException(Exception):
    pass


class Metric:
    """Minimal accumulator interface: update / compute / reset."""

    def update(self, value: Any) -> None:
        raise NotImplementedError

    def compute(self) -> float:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError


class MeanMetric(Metric):
    def __init__(self) -> None:
        self._sum = 0.0
        self._count = 0

    def update(self, value: Any) -> None:
        v = float(value)
        self._sum += v
        self._count += 1

    def compute(self) -> float:
        return self._sum / self._count if self._count else float("nan")

    def reset(self) -> None:
        self._sum, self._count = 0.0, 0


class SumMetric(Metric):
    def __init__(self) -> None:
        self._sum = 0.0
        self._any = False

    def update(self, value: Any) -> None:
        self._sum += float(value)
        self._any = True

    def compute(self) -> float:
        return self._sum if self._any else float("nan")

    def reset(self) -> None:
        self._sum, self._any = 0.0, False


class LastValueMetric(Metric):
    def __init__(self) -> None:
        self._value = float("nan")

    def update(self, value: Any) -> None:
        self._value = float(value)

    def compute(self) -> float:
        return self._value

    def reset(self) -> None:
        self._value = float("nan")


class MaxMetric(Metric):
    def __init__(self) -> None:
        self._value = float("nan")

    def update(self, value: Any) -> None:
        v = float(value)
        self._value = v if isnan(self._value) else max(self._value, v)

    def compute(self) -> float:
        return self._value

    def reset(self) -> None:
        self._value = float("nan")


_METRIC_TYPES = {
    "mean": MeanMetric,
    "sum": SumMetric,
    "last": LastValueMetric,
    "max": MaxMetric,
}


def make_metric(spec: Any) -> Metric:
    """Build a metric from a name ("mean"), a class, or a ``_target_`` node
    (the reference instantiates torchmetrics via hydra, configs/metric)."""
    if isinstance(spec, Metric):
        return spec
    if isinstance(spec, type) and issubclass(spec, Metric):
        return spec()
    if isinstance(spec, dict) and "_target_" in spec:
        name = spec["_target_"].rsplit(".", 1)[-1].replace("Metric", "").lower()
        return _METRIC_TYPES[name]()
    if isinstance(spec, str):
        key = spec.rsplit(".", 1)[-1].replace("Metric", "").lower()
        if key in _METRIC_TYPES:
            return _METRIC_TYPES[key]()
    raise ValueError(f"unknown metric spec {spec!r}; available: {sorted(_METRIC_TYPES)}")


class MetricAggregator:
    """Keyed metric registry with class-level disable and NaN-dropping
    compute (reference metric.py:17-143)."""

    disabled: bool = False

    def __init__(self, metrics: Optional[Dict[str, Any]] = None, raise_on_missing: bool = False) -> None:
        self.metrics: Dict[str, Metric] = {}
        if metrics:
            for k, v in metrics.items():
                self.metrics[k] = make_metric(v)
        self._raise_on_missing = raise_on_missing

    def __iter__(self) -> Iterator[str]:
        return iter(self.metrics.keys())

    def _missing(self, name: str, action: str) -> None:
        if self._raise_on_missing:
            raise MetricAggregatorException(f"Metric {name} does not exist")
        warnings.warn(f"The key '{name}' is missing from the metric aggregator. Nothing will be {action}.")

    def add(self, name: str, metric: Any) -> None:
        if self.disabled:
            return
        if name in self.metrics:
            if self._raise_on_missing:
                raise MetricAggregatorException(f"Metric {name} already exists")
            warnings.warn(f"The key '{name}' is already in the metric aggregator. Nothing will be added.")
            return
        self.metrics[name] = make_metric(metric)

    def update(self, name: str, value: Any) -> None:
        if self.disabled:
            return
        if name not in self.metrics:
            self._missing(name, "added")
            return
        v = np.asarray(value)
        if v.ndim == 0:
            self.metrics[name].update(v)
        else:
            for x in v.ravel():
                self.metrics[name].update(x)

    def pop(self, name: str) -> None:
        if self.disabled:
            return
        if name not in self.metrics:
            self._missing(name, "popped")
        self.metrics.pop(name, None)

    def reset(self) -> None:
        if self.disabled:
            return
        for m in self.metrics.values():
            m.reset()

    def compute(self) -> Dict[str, float]:
        """Reduce all metrics, dropping NaN (empty) entries
        (reference metric.py:110-143)."""
        if self.disabled:
            return {}
        out = {}
        for k, m in self.metrics.items():
            v = m.compute()
            if not isnan(v):
                out[k] = v
        return out
