"""Optional XLA profiler hook (SURVEY.md §5 tracing: "same wall-clock timers
plus optional ``jax.profiler.trace`` hooks").

The reference has no torch-profiler integration; on TPU the XLA trace is the
native tool — it records HLO timelines, per-op device time, and HBM traffic
viewable in TensorBoard's profile plugin or Perfetto.  Enabled via config:

    metric.profiler.enabled=True [metric.profiler.trace_dir=...]

and wrapped around the whole training entrypoint by the CLI, so one run
yields one trace directory next to the run's logs.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Iterator, Mapping, Optional


@contextmanager
def maybe_profile(cfg: Mapping[str, Any], log_dir: Optional[str] = None) -> Iterator[Optional[str]]:
    """Start a ``jax.profiler`` trace when ``metric.profiler.enabled`` is set;
    no-op (yields None) otherwise. Only process 0 traces — each host tracing
    its own devices would do, but one trace is what the tooling expects."""
    prof_cfg = (cfg.get("metric") or {}).get("profiler") or {}
    enabled = bool(prof_cfg.get("enabled", False))
    if not enabled:
        yield None
        return

    import jax

    if jax.process_index() != 0:
        yield None
        return
    trace_dir = prof_cfg.get("trace_dir") or os.path.join(log_dir or ".", "profile")
    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)
    try:
        yield trace_dir
    finally:
        jax.profiler.stop_trace()
