"""Optional XLA profiler hook (SURVEY.md §5 tracing: "same wall-clock timers
plus optional ``jax.profiler.trace`` hooks").

The reference has no torch-profiler integration; on TPU the XLA trace is the
native tool — it records HLO timelines, per-op device time, and HBM traffic
viewable in TensorBoard's profile plugin or Perfetto.  Enabled via config:

    metric.profiler.enabled=True [metric.profiler.trace_dir=...]

and wrapped around the whole training entrypoint by the CLI, so one run
yields one trace directory next to the run's logs.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Any, Iterator, Mapping, Optional


@contextmanager
def maybe_profile(cfg: Mapping[str, Any], log_dir: Optional[str] = None) -> Iterator[Optional[str]]:
    """Start a ``jax.profiler`` trace when ``metric.profiler.enabled`` is set;
    no-op (yields None) otherwise. Only process 0 traces — each host tracing
    its own devices would do, but one trace is what the tooling expects."""
    prof_cfg = (cfg.get("metric") or {}).get("profiler") or {}
    enabled = bool(prof_cfg.get("enabled", False))
    if not enabled:
        yield None
        return

    import jax

    if jax.process_index() != 0:
        yield None
        return
    trace_dir = prof_cfg.get("trace_dir") or os.path.join(log_dir or ".", "profile")
    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)
    try:
        yield trace_dir
    finally:
        jax.profiler.stop_trace()


# bf16 peak of known chips, for MFU claims (jax device_kind -> FLOP/s).
# Unknown chips get no MFU claim, only raw FLOPs.
PEAK_BF16_FLOPS = {"TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5p": 459e12}


def tiny_op_rtt_seconds() -> float:
    """Best-of-5 dispatch + materializing-fetch round trip of a tiny jitted
    op — the link-health probe for remote-attached chips (a materializing
    fetch is the only real sync on the axon client)."""
    import time

    import jax
    import numpy as np

    f = jax.jit(lambda x: x + 1)
    x = jax.device_put(np.ones((8, 8), np.float32))
    np.asarray(f(x))  # compile + warm
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(f(x))
        rtts.append(time.perf_counter() - t0)
    return min(rtts)


def compiled_flops(jitted_fn: Any, *args: Any) -> Optional[float]:
    """FLOPs of ONE invocation of ``jitted_fn`` at the shapes of ``args``,
    read from XLA's cost analysis of an AOT compile built from
    ``ShapeDtypeStruct``s — no data moves, but one extra compile is paid, so
    callers run this outside any measured window. The number feeds the MFU
    computation (``bench.py``): flops x steps / seconds / chip peak."""
    import jax

    def as_shape(x: Any) -> Any:
        return jax.ShapeDtypeStruct(x.shape, x.dtype) if hasattr(x, "shape") and hasattr(x, "dtype") else x

    try:
        compiled = jitted_fn.lower(*jax.tree.map(as_shape, args)).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        flops = float(analysis.get("flops", 0.0))
        return flops or None
    except Exception:
        return None
