"""Algorithm / evaluation registries (reference: sheeprl/utils/registry.py:1-108).

Algorithms self-register at import time through decorators; the CLI looks up the
entrypoint by ``cfg.algo.name``. ``decoupled=True`` marks player/trainer
topologies that manage their own process roles.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

# {module_name: [{"name": algo_name, "entrypoint": fn_name, "decoupled": bool}]}
algorithm_registry: Dict[str, List[Dict[str, Any]]] = {}
evaluation_registry: Dict[str, List[Dict[str, Any]]] = {}


def _algo_name(module: str) -> str:
    # Algorithms live at sheeprl_tpu.algos.<name>.<file>; the registered name is
    # the module file's name (so ppo/ppo.py -> "ppo", ppo/ppo_decoupled.py ->
    # "ppo_decoupled"), matching the reference registry contract.
    return module.split(".")[-1]


def _register(registry: Dict[str, List[Dict[str, Any]]], fn: Callable, decoupled: bool = False) -> Callable:
    module = fn.__module__
    entry = {"name": _algo_name(module), "entrypoint": fn.__name__, "decoupled": decoupled}
    registered = registry.setdefault(module, [])
    if any(e["name"] == entry["name"] and e["entrypoint"] == entry["entrypoint"] for e in registered):
        raise ValueError(f"{entry['name']}.{entry['entrypoint']} already registered")
    registered.append(entry)
    return fn


def register_algorithm(decoupled: bool = False) -> Callable:
    def wrap(fn: Callable) -> Callable:
        return _register(algorithm_registry, fn, decoupled)

    return wrap


def register_evaluation(algorithms: str | List[str]) -> Callable:
    algos = [algorithms] if isinstance(algorithms, str) else list(algorithms)

    def wrap(fn: Callable) -> Callable:
        module = fn.__module__
        registered = evaluation_registry.setdefault(module, [])
        for name in algos:
            # cross-check: an evaluation must refer to a registered algorithm
            known = {e["name"] for entries in algorithm_registry.values() for e in entries}
            if name not in known:
                raise ValueError(
                    f"cannot register evaluation for unknown algorithm {name!r}; "
                    f"known algorithms: {sorted(known)}"
                )
            registered.append({"name": name, "entrypoint": fn.__name__})
        return fn

    return wrap


def find_algorithm(algo_name: str) -> Dict[str, Any]:
    for module, entries in algorithm_registry.items():
        for entry in entries:
            if entry["name"] == algo_name:
                return {"module": module, **entry}
    known = sorted({e["name"] for entries in algorithm_registry.values() for e in entries})
    raise ValueError(f"unknown algorithm {algo_name!r}; registered algorithms: {known}")


def find_evaluation(algo_name: str) -> Dict[str, Any]:
    for module, entries in evaluation_registry.items():
        for entry in entries:
            if entry["name"] == algo_name:
                return {"module": module, **entry}
    known = sorted({e["name"] for entries in evaluation_registry.values() for e in entries})
    raise ValueError(f"no registered evaluation for {algo_name!r}; available: {known}")
