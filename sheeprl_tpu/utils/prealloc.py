"""Preallocated rollout storage for the on-policy host loops.

The reference collection loop appends per-step numpy arrays to Python lists
and ``np.stack``s them at the end of the window — for small classic-control
obs the stack (one more full copy plus T*keys list traversals) is a visible
slice of the ``benchmarks/ppo_floor.py`` bookkeeping gap.  ``RolloutStore``
replaces it with arrays of shape ``[T, ...]`` allocated once on the first
window and written in place (``buf[k][t] = v`` — the write IS the copy, so
callers that used to ``.copy()`` values before appending can stop).

``slots=2`` double-buffers: with ``algo.overlap_collection`` the async train
dispatch may still be reading update N's arrays (jax can alias host numpy
zero-copy on the CPU backend) while the loop writes update N+1, so successive
updates alternate buffers.
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np


class RolloutBuffer:
    """One window's storage: per-key ``[length, ...]`` arrays, lazily
    allocated from the first written value's shape/dtype, then reused."""

    def __init__(self, length: int):
        self._length = int(length)
        self._arrays: Dict[str, np.ndarray] = {}

    def put(self, t: int, values: Mapping[str, np.ndarray]) -> None:
        """Write one step's values at index ``t`` (in-place copy)."""
        for k, v in values.items():
            arr = self._arrays.get(k)
            if arr is None:
                v = np.asarray(v)
                arr = np.empty((self._length,) + v.shape, dtype=v.dtype)
                self._arrays[k] = arr
            arr[t] = v

    def arrays(self) -> Dict[str, np.ndarray]:
        """The ``[T, ...]`` arrays (the live buffers, not copies)."""
        return dict(self._arrays)


class RolloutStore:
    """A rotating set of :class:`RolloutBuffer` slots, one window each."""

    def __init__(self, length: int, slots: int = 1):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self._buffers = [RolloutBuffer(length) for _ in range(slots)]

    def begin(self, update: int) -> RolloutBuffer:
        """The buffer for this update's window (rotates across slots)."""
        return self._buffers[update % len(self._buffers)]
