"""Worker lifecycle: spawn, handshake, supervised waits, restart, masking.

The supervisor is the parent-side half of the pool's fault tolerance:

- **crash detection** — a dead process or broken pipe while waiting for a
  reply raises :class:`WorkerDied`;
- **hang detection** — replies carry a deadline that *extends while the
  worker heartbeats* (workers beat after every env step inside a batch, so a
  worker legitimately stepping 8 slow envs is distinguished from one wedged
  inside a single ``env.step``); a stale heartbeat past
  ``rollout.step_timeout_s`` raises :class:`WorkerTimeout`;
- **restart** — kill, exponential backoff (``backoff_base_s * 2**(n-1)``
  capped at ``backoff_max_s``), respawn, re-attach shm, reset the recreated
  envs. Restarts are budgeted by ``rollout.max_restarts`` *per worker*;
- **masking** — a worker over budget is torn down for good and its slots are
  reported to the pool, which serves zeros for them instead of hanging the
  run.

Every ``Process.start()`` happens under :func:`_spawn_environ`, which applies
:func:`~sheeprl_tpu.rollout.worker.sanitize_worker_environ` to the *parent's*
environ for the duration of the fork/spawn — the child snapshots its environ
at start, and its very first imports (this package → possibly jax) happen
before ``worker_main`` can sanitize anything itself.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from sheeprl_tpu.rollout.shm import ShmSpec
from sheeprl_tpu.rollout.worker import sanitize_worker_environ, worker_main


class RestartBudget:
    """Restart budget with a healthy-window refund.

    A plain ``max_restarts`` cap conflates two failure shapes: a worker that
    crash-loops (restarts do not help — mask it) and a long-lived worker that
    accumulates rare, uncorrelated faults over hours (restarts always help —
    but the cap eventually masks it exactly when graceful degradation matters
    most). The refund separates them: every ``refund_after_s`` seconds WITHOUT
    a fault hands one restart back, so only faults *clustered* inside a
    healthy window can exhaust the budget. ``refund_after_s=None`` disables
    the refund (the original fixed-cap behaviour).

    Not thread-safe by itself — callers serialize (the rollout pool charges
    from the stepping thread, the serve supervisor from its monitor thread).
    """

    def __init__(self, max_restarts: int, refund_after_s: Optional[float] = None, clock=time.monotonic) -> None:
        self.max_restarts = int(max_restarts)
        self.refund_after_s = float(refund_after_s) if refund_after_s else None
        self._clock = clock
        self.used = 0
        self._last_fault_t: Optional[float] = None

    def _refund(self) -> None:
        if self.refund_after_s is None or self.used <= 0 or self._last_fault_t is None:
            return
        windows = int((self._clock() - self._last_fault_t) / self.refund_after_s)
        if windows > 0:
            self.used = max(0, self.used - windows)
            # keep the remainder of the current window so two refunds cannot
            # ride one healthy stretch
            self._last_fault_t += windows * self.refund_after_s
            if self.used == 0:
                self._last_fault_t = None

    @property
    def exhausted(self) -> bool:
        """True once the budget cannot absorb another fault — the caller
        masks instead of restarting."""
        self._refund()
        return self.used >= self.max_restarts

    def charge(self) -> int:
        """Record one fault/restart; returns the post-refund charge count
        (1-based within the current fault cluster — feeds the backoff)."""
        self._refund()
        self.used += 1
        self._last_fault_t = self._clock()
        return self.used


class WorkerDied(RuntimeError):
    def __init__(self, worker: int, detail: str = "") -> None:
        super().__init__(f"env worker {worker} died{': ' + detail if detail else ''}")
        self.worker = worker
        self.detail = detail


class WorkerTimeout(RuntimeError):
    def __init__(self, worker: int, waited_s: float) -> None:
        super().__init__(f"env worker {worker} exceeded the step timeout ({waited_s:.1f}s without progress)")
        self.worker = worker
        self.waited_s = waited_s


class WorkerHandle:
    """One worker process and its bookkeeping."""

    def __init__(self, index: int, slots: Sequence[int], thunk_blob: bytes) -> None:
        self.index = index
        self.slots = list(slots)
        self.thunk_blob = thunk_blob
        self.proc = None
        self.conn = None
        self.restarts = 0  # lifetime total (telemetry); the maskable budget is `budget`
        self.budget: Optional[RestartBudget] = None  # attached by Supervisor.launch
        self.masked = False
        self.video_slots: List[int] = []

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.is_alive()


@contextlib.contextmanager
def _spawn_environ():
    """Sanitized-environ window around ``Process.start()`` (see module doc)."""
    from sheeprl_tpu.rollout.worker import _COORDINATOR_VARS

    touched = ("JAX_PLATFORMS", "SHEEPRL_TPU_ENV_WORKER", *_COORDINATOR_VARS)
    saved: Dict[str, Optional[str]] = {key: os.environ.get(key) for key in touched}
    try:
        sanitize_worker_environ()
        yield
    finally:
        for key, value in saved.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value


class Supervisor:
    def __init__(self, config, num_workers: int, on_restart=None, on_mask=None) -> None:
        import multiprocessing as mp

        self.config = config
        self._ctx = mp.get_context(config.start_method)
        # lock-free doubles: one heartbeat timestamp per worker, written by
        # the worker after every env step and read by the waiting parent
        self.heartbeats = self._ctx.Array("d", num_workers, lock=False)
        self.on_restart = on_restart  # callback(worker, reason, restarts)
        self.on_mask = on_mask  # callback(worker, slots, reason)
        self._shm_specs: Optional[Dict[str, ShmSpec]] = None

    # ------------------------------------------------------------- lifecycle
    def launch(self, handle: WorkerHandle) -> None:
        """Start ``handle``'s process (no handshake — boots overlap when the
        pool launches every worker before waiting on any of them)."""
        if handle.budget is None:
            handle.budget = RestartBudget(
                self.config.max_restarts, getattr(self.config, "restart_refund_s", None)
            )
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        proc = self._ctx.Process(
            target=worker_main,
            args=(child_conn, self.heartbeats, handle.index, handle.slots, handle.thunk_blob),
            name=f"envpool-worker-{handle.index}",
            daemon=True,
        )
        with _spawn_environ():
            proc.start()
        child_conn.close()
        handle.proc = proc
        handle.conn = parent_conn
        self.heartbeats[handle.index] = time.time()

    def handshake(self, handle: WorkerHandle) -> Tuple[Any, Any]:
        """Wait for the ready message; returns ``(observation_space,
        action_space)`` as reported by the worker's env 0."""
        reply = self.wait_reply(handle, timeout=self.config.spawn_timeout_s)
        if reply[0] != "ready":
            raise WorkerDied(handle.index, f"bad handshake: {reply[0]!r}")
        _, obs_space, act_space, video_slots = reply
        handle.video_slots = list(video_slots)
        return obs_space, act_space

    def spawn(self, handle: WorkerHandle) -> Tuple[Any, Any]:
        self.launch(handle)
        return self.handshake(handle)

    def attach(self, handle: WorkerHandle, specs: Dict[str, ShmSpec]) -> None:
        self._shm_specs = specs
        handle.conn.send(("attach", specs))
        reply = self.wait_reply(handle, timeout=self.config.spawn_timeout_s)
        if reply[0] != "attached":
            raise WorkerDied(handle.index, f"bad attach reply: {reply[0]!r}")

    def kill(self, handle: WorkerHandle) -> None:
        if handle.conn is not None:
            try:
                handle.conn.close()
            except Exception:
                pass
            handle.conn = None
        if handle.proc is not None:
            if handle.proc.is_alive():
                handle.proc.terminate()
                handle.proc.join(timeout=2.0)
                if handle.proc.is_alive():
                    handle.proc.kill()
                    handle.proc.join(timeout=2.0)
            handle.proc = None

    def shutdown(self, handle: WorkerHandle, timeout: float = 2.0) -> None:
        """Graceful close; falls back to kill."""
        if handle.conn is not None and handle.alive:
            try:
                handle.conn.send(("close",))
                self.wait_reply(handle, timeout=timeout)
            except Exception:
                pass
        self.kill(handle)

    # ----------------------------------------------------------------- waits
    def wait_reply(
        self,
        handle: WorkerHandle,
        timeout: Optional[float] = None,
        idle: Optional[Callable[[], None]] = None,
    ) -> Tuple[Any, ...]:
        """Block until ``handle`` replies. The deadline is heartbeat-aware:
        it extends to ``last_heartbeat + timeout`` while the worker shows
        progress, so per-batch work scales with envs-per-worker without a
        matching timeout bump. ``idle`` runs every poll cycle — the TCP
        actor-learner transport uses it to keep servicing handshakes while
        the supervisor blocks here."""
        timeout = self.config.step_timeout_s if timeout is None else float(timeout)
        grace = self.config.heartbeat_grace
        start = time.time()
        conn = handle.conn
        while True:
            if idle is not None:
                idle()
            if conn.poll(0.02):
                try:
                    reply = conn.recv()
                except (EOFError, OSError) as e:
                    raise WorkerDied(handle.index, repr(e))
                if reply[0] == "error":
                    raise WorkerDied(handle.index, reply[1])
                return reply
            if not handle.alive:
                # drain any message written right before death
                if conn.poll(0):
                    continue
                raise WorkerDied(handle.index, f"exitcode={getattr(handle.proc, 'exitcode', None)}")
            now = time.time()
            # `timeout` is the budget with no heartbeats at all (a boot gets
            # spawn_timeout_s even though nothing beats yet); each heartbeat
            # then pushes the deadline out by `grace`.
            deadline = max(start + timeout, self.heartbeats[handle.index] + grace)
            if now > deadline:
                raise WorkerTimeout(handle.index, now - start)

    # --------------------------------------------------------------- restart
    def backoff_s(self, restarts: int) -> float:
        return min(self.config.backoff_max_s, self.config.backoff_base_s * (2 ** max(0, restarts - 1)))

    def restart(self, handle: WorkerHandle, reason: str, reset_seeds: Sequence[Optional[int]]) -> List[Tuple[int, dict]]:
        """Kill + backoff + respawn + re-attach + reset ``handle``'s envs.

        Returns the reset infos ``[(global_slot, info)]`` — the pool uses the
        freshly-reset observations (already in shm) to complete the in-flight
        step with ``truncated=True``. Raises ``WorkerDied``/``WorkerTimeout``
        if the replacement itself fails (the caller loops against the retry
        budget)."""
        self.kill(handle)
        handle.restarts += 1
        # backoff scales with the budget's post-refund charge count, not the
        # lifetime total: a fault after a long healthy stretch restarts fast
        # again instead of inheriting hours-old backoff escalation
        charge = handle.budget.charge() if handle.budget is not None else handle.restarts
        if self.on_restart is not None:
            self.on_restart(handle.index, reason, handle.restarts)
        time.sleep(self.backoff_s(charge))
        self.spawn(handle)
        if self._shm_specs is None:
            raise RuntimeError("restart before shared-memory allocation")
        self.attach(handle, self._shm_specs)
        handle.conn.send(("reset", list(reset_seeds), None))
        reply = self.wait_reply(handle, timeout=self.config.spawn_timeout_s)
        if reply[0] != "reset_done":
            raise WorkerDied(handle.index, f"bad restart reset reply: {reply[0]!r}")
        return reply[1]

    def mask(self, handle: WorkerHandle, reason: str) -> None:
        self.kill(handle)
        handle.masked = True
        if self.on_mask is not None:
            self.on_mask(handle.index, handle.slots, reason)
