"""Pool sizing / supervision knobs, parsed once from the composed config.

Everything lives under the top-level ``rollout`` node (``configs/config.yaml``)
so CLI overrides read ``rollout.step_timeout_s=5``; the *backend selection*
itself is ``env.backend`` (``sync | async | pool``) because it is a property of
the env plane, not of the pool.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, List, Mapping, Optional

from sheeprl_tpu.rollout.fault_injection import FaultSpec, parse_fault_config


@dataclass
class PoolConfig:
    """Supervision and sizing parameters for :class:`~sheeprl_tpu.rollout.pool.EnvPool`.

    ``num_workers=None`` means one worker per env capped at the host's CPU
    count — EnvPool-style batched stepping only pays off once envs outnumber
    cores, so by default every env gets its own failure domain.
    """

    num_workers: Optional[int] = None
    step_timeout_s: float = 60.0
    spawn_timeout_s: float = 120.0
    heartbeat_grace_s: Optional[float] = None  # default: step_timeout_s
    max_restarts: int = 3
    restart_refund_s: Optional[float] = 600.0  # healthy seconds refunding one restart; None disables
    backoff_base_s: float = 0.5
    backoff_max_s: float = 10.0
    copy_obs: bool = True
    start_method: str = "spawn"
    faults: List[FaultSpec] = field(default_factory=list)

    def resolve_num_workers(self, num_envs: int) -> int:
        if self.num_workers is not None:
            n = int(self.num_workers)
            if n < 1:
                raise ValueError(f"rollout.num_workers must be >= 1, got {n}")
            return min(n, num_envs)
        return max(1, min(num_envs, os.cpu_count() or 1))

    @property
    def heartbeat_grace(self) -> float:
        return self.step_timeout_s if self.heartbeat_grace_s is None else float(self.heartbeat_grace_s)


def pool_config_from_cfg(cfg: Mapping[str, Any]) -> PoolConfig:
    """Build a :class:`PoolConfig` from the composed run config's ``rollout``
    node (absent node → all defaults, faults disabled)."""
    node = _get(cfg, "rollout") or {}
    fault_node = _get(node, "fault_injection") or {}
    faults: List[FaultSpec] = []
    if bool(_get(fault_node, "enabled", False)):
        faults = parse_fault_config(_get(fault_node, "faults") or [])
    num_workers = _get(node, "num_workers", None)
    return PoolConfig(
        num_workers=int(num_workers) if num_workers is not None else None,
        step_timeout_s=float(_get(node, "step_timeout_s", 60.0)),
        spawn_timeout_s=float(_get(node, "spawn_timeout_s", 120.0)),
        heartbeat_grace_s=_get(node, "heartbeat_grace_s", None),
        max_restarts=int(_get(node, "max_restarts", 3)),
        restart_refund_s=(
            float(_get(node, "restart_refund_s", 600.0))
            if _get(node, "restart_refund_s", 600.0) is not None
            else None
        ),
        backoff_base_s=float(_get(node, "backoff_base_s", 0.5)),
        backoff_max_s=float(_get(node, "backoff_max_s", 10.0)),
        copy_obs=bool(_get(node, "copy_obs", True)),
        start_method=str(_get(node, "start_method", "spawn")),
        faults=faults,
    )


def _get(node: Any, key: str, default: Any = None) -> Any:
    if node is None:
        return default
    if hasattr(node, "get"):
        return node.get(key, default)
    return getattr(node, key, default)
