"""Shared-memory observation buffers for the env-worker pool.

One ``SharedMemory`` block per observation key, laid out ``[num_envs, *obs
shape]``. Workers write their slots in place after every step/reset; the
parent holds full-pool numpy views — reading a step's observations is zero
syscalls and zero copies (``EnvPool`` copies on return only when
``rollout.copy_obs=True``, the gymnasium-compatible default).

The parent owns the blocks (creates and unlinks); workers attach by name and
only ``close()``. Attaching suppresses ``multiprocessing.resource_tracker``
registration — on CPython < 3.13 every attach is (wrongly) registered for
cleanup, so a dying worker would otherwise unlink a segment the parent still
serves (and spawn children share the parent's tracker process, so a worker
*unregistering* after the fact would clobber the parent's own registration).
"""

from __future__ import annotations

from dataclasses import dataclass
from multiprocessing import shared_memory
from typing import Dict, List, Tuple

import gymnasium as gym
import numpy as np


@dataclass
class ShmSpec:
    """Wire-format description of one shared block (std-picklable)."""

    name: str
    shape: Tuple[int, ...]
    dtype: str  # np.dtype string, e.g. "uint8"


def obs_layout(single_observation_space: gym.spaces.Dict, num_envs: int) -> Dict[str, Tuple[Tuple[int, ...], np.dtype]]:
    """Per-key ``(shape, dtype)`` for the pooled buffers. The pool only
    supports ``Dict``-of-``Box`` observation spaces — which is what
    ``make_env`` guarantees (dict-ification is part of its pipeline)."""
    if not isinstance(single_observation_space, gym.spaces.Dict):
        raise TypeError(
            f"EnvPool requires a Dict observation space (make_env guarantees one), "
            f"got {type(single_observation_space).__name__}"
        )
    layout = {}
    for key, space in single_observation_space.spaces.items():
        if not isinstance(space, gym.spaces.Box):
            raise TypeError(
                f"EnvPool shared-memory buffers require Box subspaces; key {key!r} is "
                f"{type(space).__name__} — use env.backend=sync/async for this env"
            )
        layout[key] = ((num_envs, *space.shape), np.dtype(space.dtype))
    return layout


class ShmObsBuffers:
    """Parent-side owner of the per-key shared blocks + full-pool views."""

    def __init__(self, single_observation_space: gym.spaces.Dict, num_envs: int) -> None:
        self.num_envs = int(num_envs)
        self._blocks: Dict[str, shared_memory.SharedMemory] = {}
        self.views: Dict[str, np.ndarray] = {}
        self.specs: Dict[str, ShmSpec] = {}
        for key, (shape, dtype) in obs_layout(single_observation_space, num_envs).items():
            nbytes = max(1, int(np.prod(shape)) * dtype.itemsize)
            block = shared_memory.SharedMemory(create=True, size=nbytes)
            self._blocks[key] = block
            self.views[key] = np.ndarray(shape, dtype=dtype, buffer=block.buf)
            self.views[key][...] = 0
            self.specs[key] = ShmSpec(name=block.name, shape=tuple(shape), dtype=dtype.str)

    def read(self, copy: bool) -> Dict[str, np.ndarray]:
        if copy:
            return {k: v.copy() for k, v in self.views.items()}
        return dict(self.views)

    def zero_slot(self, slot: int) -> None:
        for v in self.views.values():
            v[slot] = 0

    def close(self) -> None:
        # drop the numpy views before closing the mmaps: an exported buffer
        # keeps memoryview references alive and SharedMemory.close() raises
        self.views = {}
        for block in self._blocks.values():
            try:
                block.close()
                block.unlink()
            except FileNotFoundError:
                pass
        self._blocks = {}


class ShmSlotViews:
    """Worker-side attachment: numpy views restricted to this worker's slots."""

    def __init__(self, specs: Dict[str, ShmSpec]) -> None:
        self._blocks: List[shared_memory.SharedMemory] = []
        self._full: Dict[str, np.ndarray] = {}
        for key, spec in specs.items():
            block = _attach_untracked(spec.name)
            self._blocks.append(block)
            self._full[key] = np.ndarray(spec.shape, dtype=np.dtype(spec.dtype), buffer=block.buf)

    def write(self, slot: int, obs: Dict[str, np.ndarray]) -> None:
        for key, view in self._full.items():
            view[slot] = obs[key]

    def close(self) -> None:
        self._full = {}
        for block in self._blocks:
            try:
                block.close()
            except Exception:
                pass
        self._blocks = []


def _attach_untracked(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment without registering it for cleanup.

    CPython < 3.13 registers *every* ``SharedMemory`` instance with the
    resource tracker, attach included (bpo-39959; fixed by ``track=False`` in
    3.13). Briefly no-op ``resource_tracker.register`` instead of
    unregistering afterwards: spawn children share the parent's tracker, so an
    unregister from a worker would erase the parent's own registration and
    turn the parent's later ``unlink()`` into a tracker KeyError.
    """
    try:  # pragma: no cover - tracker layout is a CPython internal
        from multiprocessing import resource_tracker

        original = resource_tracker.register
        resource_tracker.register = lambda *a, **k: None  # type: ignore[assignment]
        try:
            return shared_memory.SharedMemory(name=name)
        finally:
            resource_tracker.register = original  # type: ignore[assignment]
    except Exception:
        return shared_memory.SharedMemory(name=name)
