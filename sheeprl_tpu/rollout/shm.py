"""Shared-memory observation buffers for the env-worker pool.

One ``SharedMemory`` block per observation key, laid out ``[num_envs, *obs
shape]``. Workers write their slots in place after every step/reset; the
parent holds full-pool numpy views — reading a step's observations is zero
syscalls and zero copies (``EnvPool`` copies on return only when
``rollout.copy_obs=True``, the gymnasium-compatible default).

The parent owns the blocks (creates and unlinks); workers attach by name and
only ``close()``. Attaching suppresses ``multiprocessing.resource_tracker``
registration — on CPython < 3.13 every attach is (wrongly) registered for
cleanup, so a dying worker would otherwise unlink a segment the parent still
serves (and spawn children share the parent's tracker process, so a worker
*unregistering* after the fact would clobber the parent's own registration).
"""

from __future__ import annotations

import atexit
import threading
from dataclasses import dataclass
from multiprocessing import shared_memory
from typing import Dict, List, Tuple

import gymnasium as gym
import numpy as np

# ---------------------------------------------------------------------------
# Leak guard: owners register their segments; an atexit sweep unlinks anything
# still registered when the process dies. Without this, a parent that crashes
# between creating the blocks and tearing the pool down leaves named segments
# in /dev/shm for the next run to collide with (and workers that die while
# attaching leave dangling fds). ``close()`` paths unregister first, so the
# sweep only ever fires for segments that would otherwise leak.
# ---------------------------------------------------------------------------

_OWNED_LOCK = threading.Lock()
_OWNED_SEGMENTS: Dict[str, shared_memory.SharedMemory] = {}


def register_owned_segment(block: shared_memory.SharedMemory) -> None:
    """Record ``block`` (created by THIS process) for the atexit leak sweep."""
    with _OWNED_LOCK:
        _OWNED_SEGMENTS[block.name] = block


def unregister_owned_segment(name: str) -> None:
    with _OWNED_LOCK:
        _OWNED_SEGMENTS.pop(name, None)


def sweep_owned_segments() -> int:
    """Unlink every still-registered segment; returns how many were swept.
    Registered atexit, but callable directly (tests, emergency teardown)."""
    with _OWNED_LOCK:
        leaked = list(_OWNED_SEGMENTS.values())
        _OWNED_SEGMENTS.clear()
    for block in leaked:
        try:
            block.close()
        except Exception:
            pass
        try:
            block.unlink()
        except Exception:
            pass
    return len(leaked)


atexit.register(sweep_owned_segments)


@dataclass
class ShmSpec:
    """Wire-format description of one shared block (std-picklable)."""

    name: str
    shape: Tuple[int, ...]
    dtype: str  # np.dtype string, e.g. "uint8"


def obs_layout(single_observation_space: gym.spaces.Dict, num_envs: int) -> Dict[str, Tuple[Tuple[int, ...], np.dtype]]:
    """Per-key ``(shape, dtype)`` for the pooled buffers. The pool only
    supports ``Dict``-of-``Box`` observation spaces — which is what
    ``make_env`` guarantees (dict-ification is part of its pipeline)."""
    if not isinstance(single_observation_space, gym.spaces.Dict):
        raise TypeError(
            f"EnvPool requires a Dict observation space (make_env guarantees one), "
            f"got {type(single_observation_space).__name__}"
        )
    layout = {}
    for key, space in single_observation_space.spaces.items():
        if not isinstance(space, gym.spaces.Box):
            raise TypeError(
                f"EnvPool shared-memory buffers require Box subspaces; key {key!r} is "
                f"{type(space).__name__} — use env.backend=sync/async for this env"
            )
        layout[key] = ((num_envs, *space.shape), np.dtype(space.dtype))
    return layout


class ShmObsBuffers:
    """Parent-side owner of the per-key shared blocks + full-pool views."""

    def __init__(self, single_observation_space: gym.spaces.Dict, num_envs: int) -> None:
        self.num_envs = int(num_envs)
        self._blocks: Dict[str, shared_memory.SharedMemory] = {}
        self.views: Dict[str, np.ndarray] = {}
        self.specs: Dict[str, ShmSpec] = {}
        try:
            for key, (shape, dtype) in obs_layout(single_observation_space, num_envs).items():
                nbytes = max(1, int(np.prod(shape)) * dtype.itemsize)
                block = shared_memory.SharedMemory(create=True, size=nbytes)
                self._blocks[key] = block
                register_owned_segment(block)
                self.views[key] = np.ndarray(shape, dtype=dtype, buffer=block.buf)
                self.views[key][...] = 0
                self.specs[key] = ShmSpec(name=block.name, shape=tuple(shape), dtype=dtype.str)
        except Exception:
            self.close()
            raise

    def read(self, copy: bool) -> Dict[str, np.ndarray]:
        if copy:
            return {k: v.copy() for k, v in self.views.items()}
        return dict(self.views)

    def zero_slot(self, slot: int) -> None:
        for v in self.views.values():
            v[slot] = 0

    def close(self) -> None:
        # drop the numpy views before closing the mmaps: an exported buffer
        # keeps memoryview references alive and SharedMemory.close() raises
        self.views = {}
        for block in self._blocks.values():
            unregister_owned_segment(block.name)
            try:
                block.close()
                block.unlink()
            except FileNotFoundError:
                pass
        self._blocks = {}


class ShmSlotViews:
    """Worker-side attachment: numpy views restricted to this worker's slots."""

    def __init__(self, specs: Dict[str, ShmSpec]) -> None:
        self._blocks: List[shared_memory.SharedMemory] = []
        self._full: Dict[str, np.ndarray] = {}
        try:
            for key, spec in specs.items():
                block = attach_untracked(spec.name)
                self._blocks.append(block)
                self._full[key] = np.ndarray(spec.shape, dtype=np.dtype(spec.dtype), buffer=block.buf)
        except Exception:
            # A half-attached worker (parent died mid-handshake, segment
            # already unlinked) must not leak the blocks it DID map.
            self.close()
            raise

    def write(self, slot: int, obs: Dict[str, np.ndarray]) -> None:
        for key, view in self._full.items():
            view[slot] = obs[key]

    def close(self) -> None:
        self._full = {}
        for block in self._blocks:
            try:
                block.close()
            except Exception:
                pass
        self._blocks = []


def create_untracked(size: int) -> shared_memory.SharedMemory:
    """Create a segment, registered for the atexit leak sweep. Owners that
    tear down cleanly call ``unregister_owned_segment`` + ``unlink``; owners
    that crash get swept."""
    block = shared_memory.SharedMemory(create=True, size=max(1, int(size)))
    register_owned_segment(block)
    return block


def attach_untracked(name: str) -> shared_memory.SharedMemory:
    """Attach to an existing segment without registering it for cleanup.

    CPython < 3.13 registers *every* ``SharedMemory`` instance with the
    resource tracker, attach included (bpo-39959; fixed by ``track=False`` in
    3.13). Briefly no-op ``resource_tracker.register`` instead of
    unregistering afterwards: spawn children share the parent's tracker, so an
    unregister from a worker would erase the parent's own registration and
    turn the parent's later ``unlink()`` into a tracker KeyError.
    """
    try:  # pragma: no cover - tracker layout is a CPython internal
        from multiprocessing import resource_tracker

        original = resource_tracker.register
        resource_tracker.register = lambda *a, **k: None  # type: ignore[assignment]
        try:
            return shared_memory.SharedMemory(name=name)
        finally:
            resource_tracker.register = original  # type: ignore[assignment]
    except Exception:
        return shared_memory.SharedMemory(name=name)


# Backwards-compatible alias (pre-PR-11 internal name).
_attach_untracked = attach_untracked
