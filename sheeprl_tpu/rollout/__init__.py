"""Resilient rollout subsystem (host plane).

A supervised, process-based vector-env pool that is a drop-in replacement for
``gym.vector.SyncVectorEnv`` / ``AsyncVectorEnv`` across the algorithm mains
(selected behind ``env.backend=pool``; see :func:`sheeprl_tpu.envs.factory.
build_vector_env`):

- :class:`~sheeprl_tpu.rollout.pool.EnvPool` — workers step env *slots* in
  batches and write observations directly into preallocated shared-memory
  buffers (zero-copy numpy views on the host player path, one ``device_put``
  per step on the caller side), replicating gymnasium's ``SAME_STEP``
  autoreset semantics bit-for-bit.
- :class:`~sheeprl_tpu.rollout.supervisor.Supervisor` — per-worker
  heartbeats, step timeouts and crash detection; dead/hung workers are
  restarted with exponential backoff and capped retries (the in-flight
  episode is truncated, the in-flight reset replayed), and a slot whose
  worker exhausts its retries is masked dead instead of hanging the run.
- :mod:`~sheeprl_tpu.rollout.fault_injection` — a deterministic
  crash/hang/slow schedule (``rollout.fault_injection.*``) so the recovery
  paths above are exercised in CI, not discovered in production.

Telemetry: when ``metric.telemetry.enabled=True`` the pool emits
``rollout/env_step`` / ``rollout/env_reset`` spans, ``worker_restart`` and
``masked_slot`` events, and feeds the heartbeat's env step-latency p50/p95 and
queue-wait fields (``bench.py --env-stats`` summarizes the stream).

Workers never touch the TPU: the bootstrap pins ``JAX_PLATFORMS=cpu`` and
strips the distributed-coordinator environment before the child imports jax.
"""

from sheeprl_tpu.rollout.config import PoolConfig, pool_config_from_cfg
from sheeprl_tpu.rollout.fault_injection import FaultSchedule, FaultSpec, parse_fault_config
from sheeprl_tpu.rollout.pool import EnvPool
from sheeprl_tpu.rollout.supervisor import RestartBudget, WorkerDied, WorkerTimeout

__all__ = [
    "EnvPool",
    "FaultSchedule",
    "FaultSpec",
    "PoolConfig",
    "RestartBudget",
    "WorkerDied",
    "WorkerTimeout",
    "parse_fault_config",
    "pool_config_from_cfg",
]
