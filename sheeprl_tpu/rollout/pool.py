"""``EnvPool`` — supervised shared-memory vector env, ``gym.vector`` drop-in.

The surface the algorithm mains use is identical to gymnasium's vector envs
under ``AutoresetMode.SAME_STEP``: ``reset(seed=...)``, ``step(actions)``,
``single_observation_space`` / ``single_action_space``, batched
``observation_space`` / ``action_space``, ``close()`` — including the
``final_obs`` / ``final_info`` info batching contract (``_add_info`` with
``_key`` masks). With faults disabled and the same seeds, trajectories are
bit-identical to ``SyncVectorEnv`` (asserted by
``tests/test_rollout/test_pool_parity.py``).

What is different is underneath: env slots are partitioned over worker
processes, observations travel through preallocated shared memory instead of
pipes, and a :class:`~sheeprl_tpu.rollout.supervisor.Supervisor` keeps the
run alive through worker crashes and hangs:

- a failed worker is restarted with exponential backoff; its recreated envs
  are reset (deterministically reseeded) and the in-flight step completes
  with ``truncated=True`` for its slots, the reset observation standing in
  for ``final_obs`` so truncation bootstraps stay well-formed;
- a worker that exhausts ``rollout.max_restarts`` is *masked*: its slots
  report one final ``terminated=True`` and then zeros/False forever — the
  run degrades instead of deadlocking, and the ``masked_slot`` telemetry
  counter makes the degradation visible.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Union

import gymnasium as gym
import numpy as np
from gymnasium.vector.utils import batch_space, iterate

from sheeprl_tpu.rollout.config import PoolConfig
from sheeprl_tpu.rollout.fault_injection import FaultSchedule
from sheeprl_tpu.rollout.shm import ShmObsBuffers
from sheeprl_tpu.rollout.supervisor import Supervisor, WorkerDied, WorkerHandle, WorkerTimeout


class _InfoBatcher:
    """Reuses gymnasium's ``VectorEnv._add_info`` (``_key`` masks, recursive
    dicts, object-array ``final_obs``) without inheriting the whole class."""

    _add_info = gym.vector.VectorEnv._add_info

    def __init__(self, num_envs: int) -> None:
        self.num_envs = num_envs


class EnvPool:
    """Process-pool vector env over shared-memory observation buffers."""

    metadata: Dict[str, Any] = {"autoreset_mode": gym.vector.AutoresetMode.SAME_STEP}
    render_mode = None

    def __init__(
        self,
        env_fns: Sequence[Any],
        *,
        config: Optional[PoolConfig] = None,
        seed_base: int = 0,
    ) -> None:
        import cloudpickle

        if len(env_fns) == 0:
            raise ValueError("EnvPool needs at least one env_fn")
        self.config = config or PoolConfig()
        self.num_envs = len(env_fns)
        self._seed_base = int(seed_base)
        self.closed = False

        num_workers = self.config.resolve_num_workers(self.num_envs)
        slot_parts = np.array_split(np.arange(self.num_envs), num_workers)
        self._handles: List[WorkerHandle] = [
            WorkerHandle(w, [int(s) for s in part], cloudpickle.dumps([env_fns[s] for s in part]))
            for w, part in enumerate(slot_parts)
        ]
        self._slot_to_worker = {s: h.index for h in self._handles for s in h.slots}
        self._sup = Supervisor(
            self.config,
            num_workers,
            on_restart=self._on_restart,
            on_mask=self._on_mask,
        )

        # boot all workers concurrently: launch every process first, then run
        # the ready handshakes (imports dominate startup; they overlap)
        for handle in self._handles:
            self._sup.launch(handle)
        spaces = [self._sup.handshake(handle) for handle in self._handles]
        self.single_observation_space, self.single_action_space = spaces[0]
        for w, (obs_sp, act_sp) in enumerate(spaces[1:], start=1):
            if obs_sp != self.single_observation_space or act_sp != self.single_action_space:
                raise RuntimeError(
                    f"env worker {w} reports different spaces than worker 0 — all pool envs "
                    "must share one observation/action space"
                )
        self.observation_space = batch_space(self.single_observation_space, self.num_envs)
        self.action_space = batch_space(self.single_action_space, self.num_envs)

        self._shm = ShmObsBuffers(self.single_observation_space, self.num_envs)
        for handle in self._handles:
            self._sup.attach(handle, self._shm.specs)

        self._faults = FaultSchedule(self.config.faults)
        self._step_count = 0
        self._last_seeds: List[Optional[int]] = [None] * self.num_envs
        self._masked = np.zeros(self.num_envs, dtype=np.bool_)
        self._rewards = np.zeros(self.num_envs, dtype=np.float64)
        self._terminations = np.zeros(self.num_envs, dtype=np.bool_)
        self._truncations = np.zeros(self.num_envs, dtype=np.bool_)
        self.restart_counts = [0] * num_workers
        self.masked_slots: List[int] = []

    # ------------------------------------------------------------ properties
    @property
    def num_workers(self) -> int:
        return len(self._handles)

    @property
    def video_slots(self) -> List[int]:
        """Global slot indices owning a ``RecordVideo`` recorder (reported by
        the workers at handshake; exactly ``[0]`` when ``env.capture_video``
        is on for rank 0, regardless of slot→worker placement)."""
        return sorted(s for h in self._handles for s in h.video_slots)

    # -------------------------------------------------------------- gym API
    def reset(
        self,
        *,
        seed: Union[int, Sequence[Optional[int]], None] = None,
        options: Optional[dict] = None,
    ):
        self._assert_open()
        if seed is None:
            seeds: List[Optional[int]] = [None] * self.num_envs
        elif isinstance(seed, int):
            seeds = [seed + i for i in range(self.num_envs)]
        else:
            seeds = list(seed)
            if len(seeds) != self.num_envs:
                raise ValueError(f"expected {self.num_envs} seeds, got {len(seeds)}")
        self._last_seeds = seeds

        t0 = time.perf_counter()
        batcher = _InfoBatcher(self.num_envs)
        infos: Dict[str, Any] = {}
        busy = 0.0
        for handle in self._alive_handles():
            self._send(handle, ("reset", [seeds[s] for s in handle.slots], options))
        slot_infos: Dict[int, dict] = {}
        for handle in list(self._alive_handles()):
            reply = self._collect(handle, phase="reset")
            if reply is None:  # worker masked during this reset
                continue
            _, pairs, busy_s = reply
            busy = max(busy, busy_s)
            for slot, info in pairs:
                slot_infos[slot] = info
        for slot in range(self.num_envs):
            if slot in slot_infos:
                infos = batcher._add_info(infos, slot_infos[slot], slot)
        self._terminations[:] = False
        self._truncations[:] = False
        self._emit_span("rollout/env_reset", t0, busy)
        return self._shm.read(self.config.copy_obs), infos

    def step(self, actions):
        self._assert_open()
        per_slot_actions = list(iterate(self.action_space, actions))
        due_faults = self._faults.pop_due(self._step_count)
        self._step_count += 1

        t0 = time.perf_counter()
        self._rewards[:] = 0.0
        self._terminations[:] = False
        self._truncations[:] = False
        busy = 0.0
        restarted: Dict[int, dict] = {}  # slot -> final_info for truncated in-flight episodes
        masked_now: List[int] = []

        for handle in self._alive_handles():
            wire_faults = [f.to_wire() for f in due_faults.get(handle.index, [])]
            self._send(handle, ("step", [per_slot_actions[s] for s in handle.slots], wire_faults))

        results: Dict[int, tuple] = {}
        for handle in list(self._handles):
            if handle.masked or handle.conn is None:
                continue
            reply = self._collect(handle, phase="step")
            if reply is None:
                if handle.masked:
                    masked_now.extend(handle.slots)
                else:  # restarted: in-flight episodes truncated, envs reset
                    for slot in handle.slots:
                        restarted[slot] = {"worker_restart": True}
                continue
            _, worker_results, busy_s = reply
            busy = max(busy, busy_s)
            for slot, result in zip(handle.slots, worker_results):
                results[slot] = result

        batcher = _InfoBatcher(self.num_envs)
        infos: Dict[str, Any] = {}
        for slot in range(self.num_envs):
            if slot in results:
                reward, terminated, truncated, env_info, final = results[slot]
                self._rewards[slot] = reward
                self._terminations[slot] = terminated
                self._truncations[slot] = truncated
                if final is not None:
                    final_obs, final_info = final
                    infos = batcher._add_info(infos, {"final_obs": final_obs, "final_info": final_info}, slot)
                infos = batcher._add_info(infos, env_info, slot)
            elif slot in restarted:
                # the worker died mid-episode: its envs were recreated and
                # reset during the restart (the reset obs is already in shm);
                # report the lost episode as truncated, with the reset obs
                # standing in for final_obs so value bootstraps stay defined
                self._truncations[slot] = True
                final_obs = {k: v[slot].copy() for k, v in self._shm.views.items()}
                infos = batcher._add_info(
                    infos, {"final_obs": final_obs, "final_info": restarted[slot]}, slot
                )
            elif slot in masked_now:
                # last signal from a slot being masked: close the episode
                self._shm.zero_slot(slot)
                self._terminations[slot] = True
            # already-masked slots: zeros / all-False, nothing to do

        dur = time.perf_counter() - t0
        self._emit_span("rollout/env_step", t0, busy, dur=dur)
        return (
            self._shm.read(self.config.copy_obs),
            np.copy(self._rewards),
            np.copy(self._terminations),
            np.copy(self._truncations),
            infos,
        )

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        for handle in self._handles:
            try:
                self._sup.shutdown(handle)
            except Exception:
                pass
        self._shm.close()

    def __del__(self) -> None:  # pragma: no cover - interpreter-shutdown path
        try:
            self.close()
        except Exception:
            pass

    # ------------------------------------------------------------ internals
    def _assert_open(self) -> None:
        if self.closed:
            raise RuntimeError("EnvPool is closed")

    def _alive_handles(self):
        return (h for h in self._handles if not h.masked)

    def _send(self, handle: WorkerHandle, msg: tuple) -> None:
        try:
            handle.conn.send(msg)
        except (BrokenPipeError, OSError):
            pass  # surfaces as WorkerDied in _collect

    def _collect(self, handle: WorkerHandle, phase: str):
        """Wait for ``handle``'s reply, running the restart/mask policy on
        failure. Returns the reply, or ``None`` if the worker was restarted
        (its slots truncated, envs reset) or masked during this call."""
        while True:
            try:
                return self._sup.wait_reply(handle)
            except (WorkerDied, WorkerTimeout) as err:
                reason = "timeout" if isinstance(err, WorkerTimeout) else "crash"
                exhausted = (
                    handle.budget.exhausted
                    if handle.budget is not None
                    else handle.restarts >= self.config.max_restarts
                )
                if exhausted:
                    self._sup.mask(handle, reason)
                    return None
                if phase == "reset":
                    # replay the in-flight reset verbatim: same seeds, so a
                    # crash during reset is invisible to determinism
                    reset_seeds = [self._last_seeds[s] for s in handle.slots]
                else:
                    reset_seeds = [self._restart_seed(s, handle.restarts + 1) for s in handle.slots]
                try:
                    self._sup.restart(handle, f"{reason} during {phase}", reset_seeds)
                    return None
                except (WorkerDied, WorkerTimeout):
                    continue  # replacement failed too: loop against the budget

    def _restart_seed(self, slot: int, generation: int) -> int:
        base = self._last_seeds[slot]
        if base is None:
            base = self._seed_base + slot
        return int(base) + 7919 * generation

    # ------------------------------------------------------------- telemetry
    def _on_restart(self, worker: int, reason: str, restarts: int) -> None:
        self.restart_counts[worker] = restarts
        from sheeprl_tpu.obs import telemetry_worker_restart

        telemetry_worker_restart(worker=worker, reason=reason, restarts=restarts)

    def _on_mask(self, worker: int, slots: Sequence[int], reason: str) -> None:
        for slot in slots:
            if slot not in self.masked_slots:
                self.masked_slots.append(slot)
            self._masked[slot] = True
        from sheeprl_tpu.obs import telemetry_masked_slot

        telemetry_masked_slot(worker=worker, slots=list(slots), reason=reason)

    def _emit_span(self, name: str, t0: float, busy_s: float, dur: Optional[float] = None) -> None:
        from sheeprl_tpu.obs import get_telemetry

        tel = get_telemetry()
        if tel is None:
            return
        dur = time.perf_counter() - t0 if dur is None else dur
        queue_wait = max(0.0, dur - busy_s)
        tel.emit_span(name, time.time() - dur, dur, {"busy_s": busy_s, "queue_wait_s": queue_wait})
        if name == "rollout/env_step":
            tel.record_env_step(dur, queue_wait)
