"""Env-worker child process: step a batch of env slots, write obs to shm.

Protocol (pickled tuples over a duplex ``multiprocessing.Pipe``)::

    parent -> worker                      worker -> parent
    --------------------------------------------------------------------
                                          ("ready", obs_space, act_space,
                                                    video_slots)
    ("attach", {key: ShmSpec})            ("attached",)
    ("reset", [seed|None]*slots, options) ("reset_done", [(slot, info)], busy_s)
    ("step", [action]*slots, [fault])     ("step_done", [per-slot result], busy_s)
    ("close",)                            ("bye",)

A per-slot step result is ``(reward, terminated, truncated, env_info,
final)`` where ``final`` is ``None`` or ``(final_obs, final_info)`` — exactly
the payload gymnasium's ``SyncVectorEnv`` feeds ``_add_info`` under
``AutoresetMode.SAME_STEP`` (step; on termination/truncation record the final
pair, reset immediately, expose the reset obs). Replicating that shape in the
worker is what makes the pool bit-identical to ``SyncVectorEnv`` for the same
seeds.

TPU hygiene: :func:`sanitize_worker_environ` pins ``JAX_PLATFORMS=cpu`` and
strips every distributed-coordinator variable, so a worker whose env stack
imports jax transitively can never initialize the TPU runtime out from under
the learner, nor join (and wedge) the learner's process group. The parent
applies the same sanitizer to its own environ *around* ``Process.start()``
(see ``supervisor._spawn_environ``) because the child imports this package —
and therefore possibly jax — before ``worker_main`` runs.

Crashes in env code surface as an ``("error", traceback)`` message followed
by a nonzero exit; the supervisor treats both paths (message or silent death)
identically.
"""

from __future__ import annotations

import os
import time
import traceback
from typing import Any, Dict, List, Optional, Sequence

_COORDINATOR_VARS = (
    "SHEEPRL_TPU_COORDINATOR",
    "SHEEPRL_TPU_NUM_PROCESSES",
    "SHEEPRL_TPU_PROCESS_ID",
    "JAX_COORDINATOR_ADDRESS",
    "JAX_NUM_PROCESSES",
    "JAX_PROCESS_ID",
    "COORDINATOR_ADDRESS",
)


def sanitize_worker_environ(environ: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Pin ``environ`` (default ``os.environ``) to a learner-safe state: jax
    restricted to the CPU backend, no distributed init, and a marker so any
    code that cares can tell it runs inside an env worker."""
    env = os.environ if environ is None else environ
    env["JAX_PLATFORMS"] = "cpu"
    env["SHEEPRL_TPU_ENV_WORKER"] = "1"
    for var in _COORDINATOR_VARS:
        env.pop(var, None)
    return env


def _has_video_recorder(env: Any) -> bool:
    import gymnasium as gym

    while isinstance(env, gym.Wrapper):
        if isinstance(env, gym.wrappers.RecordVideo):
            return True
        env = env.env
    return False


def _execute_fault(fault: Dict[str, Any], hb, worker_index: int) -> None:
    kind = fault.get("kind")
    if kind == "crash":
        # skip atexit/finalizers: a SIGKILL-like death is exactly what the
        # supervisor must recover from
        os._exit(13)
    elif kind == "hang":
        # stop heartbeating too — a hung env can't make progress; sleep in
        # small slices so a terminate() lands promptly
        deadline = time.time() + (float(fault.get("duration_s") or 0.0) or 3600.0)
        while time.time() < deadline:
            time.sleep(0.05)
    elif kind == "slow":
        dur = float(fault.get("duration_s") or 0.0) or 1.0
        deadline = time.time() + dur
        while time.time() < deadline:
            hb[worker_index] = time.time()
            time.sleep(min(0.05, dur))


def worker_main(
    conn,
    hb,
    worker_index: int,
    global_slots: Sequence[int],
    thunk_blob: bytes,
) -> None:
    """Child-process entrypoint (module-level: spawn pickles it by name)."""
    sanitize_worker_environ()
    shm_views = None
    envs: List[Any] = []
    try:
        import cloudpickle

        thunks = cloudpickle.loads(thunk_blob)
        envs = [thunk() for thunk in thunks]
        video_slots = [slot for env, slot in zip(envs, global_slots) if _has_video_recorder(env)]
        hb[worker_index] = time.time()
        conn.send(("ready", envs[0].observation_space, envs[0].action_space, video_slots))

        from sheeprl_tpu.rollout.shm import ShmSlotViews

        while True:
            msg = conn.recv()
            hb[worker_index] = time.time()
            cmd = msg[0]
            if cmd == "attach":
                shm_views = ShmSlotViews(msg[1])
                conn.send(("attached",))
            elif cmd == "reset":
                _, seeds, options = msg
                t0 = time.perf_counter()
                infos = []
                for env, slot, seed in zip(envs, global_slots, seeds):
                    obs, info = env.reset(seed=seed, options=options)
                    shm_views.write(slot, obs)
                    infos.append((slot, info))
                    hb[worker_index] = time.time()
                conn.send(("reset_done", infos, time.perf_counter() - t0))
            elif cmd == "step":
                _, actions, faults = msg
                for fault in faults:
                    _execute_fault(fault, hb, worker_index)
                t0 = time.perf_counter()
                results = []
                for env, slot, action in zip(envs, global_slots, actions):
                    obs, reward, terminated, truncated, env_info = env.step(action)
                    final = None
                    if terminated or truncated:
                        final = (obs, env_info)
                        obs, env_info = env.reset()
                    shm_views.write(slot, obs)
                    results.append((reward, bool(terminated), bool(truncated), env_info, final))
                    hb[worker_index] = time.time()
                conn.send(("step_done", results, time.perf_counter() - t0))
            elif cmd == "close":
                conn.send(("bye",))
                break
            else:  # pragma: no cover - protocol bug, not a runtime path
                raise RuntimeError(f"unknown pool command {cmd!r}")
    except (EOFError, KeyboardInterrupt):
        pass
    except Exception:
        try:
            conn.send(("error", traceback.format_exc()))
        except Exception:
            pass
        os._exit(1)
    finally:
        if shm_views is not None:
            shm_views.close()
        for env in envs:
            try:
                env.close()
            except Exception:
                pass
        try:
            conn.close()
        except Exception:
            pass
