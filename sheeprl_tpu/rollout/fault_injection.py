"""Deterministic fault injection for the env-worker pool.

Faults are *scheduled by the parent* and *executed by the worker*: the pool
keeps a monotone step counter (number of completed ``step()`` calls) and
attaches any fault whose ``at_step`` matches the current counter to the step
command it sends that worker. Parent-side scheduling is what makes the
harness deterministic across worker restarts — a crashed worker cannot lose
the record of which faults already fired, because it never owned it.

The schedule/parse machinery is the shared engine in
:mod:`sheeprl_tpu.utils.faults`; this module keeps the rollout-flavored
config keys (``worker``/``at_step``) and spec dataclass as aliases into it.

Config shape (``rollout.fault_injection`` in the composed config)::

    rollout:
      fault_injection:
        enabled: true
        faults:
          - {kind: crash, worker: 0, at_step: 50}
          - {kind: hang,  worker: 1, at_step: 120}
          - {kind: slow,  worker: 0, at_step: 200, duration_s: 0.5}

``kind``:
- ``crash`` — the worker ``os._exit(13)``s before stepping its batch; the
  supervisor sees the dead process and restarts it.
- ``hang`` — the worker sleeps ``duration_s`` (default: effectively forever)
  before stepping; the supervisor's step timeout fires and the worker is
  killed + restarted.
- ``slow`` — the worker sleeps ``duration_s`` (default 1s) and then steps
  normally; shows up as a step-latency spike in telemetry, no restart.

``at_step`` is 0-based: the fault fires during the ``at_step``-th call to
``EnvPool.step()`` after the last ``reset()`` did NOT reset it — the counter
is monotone over the pool's lifetime. Each fault fires exactly once.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Sequence

from sheeprl_tpu.utils.faults import DeterministicSchedule, parse_fault_entries, register_fault_domain

_KINDS = ("crash", "hang", "slow")
register_fault_domain("rollout", _KINDS)


@dataclass
class FaultSpec:
    kind: str
    worker: int
    at_step: int
    duration_s: float = 0.0

    def __post_init__(self) -> None:
        self.kind = str(self.kind).lower()
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; expected one of {_KINDS}")
        self.worker = int(self.worker)
        self.at_step = int(self.at_step)
        self.duration_s = float(self.duration_s)
        if self.worker < 0:
            raise ValueError(f"fault worker index must be >= 0, got {self.worker}")
        if self.at_step < 0:
            raise ValueError(f"fault at_step must be >= 0, got {self.at_step}")

    def to_wire(self) -> Dict[str, Any]:
        """Plain-dict form sent over the worker pipe (std-picklable)."""
        return {"kind": self.kind, "duration_s": self.duration_s}


def parse_fault_config(node: Sequence[Mapping[str, Any]]) -> List[FaultSpec]:
    entries = parse_fault_entries(
        node,
        domain="rollout.fault_injection",
        required=("kind", "worker", "at_step"),
        fields=(
            ("worker", int, 0),
            ("at_step", int, 0),
            ("duration_s", float, 0.0),
        ),
    )
    return [FaultSpec(**e) for e in entries]


class FaultSchedule:
    """Tracks which faults already fired; queried once per pool step."""

    def __init__(self, faults: Sequence[FaultSpec]) -> None:
        self._schedule = DeterministicSchedule(
            faults, at=lambda f: f.at_step, index=lambda f: f.worker
        )

    def __bool__(self) -> bool:
        return bool(self._schedule)

    def pop_due(self, step: int) -> Dict[int, List[FaultSpec]]:
        """Return {worker_index: [faults]} due at pool step ``step`` and mark
        them fired. Faults scheduled for a step the pool already passed (e.g.
        ``at_step`` during a window where the worker was being restarted) fire
        on the next step so nothing is silently dropped."""
        return self._schedule.pop_due_by_index(step)
