"""``python -m sheeprl_tpu.cli_eval checkpoint_path=...`` (reference: sheeprl_eval.py)."""

from sheeprl_tpu.cli import evaluation

if __name__ == "__main__":
    evaluation()
