"""Host-side replay buffers (reference: sheeprl/data/buffers.py:20-1180).

Design (TPU-first):

- Storage is a dict of ``[buffer_size, n_envs, ...]`` numpy arrays on the
  host (optionally disk-backed via :class:`MemmapArray`) — replay data never
  lives in HBM; only sampled batches cross to the device.
- ``sample()`` returns numpy; ``sample_device()`` stages the batch into HBM
  with ``jax.device_put`` (optionally under a ``Sharding`` so a data-parallel
  batch lands pre-sharded across the mesh, one transfer per shard over PCIe).
  This replaces the reference's ``sample_tensors(device=...)`` torch path.
- RNGs are seedable (``seed=``) for reproducible runs; the reference uses an
  unseeded ``np.random.default_rng()``.

Shapes follow the reference contract exactly so algorithms and tests map 1:1:
``add`` takes ``[seq_len, n_envs, ...]``; ``ReplayBuffer.sample`` returns
``[n_samples, batch_size, ...]``; sequential/episode buffers return
``[n_samples, seq_len, batch_size, ...]``.
"""

from __future__ import annotations

import os
import shutil
import uuid
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Type

import numpy as np

from sheeprl_tpu import native
from sheeprl_tpu.data.memmap import MemmapArray, _ALLOWED_MODES


def _validate_add_data(data: Dict[str, np.ndarray]) -> None:
    if not isinstance(data, dict):
        raise ValueError(f"'data' must be a dictionary of numpy arrays, got {type(data)}")
    shape0 = None
    key0 = None
    for k, v in data.items():
        if not isinstance(v, (np.ndarray, MemmapArray)):
            raise ValueError(f"'data' must contain numpy arrays; key {k!r} has type {type(v)}")
        if v.ndim < 2:
            raise RuntimeError(
                f"'data' arrays must be [sequence_length, n_envs, ...]; shape of {k!r} is {v.shape}"
            )
        if shape0 is None:
            shape0, key0 = v.shape[:2], k
        elif v.shape[:2] != shape0:
            raise RuntimeError(
                f"arrays must agree in the first 2 dims: {key0!r} has {shape0}, {k!r} has {v.shape[:2]}"
            )


def to_device(
    samples: Dict[str, np.ndarray],
    dtype: Any = None,
    sharding: Any = None,
) -> Dict[str, Any]:
    """Stage a sampled host batch into device HBM.

    With ``sharding`` (a ``jax.sharding.Sharding``) each array is placed
    pre-sharded across the mesh — the TPU equivalent of the reference's
    per-rank ``sample_tensors(device=fabric.device)`` (buffers.py:291-326),
    except one call feeds every replica. ``dtype=None`` keeps host dtypes.
    """
    import jax
    import jax.numpy as jnp

    out: Dict[str, Any] = {}
    for k, v in samples.items():
        arr = np.asarray(v)
        if dtype is not None:
            arr = arr.astype(dtype, copy=False)
        out[k] = jax.device_put(arr, sharding) if sharding is not None else jnp.asarray(arr)
    return out


class ReplayBuffer:
    """Uniform-sampling circular buffer over ``[buffer_size, n_envs, ...]``
    arrays (reference buffers.py:20-360)."""

    batch_axis: int = 1

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: str | os.PathLike | None = None,
        memmap_mode: str = "r+",
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        if memmap:
            if memmap_mode not in _ALLOWED_MODES:
                raise ValueError(f"Accepted values for memmap_mode are {_ALLOWED_MODES}, got {memmap_mode!r}")
            if memmap_dir is None:
                raise ValueError(
                    "The buffer is memory-mapped but 'memmap_dir' is None. Set it to a known directory."
                )
            memmap_dir = Path(memmap_dir)
            memmap_dir.mkdir(parents=True, exist_ok=True)
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._memmap = memmap
        self._memmap_dir = memmap_dir
        self._memmap_mode = memmap_mode
        self._buf: Dict[str, np.ndarray | MemmapArray] = {}
        self._pos = 0
        self._full = False
        self._rng = np.random.default_rng(seed)

    # ------------------------------------------------------------------ #
    @property
    def buffer(self) -> Dict[str, np.ndarray]:
        return self._buf

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def full(self) -> bool:
        return self._full

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def empty(self) -> bool:
        return len(self._buf) == 0

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    def __len__(self) -> int:
        return self._buffer_size

    def seed(self, seed: Optional[int]) -> None:
        self._rng = np.random.default_rng(seed)

    # ------------------------------------------------------------------ #
    def _allocate(self, key: str, trailing_shape: Sequence[int], dtype: np.dtype) -> np.ndarray | MemmapArray:
        shape = (self._buffer_size, self._n_envs, *trailing_shape)
        if self._memmap:
            return MemmapArray(
                shape=shape,
                dtype=dtype,
                mode=self._memmap_mode,
                filename=Path(self._memmap_dir) / f"{key}.memmap",
            )
        return np.empty(shape, dtype=dtype)

    def add(self, data: "ReplayBuffer" | Dict[str, np.ndarray], validate_args: bool = False) -> None:
        """Append ``[seq_len, n_envs, ...]`` data at the cursor, wrapping and
        overwriting the oldest entries (reference buffers.py:145-221)."""
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            _validate_add_data(data)
        data_len = next(iter(data.values())).shape[0]
        if data_len > self._buffer_size:
            # only the last buffer_size rows can survive; keep the cursor
            # position consistent with having written everything
            data = {k: v[-self._buffer_size :] for k, v in data.items()}
            effective_len = self._buffer_size
        else:
            effective_len = data_len
        start = self._pos if effective_len == data_len else (self._pos + data_len) % self._buffer_size
        idxes = (start + np.arange(effective_len)) % self._buffer_size
        for k, v in data.items():
            if k not in self._buf:
                self._buf[k] = self._allocate(k, v.shape[2:], np.asarray(v).dtype)
            self._buf[k][idxes] = v[-effective_len:]
        if self._pos + data_len >= self._buffer_size:
            self._full = True
        self._pos = (self._pos + data_len) % self._buffer_size

    # ------------------------------------------------------------------ #
    def _valid_idxes(self, sample_next_obs: bool) -> np.ndarray:
        """Start indices whose transition does not straddle the write cursor
        (reference buffers.py:244-264 validity rules)."""
        if not self._full and self._pos == 0:
            raise ValueError(
                "No sample has been added to the buffer. Please add at least one sample calling 'self.add()'"
            )
        if self._full:
            end = self._pos - 1 if sample_next_obs else self._pos
            second_end = self._buffer_size if end >= 0 else self._buffer_size + end
            valid = np.concatenate(
                [np.arange(0, max(end, 0)), np.arange(self._pos, second_end)]
            ).astype(np.intp)
            if len(valid) == 0:
                raise RuntimeError(
                    "You want to sample the next observations, but every stored transition straddles "
                    "the write cursor. Make sure that at least two samples are added."
                )
            return valid
        end = self._pos - 1 if sample_next_obs else self._pos
        if end == 0:
            raise RuntimeError(
                "You want to sample the next observations, but only one sample has been added to the buffer. "
                "Make sure that at least two samples are added."
            )
        return np.arange(0, end, dtype=np.intp)

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        """Uniform sample, shape ``[n_samples, batch_size, ...]``."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0")
        if self.empty:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        valid = self._valid_idxes(sample_next_obs)
        batch_idxes = valid[self._rng.integers(0, len(valid), size=(batch_size * n_samples,), dtype=np.intp)]
        samples = self._gather(batch_idxes, sample_next_obs=sample_next_obs, clone=clone)
        return {k: v.reshape(n_samples, batch_size, *v.shape[1:]) for k, v in samples.items()}

    def _gather(
        self, batch_idxes: np.ndarray, sample_next_obs: bool = False, clone: bool = False
    ) -> Dict[str, np.ndarray]:
        env_idxes = self._rng.integers(0, self._n_envs, size=(len(batch_idxes),), dtype=np.intp)
        out: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            arr = np.asarray(v)
            g = native.gather_rows(arr, batch_idxes, env_idxes)
            if g is None:
                g = arr[batch_idxes, env_idxes]
                if clone:
                    g = g.copy()
            out[k] = g
            if sample_next_obs and k in self._obs_keys:
                nxt = native.gather_rows(arr, (batch_idxes + 1) % self._buffer_size, env_idxes)
                if nxt is None:
                    nxt = arr[(batch_idxes + 1) % self._buffer_size, env_idxes]
                    if clone:
                        nxt = nxt.copy()
                out[f"next_{k}"] = nxt
        return out

    def sample_device(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        dtype: Any = None,
        sharding: Any = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        """Sample and stage to HBM (replaces reference ``sample_tensors``)."""
        samples = self.sample(batch_size, sample_next_obs=sample_next_obs, n_samples=n_samples, **kwargs)
        return to_device(samples, dtype=dtype, sharding=sharding)

    # ------------------------------------------------------------------ #
    def __getitem__(self, key: str) -> np.ndarray | MemmapArray:
        if not isinstance(key, str):
            raise TypeError("'key' must be a string")
        if self.empty:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        return self._buf.get(key)

    def __setitem__(self, key: str, value: np.ndarray | MemmapArray) -> None:
        if not isinstance(value, (np.ndarray, MemmapArray)):
            raise ValueError(f"the value must be a np.ndarray or MemmapArray, got {type(value)}")
        if self.empty:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        if tuple(value.shape[:2]) != (self._buffer_size, self._n_envs):
            raise RuntimeError(
                f"'value' must be [buffer_size, n_envs, ...]; got shape {value.shape} with "
                f"buffer_size={self._buffer_size}, n_envs={self._n_envs}"
            )
        if self._memmap:
            filename = value.filename if isinstance(value, MemmapArray) else Path(self._memmap_dir) / f"{key}.memmap"
            old = self._buf.get(key)
            if isinstance(old, MemmapArray) and Path(old.filename) == Path(filename).resolve():
                # the displaced array must not unlink the file the new owner
                # is about to adopt
                old.has_ownership = False
            self._buf[key] = MemmapArray.from_array(value, mode=self._memmap_mode, filename=filename)
        else:
            self._buf[key] = np.copy(np.asarray(value))

    # checkpointable host state (cursor + fullness; arrays are saved separately)
    def state_dict(self) -> Dict[str, Any]:
        return {"pos": self._pos, "full": self._full}

    def load_state_dict(self, state: Dict[str, Any]) -> "ReplayBuffer":
        self._pos = int(state["pos"])
        self._full = bool(state["full"])
        return self


class SequentialReplayBuffer(ReplayBuffer):
    """Samples contiguous length-L windows ignoring episode bounds, returning
    ``[n_samples, seq_len, batch_size, ...]`` (reference buffers.py:363-526)."""

    batch_axis: int = 2

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        sequence_length: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0")
        if self.empty:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        if not self._full and self._pos == 0:
            raise ValueError(
                "No sample has been added to the buffer. Please add at least one sample calling 'self.add()'"
            )
        # with next-obs sampling the window effectively spans L+1 slots (the
        # last element's successor must also be valid)
        span = sequence_length + 1 if sample_next_obs else sequence_length
        if not self._full and self._pos - span + 1 < 1:
            raise ValueError(f"Cannot sample a sequence of length {sequence_length}. Data added so far: {self._pos}")
        if self._full and span > self._buffer_size:
            raise ValueError(
                f"The sequence length ({sequence_length}) is greater than the buffer size ({self._buffer_size})"
            )
        batch_dim = batch_size * n_samples
        if self._full:
            # valid starts: sequences must not cross the write cursor
            first_end = self._pos - span + 1
            second_end = self._buffer_size if first_end >= 0 else self._buffer_size + first_end
            valid = np.concatenate(
                [np.arange(0, max(first_end, 0)), np.arange(self._pos, second_end)]
            ).astype(np.intp)
            if len(valid) == 0:
                raise RuntimeError(
                    f"No valid sequence of length {sequence_length} exists that does not straddle the write cursor."
                )
            start_idxes = valid[self._rng.integers(0, len(valid), size=(batch_dim,), dtype=np.intp)]
        else:
            start_idxes = self._rng.integers(0, self._pos - span + 1, size=(batch_dim,), dtype=np.intp)
        # one env per sequence
        env_idxes = self._rng.integers(0, self._n_envs, size=(batch_dim,), dtype=np.intp)

        # numpy-fallback index grids, built once and only if the native path
        # declines (they are pure overhead on the C++ hot path)
        _grids: List[np.ndarray] = []

        def _fallback_grids():
            if not _grids:
                offsets = np.arange(sequence_length, dtype=np.intp)
                _grids.append((start_idxes[:, None] + offsets[None, :]) % self._buffer_size)
                _grids.append(np.repeat(env_idxes[:, None], sequence_length, axis=1))
            return _grids[0], _grids[1]

        out: Dict[str, np.ndarray] = {}
        for k, v in self._buf.items():
            arr = np.asarray(v)
            # native path: one multi-threaded C++ pass writes the final
            # contiguous [n_samples, L, batch, ...] layout (gather + transpose
            # fused), so the host->device DMA reads sequential memory
            g = native.gather_sequences(
                arr, start_idxes, env_idxes, sequence_length, n_samples, batch_size
            )
            if g is None:
                idxes, env_tiled = _fallback_grids()
                g = arr[idxes, env_tiled]  # [batch_dim, L, ...]
                g = g.reshape(n_samples, batch_size, sequence_length, *g.shape[2:]).swapaxes(1, 2)
                g = g.copy() if clone else g
            out[k] = g
            if sample_next_obs and k in self._obs_keys:
                nxt = native.gather_sequences(
                    arr, start_idxes, env_idxes, sequence_length, n_samples, batch_size, shift=1
                )
                if nxt is None:
                    idxes, env_tiled = _fallback_grids()
                    nxt = arr[(idxes + 1) % self._buffer_size, env_tiled]
                    nxt = nxt.reshape(n_samples, batch_size, sequence_length, *nxt.shape[2:]).swapaxes(1, 2)
                    nxt = nxt.copy() if clone else nxt
                out[f"next_{k}"] = nxt
        return out


class EnvIndependentReplayBuffer:
    """One sub-buffer per environment with independent cursors — needed when
    envs can restart at different points (reference buffers.py:529-743)."""

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        memmap: bool = False,
        memmap_dir: str | os.PathLike | None = None,
        memmap_mode: str = "r+",
        buffer_cls: Type[ReplayBuffer] = ReplayBuffer,
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        if memmap:
            if memmap_mode not in _ALLOWED_MODES:
                raise ValueError(f"Accepted values for memmap_mode are {_ALLOWED_MODES}, got {memmap_mode!r}")
            if memmap_dir is None:
                raise ValueError(
                    "The buffer is memory-mapped but 'memmap_dir' is None. Set it to a known directory."
                )
            memmap_dir = Path(memmap_dir)
        self._buf: List[ReplayBuffer] = [
            buffer_cls(
                buffer_size=buffer_size,
                n_envs=1,
                obs_keys=obs_keys,
                memmap=memmap,
                memmap_dir=(memmap_dir / f"env_{i}") if memmap else None,
                memmap_mode=memmap_mode,
                seed=None if seed is None else seed + i,
                **kwargs,
            )
            for i in range(n_envs)
        ]
        self._buffer_size = buffer_size
        self._n_envs = n_envs
        self._rng = np.random.default_rng(seed)
        self._concat_along_axis = buffer_cls.batch_axis

    @property
    def buffer(self) -> Sequence[ReplayBuffer]:
        return tuple(self._buf)

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def full(self) -> Sequence[bool]:
        return tuple(b.full for b in self._buf)

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def empty(self) -> Sequence[bool]:
        return tuple(b.empty for b in self._buf)

    @property
    def is_memmap(self) -> Sequence[bool]:
        return tuple(b.is_memmap for b in self._buf)

    def __len__(self) -> int:
        return self._buffer_size

    def add(
        self,
        data: "ReplayBuffer" | Dict[str, np.ndarray],
        indices: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if indices is None:
            indices = tuple(range(self._n_envs))
        elif len(indices) != next(iter(data.values())).shape[1]:
            raise ValueError(
                f"The length of 'indices' ({len(indices)}) must be equal to the second dimension of the "
                f"arrays in 'data' ({next(iter(data.values())).shape[1]})"
            )
        for data_idx, env_idx in enumerate(indices):
            env_data = {k: v[:, data_idx : data_idx + 1] for k, v in data.items()}
            self._buf[env_idx].add(env_data, validate_args=validate_args)

    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        clone: bool = False,
        n_samples: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0")
        # multinomial split of the batch across envs, concat on the batch axis
        bs_per_buf = np.bincount(self._rng.integers(0, self._n_envs, (batch_size,)), minlength=self._n_envs)
        per_buf = [
            b.sample(batch_size=bs, sample_next_obs=sample_next_obs, clone=clone, n_samples=n_samples, **kwargs)
            for b, bs in zip(self._buf, bs_per_buf)
            if bs > 0
        ]
        return {
            k: np.concatenate([s[k] for s in per_buf], axis=self._concat_along_axis) for k in per_buf[0].keys()
        }

    def sample_device(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        dtype: Any = None,
        sharding: Any = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        samples = self.sample(batch_size, sample_next_obs=sample_next_obs, n_samples=n_samples, **kwargs)
        return to_device(samples, dtype=dtype, sharding=sharding)

    def state_dict(self) -> Dict[str, Any]:
        return {"buffers": [b.state_dict() for b in self._buf]}

    def load_state_dict(self, state: Dict[str, Any]) -> "EnvIndependentReplayBuffer":
        for b, s in zip(self._buf, state["buffers"]):
            b.load_state_dict(s)
        return self


class EpisodeBuffer:
    """Stores whole episodes; samples length-L windows from within episodes
    (reference buffers.py:746-1155). Used by Dreamer-V1/V2 configs."""

    batch_axis: int = 2

    def __init__(
        self,
        buffer_size: int,
        minimum_episode_length: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        prioritize_ends: bool = False,
        memmap: bool = False,
        memmap_dir: str | os.PathLike | None = None,
        memmap_mode: str = "r+",
        seed: Optional[int] = None,
    ) -> None:
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if minimum_episode_length <= 0:
            raise ValueError(f"The sequence length must be greater than zero, got: {minimum_episode_length}")
        if buffer_size < minimum_episode_length:
            raise ValueError(
                "The sequence length must be lower than the buffer size, "
                f"got: bs = {buffer_size} and sl = {minimum_episode_length}"
            )
        self._n_envs = n_envs
        self._obs_keys = tuple(obs_keys)
        self._buffer_size = buffer_size
        self._minimum_episode_length = minimum_episode_length
        self._prioritize_ends = prioritize_ends
        self._open_episodes: List[List[Dict[str, np.ndarray]]] = [[] for _ in range(n_envs)]
        self._cum_lengths: List[int] = []
        self._buf: List[Dict[str, np.ndarray | MemmapArray]] = []
        self._rng = np.random.default_rng(seed)
        self._memmap = memmap
        self._memmap_dir = memmap_dir
        self._memmap_mode = memmap_mode
        if memmap:
            if memmap_mode not in _ALLOWED_MODES:
                raise ValueError(f"Accepted values for memmap_mode are {_ALLOWED_MODES}, got {memmap_mode!r}")
            if memmap_dir is None:
                raise ValueError(
                    "The buffer is memory-mapped but 'memmap_dir' is None. Set it to a known directory."
                )
            self._memmap_dir = Path(memmap_dir)
            self._memmap_dir.mkdir(parents=True, exist_ok=True)

    # ------------------------------------------------------------------ #
    @property
    def prioritize_ends(self) -> bool:
        return self._prioritize_ends

    @prioritize_ends.setter
    def prioritize_ends(self, value: bool) -> None:
        self._prioritize_ends = value

    @property
    def buffer(self) -> Sequence[Dict[str, np.ndarray | MemmapArray]]:
        return self._buf

    @property
    def obs_keys(self) -> Sequence[str]:
        return self._obs_keys

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def minimum_episode_length(self) -> int:
        return self._minimum_episode_length

    @property
    def is_memmap(self) -> bool:
        return self._memmap

    @property
    def full(self) -> bool:
        return self._cum_lengths[-1] + self._minimum_episode_length > self._buffer_size if self._buf else False

    def __len__(self) -> int:
        return self._cum_lengths[-1] if self._buf else 0

    # ------------------------------------------------------------------ #
    def add(
        self,
        data: "ReplayBuffer" | Dict[str, np.ndarray],
        env_idxes: Sequence[int] | None = None,
        validate_args: bool = False,
    ) -> None:
        """Split ``[seq_len, n_envs, ...]`` data on terminated|truncated and
        route chunks into per-env open episodes; a chunk ending in done closes
        and stores the episode (reference buffers.py:875-969)."""
        if isinstance(data, ReplayBuffer):
            data = data.buffer
        if validate_args:
            if data is None:
                raise ValueError("The data must be not None")
            _validate_add_data(data)
            if "terminated" not in data or "truncated" not in data:
                raise RuntimeError(
                    f"The episode must contain the `terminated` and the `truncated` keys, got: {list(data.keys())}"
                )
            if env_idxes is not None and (np.asarray(env_idxes) >= self._n_envs).any():
                raise ValueError(
                    f"The indices of the environment must be integers in [0, {self._n_envs}), given {env_idxes}"
                )
        if env_idxes is None:
            env_idxes = range(self._n_envs)
        for data_idx, env in enumerate(env_idxes):
            env_data = {k: v[:, data_idx] for k, v in data.items()}
            done = np.logical_or(env_data["terminated"], env_data["truncated"]).reshape(-1)
            ends = done.nonzero()[0].tolist()
            if not ends:
                self._open_episodes[env].append(env_data)
                continue
            ends.append(len(done))
            start = 0
            for stop in ends:
                chunk = {k: v[start : stop + 1] for k, v in env_data.items()}
                if len(chunk["terminated"]) > 0:
                    self._open_episodes[env].append(chunk)
                start = stop + 1
                if self._open_episodes[env] and bool(
                    np.logical_or(
                        self._open_episodes[env][-1]["terminated"][-1],
                        self._open_episodes[env][-1]["truncated"][-1],
                    )
                ):
                    self._save_episode(self._open_episodes[env])
                    self._open_episodes[env] = []

    def _save_episode(self, episode_chunks: Sequence[Dict[str, np.ndarray]]) -> None:
        if len(episode_chunks) == 0:
            raise RuntimeError("Invalid episode, an empty sequence is given. You must pass a non-empty sequence.")
        episode = {
            k: np.concatenate([chunk[k] for chunk in episode_chunks], axis=0) for k in episode_chunks[0].keys()
        }
        ends = np.logical_or(episode["terminated"], episode["truncated"]).reshape(-1)
        ep_len = ends.shape[0]
        if len(ends.nonzero()[0]) != 1 or not ends[-1]:
            raise RuntimeError(f"The episode must contain exactly one done, got: {len(ends.nonzero()[0])}")
        if ep_len < self._minimum_episode_length:
            raise RuntimeError(
                f"Episode too short (at least {self._minimum_episode_length} steps), got: {ep_len} steps"
            )
        if ep_len > self._buffer_size:
            raise RuntimeError(f"Episode too long (at most {self._buffer_size} steps), got: {ep_len} steps")

        # evict oldest episodes until the new one fits
        if self.full or len(self) + ep_len > self._buffer_size:
            cum = np.array(self._cum_lengths)
            keep_from = int(((len(self) - cum + ep_len) <= self._buffer_size).argmax())
            evicted, self._buf = self._buf[: keep_from + 1], self._buf[keep_from + 1 :]
            if self._memmap and self._memmap_dir is not None:
                for ep in evicted:
                    dirname = os.path.dirname(str(next(iter(ep.values())).filename))
                    ep.clear()
                    shutil.rmtree(dirname, ignore_errors=True)
            cum = cum[keep_from + 1 :] - cum[keep_from]
            self._cum_lengths = cum.tolist()
        self._cum_lengths.append(len(self) + ep_len)

        if self._memmap:
            episode_dir = Path(self._memmap_dir) / f"episode_{uuid.uuid4()}"
            episode_dir.mkdir(parents=True, exist_ok=True)
            stored = {}
            for k, v in episode.items():
                stored[k] = MemmapArray(
                    shape=v.shape, dtype=v.dtype, mode=self._memmap_mode, filename=episode_dir / f"{k}.memmap"
                )
                stored[k][:] = v
            self._buf.append(stored)
        else:
            self._buf.append(episode)

    # ------------------------------------------------------------------ #
    def sample(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        clone: bool = False,
        sequence_length: int = 1,
        **kwargs: Any,
    ) -> Dict[str, np.ndarray]:
        """Sample ``[n_samples, seq_len, batch_size, ...]`` windows from
        stored episodes (reference buffers.py:1033-1120). ``prioritize_ends``
        biases window starts toward episode tails."""
        if batch_size <= 0:
            raise ValueError(f"Batch size must be greater than 0, got: {batch_size}")
        if n_samples <= 0:
            raise ValueError(f"The number of samples must be greater than 0, got: {n_samples}")
        ep_lengths = np.array(self._cum_lengths) - np.array([0] + self._cum_lengths[:-1])
        min_len = sequence_length + 1 if sample_next_obs else sequence_length
        valid_eps = [ep for ep, L in zip(self._buf, ep_lengths) if L >= min_len]
        if len(valid_eps) == 0:
            raise RuntimeError(
                "No valid episodes has been added to the buffer. Please add at least one episode of length greater "
                f"than or equal to {sequence_length} calling `self.add()`"
            )
        offsets = np.arange(sequence_length, dtype=np.intp)[None, :]
        n_per_ep = np.bincount(
            self._rng.integers(0, len(valid_eps), (batch_size * n_samples,)), minlength=len(valid_eps)
        )
        chunks: Dict[str, List[np.ndarray]] = {k: [] for k in valid_eps[0].keys()}
        if sample_next_obs:
            chunks.update({f"next_{k}": [] for k in self._obs_keys})
        for i, n in enumerate(n_per_ep):
            if n == 0:
                continue
            ep = valid_eps[i]
            ep_len = np.asarray(ep["terminated"]).shape[0]
            if sample_next_obs:
                ep_len -= 1
            upper = ep_len - sequence_length + 1
            if self._prioritize_ends:
                upper += sequence_length
            starts = np.minimum(
                self._rng.integers(0, upper, size=(n, 1)), ep_len - sequence_length
            ).astype(np.intp)
            idxes = starts + offsets
            for k in ep.keys():
                arr = np.asarray(ep[k])
                chunks[k].append(arr[idxes.reshape(-1)].reshape(n, sequence_length, *arr.shape[1:]))
                if sample_next_obs and k in self._obs_keys:
                    chunks[f"next_{k}"].append(arr[(idxes + 1).reshape(-1)].reshape(n, sequence_length, *arr.shape[1:]))
        out: Dict[str, np.ndarray] = {}
        for k, v in chunks.items():
            if v:
                stacked = np.concatenate(v, axis=0).reshape(n_samples, batch_size, sequence_length, *v[0].shape[2:])
                out[k] = np.moveaxis(stacked, 2, 1)
                if clone:
                    out[k] = out[k].copy()
        return out

    def sample_device(
        self,
        batch_size: int,
        sample_next_obs: bool = False,
        n_samples: int = 1,
        sequence_length: int = 1,
        dtype: Any = None,
        sharding: Any = None,
        **kwargs: Any,
    ) -> Dict[str, Any]:
        samples = self.sample(
            batch_size,
            sample_next_obs=sample_next_obs,
            n_samples=n_samples,
            sequence_length=sequence_length,
            **kwargs,
        )
        return to_device(samples, dtype=dtype, sharding=sharding)
