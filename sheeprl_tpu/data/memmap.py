"""Disk-backed numpy arrays for replay storage.

TPU-native counterpart of the reference's ``sheeprl/utils/memmap.py:22-270``
(``MemmapArray``). Replay data lives on host disk via ``np.memmap``; only
sampled batches are staged to device HBM (see ``sheeprl_tpu.data.prefetch``).

Behavioral contract kept from the reference:

- exactly one *owner* per file: the instance that has ownership unlinks the
  file on garbage collection; ownership moves with ``from_array`` on the same
  filename and is dropped when pickling (spawn-safe for AsyncVectorEnv
  workers — reference memmap.py:240-258);
- assignment through ``array`` validates shape/dtype;
- ndarray operator mixin + attribute delegation so a MemmapArray can be used
  wherever an ndarray is expected.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any, Optional, Tuple

import numpy as np

_ALLOWED_MODES = ("r+", "w+", "c", "copyonwrite", "readwrite", "write")


class MemmapArray(np.lib.mixins.NDArrayOperatorsMixin):
    """An ``np.memmap`` with explicit file ownership and safe pickling."""

    def __init__(
        self,
        shape: Tuple[int, ...],
        dtype: Any = np.float32,
        mode: str = "r+",
        filename: str | os.PathLike = "./memmap_array.bin",
    ) -> None:
        if mode not in _ALLOWED_MODES:
            raise ValueError(f"Accepted values for mode are {_ALLOWED_MODES}, got {mode!r}")
        self._filename = Path(filename).resolve()
        self._dtype = np.dtype(dtype)
        self._shape = tuple(int(s) for s in shape)
        self._mode = mode
        self._filename.parent.mkdir(parents=True, exist_ok=True)
        existed = self._filename.exists()
        # np.memmap with "r+" requires the file to exist with the right size
        create_mode = self._mode if existed and self._mode != "w+" else "w+"
        self._array: Optional[np.memmap] = np.memmap(
            self._filename, dtype=self._dtype, mode=create_mode, shape=self._shape
        )
        self._has_ownership = True

    # ------------------------------------------------------------------ #
    # properties
    # ------------------------------------------------------------------ #
    @property
    def filename(self) -> Path:
        return self._filename

    @property
    def dtype(self) -> np.dtype:
        return self._dtype

    @property
    def mode(self) -> str:
        return self._mode

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def has_ownership(self) -> bool:
        return self._has_ownership

    @has_ownership.setter
    def has_ownership(self, value: bool) -> None:
        self._has_ownership = bool(value)

    @property
    def array(self) -> np.memmap:
        if self._array is None:
            # re-open after unpickling in a worker process; never with "w+",
            # which would truncate data another process owns
            mode = "r+" if self._mode in ("w+", "write") else self._mode
            self._array = np.memmap(self._filename, dtype=self._dtype, mode=mode, shape=self._shape)
        return self._array

    @array.setter
    def array(self, v: np.ndarray) -> None:
        if not isinstance(v, np.ndarray):
            raise ValueError(f"The value to be set must be an instance of 'np.ndarray', got {type(v)}")
        if isinstance(v, np.memmap):
            # adopt another memmap's file: point at it without taking ownership
            if v.shape != self._shape or v.dtype != self._dtype:
                raise ValueError(
                    f"memmap shape/dtype mismatch: have {self._shape}/{self._dtype}, got {v.shape}/{v.dtype}"
                )
            if Path(v.filename).resolve() != self._filename:
                self._close()
                self._filename = Path(v.filename).resolve()
                self._has_ownership = False
            # re-open without truncating the adopted file
            mode = "r+" if self._mode in ("w+", "write") else self._mode
            self._array = np.memmap(self._filename, dtype=self._dtype, mode=mode, shape=self._shape)
        else:
            if v.shape != self._shape:
                raise ValueError(f"shape mismatch: memmap has {self._shape}, value has {v.shape}")
            self.array[:] = v.astype(self._dtype, copy=False)

    # ------------------------------------------------------------------ #
    # construction / lifecycle
    # ------------------------------------------------------------------ #
    @classmethod
    def from_array(
        cls,
        array: np.ndarray | "MemmapArray",
        mode: str = "r+",
        filename: str | os.PathLike = "./memmap_array.bin",
    ) -> "MemmapArray":
        """Create a MemmapArray holding a copy of ``array``. If ``array`` is
        itself a MemmapArray over the *same* file, the source loses ownership
        and the new instance takes it (reference memmap.py:171-210)."""
        src = array.array if isinstance(array, MemmapArray) else array
        same_file = isinstance(array, MemmapArray) and Path(array.filename) == Path(filename).resolve()
        if same_file:
            # adopting the source's file: never truncate it ("w+" would zero
            # the data before the copy is skipped), just take ownership
            out = cls(shape=src.shape, dtype=src.dtype, mode="r+", filename=filename)
            out._mode = mode
            array.has_ownership = False
        else:
            out = cls(shape=src.shape, dtype=src.dtype, mode=mode, filename=filename)
            out.array[:] = src
            out.array.flush()
        return out

    def _close(self) -> None:
        if self._array is not None:
            self._array.flush()
            # drop the mmap handle before a possible unlink
            del self._array
            self._array = None

    def __del__(self) -> None:
        try:
            owns = self._has_ownership
        except AttributeError:  # partially-constructed instance
            return
        try:
            self._close()
            if owns:
                self._filename.unlink(missing_ok=True)
        except Exception:
            # interpreter shutdown can tear down pathlib/numpy globals before
            # __del__ runs; never let cleanup raise
            pass

    # ------------------------------------------------------------------ #
    # pickling: drop handles, never move ownership across processes
    # ------------------------------------------------------------------ #
    def __getstate__(self) -> dict:
        state = self.__dict__.copy()
        state["_array"] = None
        # the unpickled copy (possibly in another process) must not delete the
        # file out from under the owner
        state["_has_ownership"] = False
        return state

    def __setstate__(self, state: dict) -> None:
        self.__dict__.update(state)

    # ------------------------------------------------------------------ #
    # ndarray interop
    # ------------------------------------------------------------------ #
    def __array__(self, dtype: Any = None) -> np.ndarray:
        arr = self.array
        return arr.astype(dtype) if dtype is not None else arr

    def __getattr__(self, attr: str) -> Any:
        if attr.startswith("_"):
            raise AttributeError(attr)
        return getattr(self.array, attr)

    def __getitem__(self, idx: Any) -> np.ndarray:
        return self.array[idx]

    def __setitem__(self, idx: Any, value: Any) -> None:
        self.array[idx] = value

    def __len__(self) -> int:
        return self._shape[0]

    def __repr__(self) -> str:
        return f"MemmapArray(shape={self._shape}, dtype={self._dtype}, file={self._filename})"
