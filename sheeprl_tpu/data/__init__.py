"""Data plane: host replay buffers + device prefetch (reference: sheeprl/data)."""

from sheeprl_tpu.data.buffers import (
    EnvIndependentReplayBuffer,
    EpisodeBuffer,
    ReplayBuffer,
    SequentialReplayBuffer,
    to_device,
)
from sheeprl_tpu.data.memmap import MemmapArray
from sheeprl_tpu.data.prefetch import DevicePrefetcher

__all__ = [
    "DevicePrefetcher",
    "EnvIndependentReplayBuffer",
    "EpisodeBuffer",
    "MemmapArray",
    "ReplayBuffer",
    "SequentialReplayBuffer",
    "to_device",
]
