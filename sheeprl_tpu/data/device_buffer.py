"""Accelerator-resident sequential replay buffer.

The reference keeps replay in host RAM (numpy / memmap,
``sheeprl/data/buffers.py:363-743``) and re-stages every sampled batch to the
accelerator: at replay ratio 0.5 each stored frame crosses the host→device
link ~16 times over its lifetime (batch 16 × seq 64 resamples). On TPU the
natural layout is the opposite — the ring lives in HBM, each env step uploads
its ~KB-sized transition exactly once, and sequence sampling is an on-chip
gather (HBM→HBM at memory bandwidth, no host link traffic at all). With a
remote-attached chip this turns the dominant per-update transfer
(megabytes of pixels) into a few kilobytes of gather indices.

Semantics mirror ``EnvIndependentReplayBuffer(buffer_cls=SequentialReplayBuffer)``
(per-env ring cursors, contiguous windows that never straddle an env's write
cursor, multinomial env split per batch — ``data/buffers.py:308-527``), so the
Dreamer-family loops can swap buffers without touching their math. Index
drawing stays on the host (the host mirrors the cursors; drawing needs no
device data), only the draw result crosses the link.

Storage layout: one array per key, ``[n_envs, capacity + 1, *item]`` —
env-major so a sampled window is a contiguous HBM run; the extra slot at
``capacity`` is a scratch row that absorbs writes of envs excluded from a
partial ``add`` (every write is a fixed-shape scatter, so one compiled
program serves full and partial adds alike). Writes donate the buffer state
to XLA, which aliases the update in place — adding a step never copies the
ring.

On a pure data-parallel mesh the ring shards along the env axis
(``NamedSharding`` over ``data_axis``, ``n_envs`` divisible by the axis
size): every device owns a contiguous block of env rows, ``add`` scatters
each device's env slice into its own shard under ``shard_map`` (per-device
cursor arithmetic, no cross-device traffic), and the pure sampling kernels
run shard-locally at fixed shapes — both from the host paths (gathers come
out batch-sharded, ready for the data-parallel train step) and from inside
a fused superstep's scan (each device draws its own batch shard).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from sheeprl_tpu.parallel.shard_map import shard_map


def _is_pixel(v: np.ndarray) -> bool:
    return v.dtype == np.uint8


# --------------------------------------------------------------------------- #
# Pure sampling kernels.
#
# Everything below is a plain function of device arrays — callable from inside
# another jitted program (the fused training supersteps scan these to draw a
# fresh replay batch per gradient step without a host round trip) as well as
# from the buffer's own jitted methods. The ring arrays are ``[n_envs,
# capacity + 1, ...]`` (slot ``capacity`` is the partial-add scratch row and
# is never sampled); validity is recomputed on device from the two tiny
# cursor arrays ``pos``/``full``, so the mask shapes are fixed and nothing
# recompiles as the ring fills.
# --------------------------------------------------------------------------- #


def _ring_capacity(bufs: Dict[str, jax.Array]) -> int:
    # static under jit: the trailing scratch slot is excluded from sampling
    return next(iter(bufs.values())).shape[1] - 1


def sequence_start_mask(
    pos: jax.Array, full: jax.Array, capacity: int, span: int
) -> jax.Array:
    """``[n_envs, capacity]`` bool mask of valid sequence-window starts — the
    on-device mirror of :meth:`DeviceReplayBuffer._valid_starts` (windows of
    ``span`` steps that do not straddle the env's write cursor)."""
    s = jnp.arange(capacity, dtype=jnp.int32)[None, :]
    pos = jnp.asarray(pos, jnp.int32)[:, None]
    full = jnp.asarray(full, bool)[:, None]
    first_end = pos - span + 1
    second_end = jnp.where(first_end >= 0, capacity, capacity + first_end)
    when_full = (s < jnp.maximum(first_end, 0)) | ((s >= pos) & (s < second_end))
    return jnp.where(full, when_full, s < first_end)


def transition_item_mask(
    pos: jax.Array, full: jax.Array, capacity: int, sample_next_obs: bool
) -> jax.Array:
    """``[n_envs, capacity]`` bool mask of valid transition items — the
    on-device mirror of :meth:`DeviceReplayBuffer._valid_items` (when
    ``sample_next_obs`` the slot before the cursor is excluded too: its
    successor is the oldest slot, about to be overwritten)."""
    s = jnp.arange(capacity, dtype=jnp.int32)[None, :]
    pos = jnp.asarray(pos, jnp.int32)[:, None]
    full = jnp.asarray(full, bool)[:, None]
    end = pos - (1 if sample_next_obs else 0)
    second_end = jnp.where(end >= 0, capacity, capacity + end)
    when_full = (s < jnp.maximum(end, 0)) | ((s >= pos) & (s < second_end))
    return jnp.where(full, when_full, s < jnp.maximum(end, 0))


def draw_from_mask(key: jax.Array, mask: jax.Array, n: int) -> Tuple[jax.Array, jax.Array]:
    """Draw ``(env_idx [n], item [n])`` from a validity mask with the stock
    sampling distribution — uniform env, then uniform over that env's valid
    entries — on a jax RNG stream (the host paths use the buffer's numpy
    generator; the streams differ, the distribution matches). Every env must
    have at least one valid entry (the callers validate on host before
    dispatch)."""
    n_envs = mask.shape[0]
    k_env, k_item = jax.random.split(key)
    env_idx = jax.random.randint(k_env, (n,), 0, n_envs, dtype=jnp.int32)
    rows = mask[env_idx].astype(jnp.int32)  # [n, capacity]
    counts = rows.sum(axis=1)
    u = jax.random.uniform(k_item, (n,))
    j = jnp.minimum((u * counts.astype(jnp.float32)).astype(jnp.int32), jnp.maximum(counts - 1, 0))
    # item = the (j+1)-th True of the env's row: uniform over valid entries
    item = jnp.argmax(jnp.cumsum(rows, axis=1) > j[:, None], axis=1)
    return env_idx, item.astype(jnp.int32)


def gather_sequences(
    bufs: Dict[str, jax.Array], env_idx: jax.Array, time_idx: jax.Array
) -> Dict[str, jax.Array]:
    """HBM→HBM sequence gather: ``env_idx [B]``, ``time_idx [B, T]`` →
    ``[T, B, ...]`` values (time-major, the layout the fused train steps
    consume)."""
    out = {}
    for k, b in bufs.items():
        g = b[env_idx[:, None], time_idx]  # [B, T, ...]
        out[k] = jnp.swapaxes(g, 0, 1)
    return out


def gather_transition_items(
    bufs: Dict[str, jax.Array], env_idx: jax.Array, time_idx: jax.Array
) -> Dict[str, jax.Array]:
    """Flat transition gather: ``env_idx``/``time_idx [N]`` → ``[N, ...]``."""
    return {k: b[env_idx, time_idx] for k, b in bufs.items()}


def draw_sequence_batch(
    bufs: Dict[str, jax.Array],
    pos: jax.Array,
    full: jax.Array,
    key: jax.Array,
    batch_size: int,
    sequence_length: int,
) -> Dict[str, jax.Array]:
    """One ``[T, B, ...]`` sequence batch drawn and gathered entirely
    in-graph — the Dreamer-family replay read of a fused superstep."""
    capacity = _ring_capacity(bufs)
    mask = sequence_start_mask(pos, full, capacity, sequence_length)
    env_idx, starts = draw_from_mask(key, mask, batch_size)
    offsets = jnp.arange(sequence_length, dtype=jnp.int32)
    time_idx = (starts[:, None] + offsets[None, :]) % capacity
    return gather_sequences(bufs, env_idx, time_idx)


def draw_transition_batch(
    bufs: Dict[str, jax.Array],
    pos: jax.Array,
    full: jax.Array,
    key: jax.Array,
    batch_size: int,
    sample_next_obs: bool = False,
    obs_keys: Sequence[str] = (),
) -> Dict[str, jax.Array]:
    """One ``[B, ...]`` uniform-transition batch drawn and gathered entirely
    in-graph — the SAC-family replay read of a fused superstep. Matches the
    :meth:`DeviceReplayBuffer.sample_transitions` output contract
    (``next_<k>`` at item+1 when ``sample_next_obs``)."""
    capacity = _ring_capacity(bufs)
    mask = transition_item_mask(pos, full, capacity, sample_next_obs)
    env_idx, items = draw_from_mask(key, mask, batch_size)
    out = {k: b[env_idx, items] for k, b in bufs.items()}
    if sample_next_obs:
        next_idx = (items + 1) % capacity
        for k in obs_keys:
            if k in bufs:
                out[f"next_{k}"] = bufs[k][env_idx, next_idx]
    return out


class DeviceReplayBuffer:
    """Sequential replay ring resident on an accelerator device.

    Drop-in for the ``EnvIndependentReplayBuffer``/``SequentialReplayBuffer``
    pair in single-process training loops: same ``add`` signature
    (``[1, n, ...]`` step dicts, optional env ``indices``), same sampling
    distribution, but ``sample_batches`` yields device-resident
    ``[T, B, ...]`` batches gathered on-chip.

    Pass ``mesh``/``data_axis`` (a pure data-parallel mesh; ``n_envs``
    divisible by the axis size) to shard the ring along the env axis: each
    device owns ``n_envs / shards`` contiguous env rows, writes and gathers
    run shard-locally under ``shard_map``, batches come out sharded along
    the batch axis, and the env draw becomes stratified — exactly
    ``batch / shards`` samples per device block, uniform within the block
    (the per-env marginal stays uniform; batch sizes must divide by the
    shard count). :meth:`superstep_inputs` then hands a fused superstep a
    context it can consume under the same sharding with zero resharding.
    """

    def __init__(
        self,
        buffer_size: int,
        n_envs: int = 1,
        obs_keys: Sequence[str] = ("observations",),
        device: Optional[jax.Device] = None,
        seed: Optional[int] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        data_axis: Optional[str] = None,
    ) -> None:
        if buffer_size <= 0:
            raise ValueError(f"The buffer size must be greater than zero, got: {buffer_size}")
        if n_envs <= 0:
            raise ValueError(f"The number of environments must be greater than zero, got: {n_envs}")
        self._buffer_size = int(buffer_size)
        self._n_envs = int(n_envs)
        self._obs_keys = tuple(obs_keys)
        self._device = device
        self._mesh = None
        self._data_axis = None
        self._n_shards = 1
        self._sharding: Optional[NamedSharding] = None
        if mesh is not None and data_axis is not None and int(mesh.shape[data_axis]) > 1:
            shards = int(mesh.shape[data_axis])
            if device is not None:
                raise ValueError("pass either 'device' or 'mesh'/'data_axis', not both")
            if n_envs % shards:
                raise ValueError(
                    f"a sharded ring needs n_envs ({n_envs}) divisible by the "
                    f"'{data_axis}' mesh axis size ({shards})"
                )
            self._mesh = mesh
            self._data_axis = data_axis
            self._n_shards = shards
            self._sharding = NamedSharding(mesh, P(data_axis))
        self._rng = np.random.default_rng(seed)
        # host mirrors of the per-env ring cursors (the device never needs
        # to report them back)
        self._pos = np.zeros((n_envs,), np.int64)
        self._full = np.zeros((n_envs,), bool)
        self._bufs: Optional[Dict[str, jax.Array]] = None
        self._pending_arrays: Optional[Dict[str, np.ndarray]] = None
        self._small_keys: Tuple[str, ...] = ()
        self._small_slices: Dict[str, Tuple[int, int, Tuple[int, ...]]] = {}
        self._pixel_keys: Tuple[str, ...] = ()
        self._write = None
        self._gather = None
        self._amend = None

    # ------------------------------------------------------------- properties
    @property
    def buffer_size(self) -> int:
        return self._buffer_size

    @property
    def n_envs(self) -> int:
        return self._n_envs

    @property
    def full(self) -> Sequence[bool]:
        return tuple(bool(f) for f in self._full)

    @property
    def empty(self) -> Sequence[bool]:
        return tuple(not f and p == 0 for f, p in zip(self._full, self._pos))

    @property
    def is_memmap(self) -> Sequence[bool]:
        return tuple(False for _ in range(self._n_envs))

    @property
    def device(self) -> Optional[jax.Device]:
        return self._device

    @property
    def sharded(self) -> bool:
        return self._n_shards > 1

    @property
    def n_shards(self) -> int:
        return self._n_shards

    def __len__(self) -> int:
        return self._buffer_size

    def __repr__(self) -> str:
        # the placement clause is load-bearing for debuggability: tests and
        # bug reports assert the ring landed where the resolver said it would
        if self.sharded:
            placement = (
                f"placement=sharded(axis={self._data_axis!r}, shards={self._n_shards}, "
                f"envs_per_shard={self._n_envs // self._n_shards})"
            )
        else:
            dev = self._device if self._device is not None else "default"
            placement = f"placement=single({dev})"
        return (
            f"DeviceReplayBuffer(buffer_size={self._buffer_size}, n_envs={self._n_envs}, "
            f"allocated={self._bufs is not None}, {placement})"
        )

    # ------------------------------------------------------------- allocation
    def _allocate(self, data: Dict[str, np.ndarray]) -> None:
        cap1 = self._buffer_size + 1
        smalls: List[str] = []
        pixels: List[str] = []
        bufs: Dict[str, jax.Array] = {}
        for k in sorted(data):
            v = np.asarray(data[k])
            item = tuple(v.shape[2:])
            if _is_pixel(v):
                pixels.append(k)
                dtype = jnp.uint8
            else:
                smalls.append(k)
                dtype = jnp.float32
            shape = (self._n_envs, cap1, *item)
            bufs[k] = jax.device_put(jnp.zeros(shape, dtype), self._sharding or self._device)
        offset = 0
        for k in smalls:
            item = tuple(np.asarray(data[k]).shape[2:])
            width = int(np.prod(item)) if item else 1
            self._small_slices[k] = (offset, offset + width, item)
            offset += width
        self._small_keys = tuple(smalls)
        self._pixel_keys = tuple(pixels)
        self._bufs = bufs
        self._build_kernels()

    def _build_kernels(self) -> None:
        # under shard_map every operand arrives as its per-device block, so
        # the kernels index with the LOCAL env count — per-device cursor
        # arithmetic falls out of the same code that serves the 1-device ring
        n_envs = self._n_envs // self._n_shards
        small_slices = dict(self._small_slices)
        pixel_keys = self._pixel_keys
        small_keys = self._small_keys

        def write(bufs, pixels, smalls, pos):
            env_ids = jnp.arange(n_envs)
            out = dict(bufs)
            for k in pixel_keys:
                out[k] = out[k].at[env_ids, pos].set(pixels[k])
            for k in small_keys:
                o0, o1, item = small_slices[k]
                seg = smalls[:, o0:o1].reshape((n_envs, *item) if item else (n_envs,))
                out[k] = out[k].at[env_ids, pos].set(seg)
            return out

        obs_keys = self._obs_keys

        def gather_transitions_next(bufs, env_idx, time_idx, next_idx):
            out = {k: b[env_idx, time_idx] for k, b in bufs.items()}
            for k in obs_keys:
                if k in bufs:
                    out[f"next_{k}"] = bufs[k][env_idx, next_idx]
            return out

        def amend(bufs, env_i, slot, terminated, truncated, is_first):
            out = dict(bufs)
            for k, v in (("terminated", terminated), ("truncated", truncated), ("is_first", is_first)):
                if k in out:
                    out[k] = out[k].at[env_i, slot].set(
                        jnp.full(out[k].shape[2:], v, out[k].dtype)
                    )
            return out

        import os

        gather_seq = gather_sequences
        gather_items = gather_transition_items
        gather_next = gather_transitions_next
        if self.sharded:
            mesh, ax = self._mesh, self._data_axis
            # write: every operand (ring, staging arrays, cursor vector) is
            # env-axis sharded, so each device scatters its own env block —
            # no collective appears in the program
            write = shard_map(write, mesh, in_specs=(P(ax), P(ax), P(ax), P(ax)), out_specs=P(ax))
            # host-path gathers: the draw is stratified per shard (see
            # draw_indices), index arrays arrive batch-axis sharded with
            # SHARD-LOCAL env ids, and the batch comes out pre-sharded along
            # the batch axis — exactly the layout the data-parallel train
            # step consumes
            gather_seq = shard_map(
                gather_seq, mesh, in_specs=(P(ax), P(ax), P(ax)), out_specs=P(None, ax)
            )
            gather_items = shard_map(
                gather_items, mesh, in_specs=(P(ax), P(None, ax), P(None, ax)), out_specs=P(None, ax)
            )
            gather_next = shard_map(
                gather_next,
                mesh,
                in_specs=(P(ax), P(None, ax), P(None, ax), P(None, ax)),
                out_specs=P(None, ax),
            )

        if os.environ.get("SHEEPRL_TPU_RING_NO_DONATE"):
            # debug switch: in-place aliasing off — every write copies the ring
            self._write = jax.jit(write)
        else:
            self._write = jax.jit(write, donate_argnums=0)
        # amend is the rare failure-recovery patch path (one env, one slot):
        # on a sharded ring the plain jit lets GSPMD route the scalar scatter
        # to whichever shard owns the env row — not worth a shard_map
        self._amend = (
            jax.jit(amend)
            if os.environ.get("SHEEPRL_TPU_RING_NO_DONATE")
            else jax.jit(amend, donate_argnums=0)
        )
        # the gathers are the module-level pure kernels (also callable from
        # inside a fused superstep's scan body), jitted here for the host paths
        self._gather = jax.jit(gather_seq)
        self._gather_transitions = jax.jit(gather_items)
        self._gather_transitions_next = jax.jit(gather_next)

    # ------------------------------------------------------------------ write
    def add(
        self,
        data: Dict[str, np.ndarray],
        indices: Optional[Sequence[int]] = None,
        validate_args: bool = False,
    ) -> None:
        """Append one time step for the given envs (all envs when ``indices``
        is None). ``data`` values are ``[1, len(indices), ...]`` host arrays —
        the same step-dict contract as ``EnvIndependentReplayBuffer.add``."""
        if not isinstance(data, dict):
            raise ValueError(f"'data' must be a dictionary, got {type(data)}")
        first = np.asarray(next(iter(data.values())))
        if first.shape[0] != 1:
            raise ValueError(
                f"DeviceReplayBuffer.add stores one step per call; got a [{first.shape[0]}, ...] block"
            )
        if indices is None:
            indices = range(self._n_envs)
        indices = list(indices)
        if validate_args and len(indices) != first.shape[1]:
            raise ValueError(
                f"The length of 'indices' ({len(indices)}) must be equal to the second dimension of the "
                f"arrays in 'data' ({first.shape[1]})"
            )
        if self._bufs is None:
            self._allocate(data)
        if set(data) != set(self._bufs):
            raise ValueError(
                f"add() keys {sorted(data)} do not match the allocated keys {sorted(self._bufs)}"
            )

        # scatter targets: the env's cursor, or the scratch slot for envs not
        # in this (partial) add. Staging arrays are allocated once and
        # overwritten in place — rows of envs excluded from a partial add
        # keep stale bytes, which land harmlessly in the scratch slot
        if not hasattr(self, "_stage_pos"):
            width = sum(s[1] - s[0] for s in self._small_slices.values())
            self._stage_pos = np.empty((self._n_envs,), np.int32)
            self._stage_smalls = np.zeros((self._n_envs, width), np.float32)
            self._stage_pixels = {
                k: np.zeros((self._n_envs, *self._bufs[k].shape[2:]), np.uint8)
                for k in self._pixel_keys
            }
        pos, pixels, smalls = self._stage_pos, self._stage_pixels, self._stage_smalls
        pos.fill(self._buffer_size)
        for col, env in enumerate(indices):
            pos[env] = self._pos[env]
            for k in self._pixel_keys:
                pixels[k][env] = data[k][0, col]
            for k in self._small_keys:
                o0, o1, _ = self._small_slices[k]
                smalls[env, o0:o1] = np.asarray(data[k][0, col], np.float32).reshape(-1)

        ref_device = (
            self._mesh.devices.flat[0] if self._mesh is not None else (self._device or jax.devices()[0])
        )
        if ref_device.platform == "cpu":
            # PJRT CPU device_put may alias aligned numpy buffers zero-copy;
            # the staging arrays are refilled on the next add() while the
            # donated write may still be queued — hand the transfer copies
            pixels = {k: v.copy() for k, v in pixels.items()}
            smalls = smalls.copy()
            pos = pos.copy()
        # on a sharded ring the staging arrays are env-major too, so one
        # sharded device_put scatters each device's env slice onto its shard
        dev_args = jax.device_put((pixels, smalls, jnp.asarray(pos)), self._sharding or self._device)
        self._bufs = self._write(self._bufs, *dev_args)
        for env in indices:
            self._pos[env] += 1
            if self._pos[env] >= self._buffer_size:
                self._pos[env] = 0
                self._full[env] = True

    def amend_last(self, env_idx: int, terminated: float, truncated: float, is_first: float) -> None:
        """Rewrite the done/first flags of the most recent step of one env —
        the failure-recovery patch path (``RestartOnException`` buffer fixup,
        reference ``dreamer_v3.py:591-604``)."""
        if self._bufs is None:
            return
        slot = int((self._pos[env_idx] - 1) % self._buffer_size)
        self._bufs = self._amend(
            self._bufs,
            jnp.int32(env_idx),
            jnp.int32(slot),
            jnp.float32(terminated),
            jnp.float32(truncated),
            jnp.float32(is_first),
        )

    # ----------------------------------------------------------------- sample
    def _draw_env_idx(self, n: int) -> np.ndarray:
        """Env split of a host-side draw. Single-device: uniform over envs
        (multinomial counts, the stock distribution). Sharded: stratified —
        batch block ``s`` draws uniformly from shard ``s``'s env rows, so the
        gathered batch partitions cleanly along the batch axis (fixed
        per-shard sample counts; the per-env marginal stays uniform because
        every shard owns the same number of envs)."""
        if not self.sharded:
            return self._rng.integers(0, self._n_envs, (n,), dtype=np.intp)
        if n % self._n_shards:
            raise ValueError(
                f"a sharded ring draws fixed per-shard batch blocks: batch size "
                f"({n}) must divide by the shard count ({self._n_shards})"
            )
        n_local = self._n_envs // self._n_shards
        block = np.repeat(np.arange(self._n_shards, dtype=np.intp), n // self._n_shards)
        return block * n_local + self._rng.integers(0, n_local, (n,), dtype=np.intp)

    def _valid_starts(self, env: int, span: int) -> np.ndarray:
        """Window starts for one env that do not straddle its write cursor —
        the same validity rule as ``SequentialReplayBuffer.sample``
        (``data/buffers.py:341-354``)."""
        pos = int(self._pos[env])
        if self._full[env]:
            first_end = pos - span + 1
            second_end = self._buffer_size if first_end >= 0 else self._buffer_size + first_end
            return np.concatenate(
                [np.arange(0, max(first_end, 0)), np.arange(pos, second_end)]
            ).astype(np.intp)
        if pos - span + 1 < 1:
            return np.empty((0,), np.intp)
        return np.arange(0, pos - span + 1, dtype=np.intp)

    def draw_indices(
        self, batch_size: int, sequence_length: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Draw ``(env_idx [B], start [B])`` with the stock sampling
        distribution: multinomial env split, then uniform over each env's
        valid windows."""
        if batch_size <= 0:
            raise ValueError(f"'batch_size' ({batch_size}) must be greater than 0")
        if self._bufs is None:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        env_idx = self._draw_env_idx(batch_size)
        starts = np.empty((batch_size,), np.intp)
        for env in np.unique(env_idx):
            valid = self._valid_starts(int(env), sequence_length)
            if len(valid) == 0:
                raise ValueError(
                    f"Cannot sample a sequence of length {sequence_length} from env {env}. "
                    f"Data added so far: {self._pos[env]}"
                )
            rows = np.nonzero(env_idx == env)[0]
            starts[rows] = valid[self._rng.integers(0, len(valid), size=(len(rows),), dtype=np.intp)]
        return env_idx, starts

    def sample_batches(
        self, batch_size: int, sequence_length: int, n_samples: int
    ) -> Iterator[Dict[str, jax.Array]]:
        """Yield ``n_samples`` device-resident ``[T, B, ...]`` batches.

        Per batch, only ``B * (T + 1)`` int32 indices cross the host→device
        link; the pixel bytes move HBM→HBM inside one jitted gather."""
        if n_samples <= 0:
            raise ValueError(f"'n_samples' ({n_samples}) must be greater than 0")
        offsets = np.arange(sequence_length, dtype=np.int64)
        for _ in range(n_samples):
            env_idx, starts = self.draw_indices(batch_size, sequence_length)
            time_idx = (starts[:, None] + offsets[None, :]) % self._buffer_size
            if self.sharded:
                # the sharded gather indexes each device's env block, so the
                # (per-block stratified) env ids are rebased shard-locally
                env_idx = env_idx % (self._n_envs // self._n_shards)
            ei, ti = jax.device_put(
                (env_idx.astype(np.int32), time_idx.astype(np.int32)),
                self._sharding or self._device,
            )
            yield self._gather(self._bufs, ei, ti)

    # ------------------------------------------------- transition sampling
    def _valid_items(self, env: int, sample_next_obs: bool) -> np.ndarray:
        """Item indices of one env whose (transition) does not straddle its
        write cursor — the per-env mirror of ``ReplayBuffer._valid_idxes``
        (``data/buffers.py:189-214``): when ``sample_next_obs`` the slot just
        before the cursor is excluded too (its successor is the oldest slot,
        about to be overwritten)."""
        pos = int(self._pos[env])
        end = pos - 1 if sample_next_obs else pos
        if self._full[env]:
            second_end = self._buffer_size if end >= 0 else self._buffer_size + end
            return np.concatenate(
                [np.arange(0, max(end, 0)), np.arange(pos, second_end)]
            ).astype(np.intp)
        return np.arange(0, max(end, 0), dtype=np.intp)

    def sample_transitions(
        self,
        batch_size: int,
        n_samples: int = 1,
        sample_next_obs: bool = False,
    ) -> Dict[str, jax.Array]:
        """Uniform transition sample, shape ``[n_samples, batch_size, ...]``,
        device-resident — the SAC-family counterpart of ``sample_batches``:
        same output contract as host ``ReplayBuffer.sample`` (uniform env,
        uniform valid item, ``next_<k>`` at item+1 when ``sample_next_obs``),
        but only the int32 indices cross the host→device link; the batch
        bytes move HBM→HBM inside one jitted gather."""
        if batch_size <= 0 or n_samples <= 0:
            raise ValueError(
                f"'batch_size' ({batch_size}) and 'n_samples' ({n_samples}) must be both greater than 0"
            )
        if self._bufs is None:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        n = batch_size * n_samples
        if self.sharded:
            # stratify each sample row independently so every [batch] row
            # partitions into equal per-shard blocks (see _draw_env_idx)
            env_idx = np.concatenate([self._draw_env_idx(batch_size) for _ in range(n_samples)])
        else:
            env_idx = self._rng.integers(0, self._n_envs, (n,), dtype=np.intp)
        items = np.empty((n,), np.intp)
        for env in np.unique(env_idx):
            valid = self._valid_items(int(env), sample_next_obs)
            if len(valid) == 0:
                # ValueError to match the host ReplayBuffer contract for
                # empty/insufficient data (buffers.py raises ValueError there
                # and RuntimeError only for the uninitialized ring) so
                # buffer-mode-swapping callers catch one exception type
                raise ValueError(
                    "You want to sample the next observations, but not enough samples have been "
                    f"added to env {env}. Make sure that at least two samples are added."
                    if sample_next_obs
                    else "No sample has been added to the buffer. Please add at least one sample "
                    "calling 'self.add()'"
                )
            rows = np.nonzero(env_idx == env)[0]
            items[rows] = valid[self._rng.integers(0, len(valid), size=(len(rows),), dtype=np.intp)]
        if self.sharded:
            # 2-D [n_samples, batch] indices (shard-local env ids), sharded
            # along the batch axis: the gather returns the final
            # [n_samples, batch, ...] layout pre-sharded — no on-device
            # reshape of a sharded axis
            row_spec = NamedSharding(self._mesh, P(None, self._data_axis))
            shape2 = (n_samples, batch_size)
            env_local = (env_idx % (self._n_envs // self._n_shards)).astype(np.int32)
            ei, ti = jax.device_put(
                (env_local.reshape(shape2), items.astype(np.int32).reshape(shape2)), row_spec
            )
            if sample_next_obs:
                ni = jax.device_put(
                    ((items + 1) % self._buffer_size).astype(np.int32).reshape(shape2), row_spec
                )
                return self._gather_transitions_next(self._bufs, ei, ti, ni)
            return self._gather_transitions(self._bufs, ei, ti)
        ei, ti = jax.device_put(
            (env_idx.astype(np.int32), items.astype(np.int32)), self._device
        )
        if sample_next_obs:
            ni = jax.device_put(((items + 1) % self._buffer_size).astype(np.int32), self._device)
            flat = self._gather_transitions_next(self._bufs, ei, ti, ni)
        else:
            flat = self._gather_transitions(self._bufs, ei, ti)
        return {k: v.reshape(n_samples, batch_size, *v.shape[1:]) for k, v in flat.items()}

    def superstep_inputs(
        self,
        sequence_length: Optional[int] = None,
        sample_next_obs: bool = False,
    ) -> Tuple[Dict[str, jax.Array], jax.Array, jax.Array]:
        """Operands for an in-graph replay draw: ``(bufs, pos, full)``.

        A fused training superstep closes over :func:`draw_sequence_batch`
        / :func:`draw_transition_batch` and receives these as its (static
        for the window) sample context — only the two ``[n_envs]`` cursor
        arrays cross the host→device link per train window. Validity is
        checked on the host here, with the same errors as
        :meth:`draw_indices` / :meth:`sample_transitions`, because the
        in-graph draw cannot raise. Pass ``sequence_length`` for sequence
        sampling, leave it ``None`` for transition sampling. The ring must
        not be written between this call and the dispatched superstep —
        train windows never interleave with env steps, so the loops satisfy
        this by construction."""
        if self._bufs is None:
            raise RuntimeError("The buffer has not been initialized. Try to add some data first.")
        for env in range(self._n_envs):
            if sequence_length is not None:
                if len(self._valid_starts(env, int(sequence_length))) == 0:
                    raise ValueError(
                        f"Cannot sample a sequence of length {sequence_length} from env {env}. "
                        f"Data added so far: {self._pos[env]}"
                    )
            elif len(self._valid_items(env, sample_next_obs)) == 0:
                raise ValueError(
                    "You want to sample the next observations, but not enough samples have been "
                    f"added to env {env}. Make sure that at least two samples are added."
                    if sample_next_obs
                    else "No sample has been added to the buffer. Please add at least one sample "
                    "calling 'self.add()'"
                )
        # copies: on CPU device_put may alias the host mirrors zero-copy, and
        # add() mutates them in place while the superstep is still queued.
        # On a sharded ring the cursors land env-axis sharded like the bufs,
        # so the superstep's shard_map hands each device its own cursor block
        pos, full = jax.device_put(
            (self._pos.astype(np.int32), self._full.copy()), self._sharding or self._device
        )
        return self._bufs, pos, full

    def flag_last_truncated(self) -> Optional[np.ndarray]:
        """Set ``truncated=1`` on every env's most recent step (checkpoint
        self-consistency — reference ``callback.py:87-142``) and return the
        clobbered values for :meth:`restore_last_truncated`."""
        if self._bufs is None or "truncated" not in self._bufs:
            return None
        slots = ((self._pos - 1) % self._buffer_size).astype(np.int32)
        env_ids = np.arange(self._n_envs, dtype=np.int32)
        saved = np.asarray(jax.device_get(self._bufs["truncated"][env_ids, slots]))
        self._bufs = dict(self._bufs)
        self._bufs["truncated"] = (
            self._bufs["truncated"].at[env_ids, slots].set(jnp.ones_like(saved))
        )
        return saved

    def restore_last_truncated(self, saved: Optional[np.ndarray]) -> None:
        if saved is None or self._bufs is None:
            return
        slots = ((self._pos - 1) % self._buffer_size).astype(np.int32)
        env_ids = np.arange(self._n_envs, dtype=np.int32)
        self._bufs = dict(self._bufs)
        self._bufs["truncated"] = self._bufs["truncated"].at[env_ids, slots].set(jnp.asarray(saved))

    # ------------------------------------------------------------- checkpoint
    def host_arrays(self) -> Dict[str, np.ndarray]:
        """Fetch the ring (without the scratch slot) as ``[E, cap, ...]``
        numpy arrays — one bulk transfer per key."""
        if self._bufs is None:
            return dict(self._pending_arrays or {})
        return {k: np.asarray(jax.device_get(v))[:, : self._buffer_size] for k, v in self._bufs.items()}

    def __getstate__(self) -> Dict[str, Any]:
        state = {
            "buffer_size": self._buffer_size,
            "n_envs": self._n_envs,
            "obs_keys": self._obs_keys,
            "rng": self._rng,
            "pos": self._pos,
            "full": self._full,
            "small_slices": self._small_slices,
            "small_keys": self._small_keys,
            "pixel_keys": self._pixel_keys,
            "arrays": self.host_arrays(),
        }
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self._buffer_size = state["buffer_size"]
        self._n_envs = state["n_envs"]
        self._obs_keys = tuple(state["obs_keys"])
        self._rng = state["rng"]
        self._pos = state["pos"]
        self._full = state["full"]
        self._small_slices = state["small_slices"]
        self._small_keys = state["small_keys"]
        self._pixel_keys = state["pixel_keys"]
        self._device = None  # re-pinned by the restoring process
        # meshes do not pickle: a restored ring comes back single-device and
        # the restoring run's jitted consumers reshard it on first use
        self._mesh = None
        self._data_axis = None
        self._n_shards = 1
        self._sharding = None
        self._bufs = None
        self._write = self._gather = self._amend = None
        self._gather_transitions = self._gather_transitions_next = None
        self._pending_arrays = state["arrays"]

    def restore_to_device(self, device: Optional[jax.Device] = None) -> "DeviceReplayBuffer":
        """Upload a restored (unpickled) ring back to ``device``."""
        self._device = device
        arrays = getattr(self, "_pending_arrays", None)
        if arrays:
            cap1 = self._buffer_size + 1
            bufs = {}
            for k, v in arrays.items():
                padded = np.zeros((self._n_envs, cap1, *v.shape[2:]), v.dtype)
                padded[:, : self._buffer_size] = v
                bufs[k] = jax.device_put(padded, device)
            self._bufs = bufs
            self._build_kernels()
            self._pending_arrays = None
        return self

    @classmethod
    def from_host_buffer(
        cls, host_rb: Any, device: Optional[jax.Device] = None, seed: Optional[int] = None
    ) -> "DeviceReplayBuffer":
        """Bulk-load an ``EnvIndependentReplayBuffer`` (e.g. from a resumed
        checkpoint) into HBM."""
        subs = host_rb.buffer
        n_envs = len(subs)
        out = cls(host_rb.buffer_size, n_envs=n_envs, obs_keys=subs[0]._obs_keys, device=device, seed=seed)
        keys = list(subs[0].buffer.keys())
        arrays = {
            k: np.stack([np.asarray(sub.buffer[k])[:, 0] for sub in subs]) for k in keys
        }
        out._pos = np.array([sub._pos for sub in subs], np.int64)
        out._full = np.array([sub.full for sub in subs], bool)
        out._pending_arrays = {
            k: (v if v.dtype == np.uint8 else v.astype(np.float32)) for k, v in arrays.items()
        }
        # _pending_arrays carries [E, cap, ...]; reuse the restore path
        out._small_slices = {}
        smalls = [k for k in sorted(keys) if arrays[k].dtype != np.uint8]
        offset = 0
        for k in smalls:
            item = tuple(arrays[k].shape[2:])
            width = int(np.prod(item)) if item else 1
            out._small_slices[k] = (offset, offset + width, item)
            offset += width
        out._small_keys = tuple(smalls)
        out._pixel_keys = tuple(k for k in sorted(keys) if arrays[k].dtype == np.uint8)
        out.restore_to_device(device)
        return out

    @classmethod
    def from_transition_host_buffer(
        cls, host_rb: Any, device: Optional[jax.Device] = None, seed: Optional[int] = None
    ) -> "DeviceReplayBuffer":
        """Bulk-load a plain ``ReplayBuffer`` (SAC-family checkpoint,
        ``[size, n_envs, ...]`` arrays with one global cursor) into HBM."""
        arrays = {k: np.asarray(v).swapaxes(0, 1) for k, v in host_rb.buffer.items()}
        out = cls(
            host_rb.buffer_size,
            n_envs=host_rb.n_envs,
            obs_keys=host_rb._obs_keys,
            device=device,
            seed=seed,
        )
        out._pos = np.full((host_rb.n_envs,), host_rb._pos, np.int64)
        out._full = np.full((host_rb.n_envs,), host_rb.full, bool)
        out._pending_arrays = {
            k: (v if v.dtype == np.uint8 else v.astype(np.float32)) for k, v in arrays.items()
        }
        smalls = [k for k in sorted(arrays) if arrays[k].dtype != np.uint8]
        offset = 0
        out._small_slices = {}
        for k in smalls:
            item = tuple(arrays[k].shape[2:])
            width = int(np.prod(item)) if item else 1
            out._small_slices[k] = (offset, offset + width, item)
            offset += width
        out._small_keys = tuple(smalls)
        out._pixel_keys = tuple(k for k in sorted(arrays) if arrays[k].dtype == np.uint8)
        out.restore_to_device(device)
        return out

    def to_transition_host_buffer(self, memmap: bool = False, memmap_dir: Any = None) -> Any:
        """Materialize as a stock plain ``ReplayBuffer`` (the SAC-family host
        layout) — the cursors advance in lockstep in those loops, so env 0's
        cursor is the global one."""
        from sheeprl_tpu.data.buffers import ReplayBuffer

        host = ReplayBuffer(
            self._buffer_size,
            n_envs=self._n_envs,
            obs_keys=self._obs_keys,
            memmap=memmap,
            memmap_dir=memmap_dir,
        )
        if not ((self._pos == self._pos[0]).all() and (self._full == self._full[0]).all()):
            raise RuntimeError(
                "to_transition_host_buffer requires lockstep env cursors (the plain "
                f"ReplayBuffer has one global cursor) but pos={self._pos.tolist()} "
                f"full={self._full.tolist()} — this ring was written with partial "
                "per-env adds; convert with to_host_buffer() instead"
            )
        arrays = self.host_arrays()
        host.add({k: v.swapaxes(0, 1) for k, v in arrays.items()})
        host._pos = int(self._pos[0])
        host._full = bool(self._full[0])
        return host

    def ring_bytes(self) -> int:
        """Current HBM footprint of the allocated ring."""
        if self._bufs is None:
            return 0
        return sum(int(np.prod(v.shape)) * v.dtype.itemsize for v in self._bufs.values())

    def to_host_buffer(self, memmap: bool = False, memmap_dir: Any = None) -> Any:
        """Materialize as a stock ``EnvIndependentReplayBuffer`` (host RAM),
        e.g. to hand a checkpoint to a host-buffer run."""
        from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer

        host = EnvIndependentReplayBuffer(
            self._buffer_size,
            n_envs=self._n_envs,
            obs_keys=self._obs_keys,
            memmap=memmap,
            memmap_dir=memmap_dir,
            buffer_cls=SequentialReplayBuffer,
        )
        arrays = self.host_arrays()
        for env, sub in enumerate(host.buffer):
            # prime allocation with a single step, then overwrite wholesale
            step = {k: v[env : env + 1, 0:1].swapaxes(0, 1) for k, v in arrays.items()}
            sub.add(step)
            for k, v in arrays.items():
                sub[k] = v[env][:, None]
            sub._pos = int(self._pos[env])
            sub._full = bool(self._full[env])
        return host


def estimate_ring_bytes(
    obs_space: Any, actions_dim: Sequence[int], buffer_size: int, n_envs: int
) -> int:
    """Upper-bound estimate of the HBM ring footprint for a Dreamer-style
    step dict (obs keys + actions + 4 scalar flags), used by the ``auto``
    device-buffer decision before any data exists."""
    per_step = 0
    for k in obs_space.spaces:
        space = obs_space[k]
        itemsize = 1 if np.issubdtype(space.dtype, np.uint8) else 4
        per_step += int(np.prod(space.shape)) * itemsize
    per_step += (int(np.sum(actions_dim)) + 4) * 4
    return per_step * int(buffer_size) * int(n_envs)


def estimate_transition_bytes(
    obs_space: Any,
    keys: Sequence[str],
    actions_dim: Sequence[int],
    buffer_size: int,
    n_envs: int,
    store_next_obs: bool,
) -> int:
    """Upper-bound HBM estimate for a SAC-style transition step dict: the
    stored obs keys (doubled when the loop stores explicit next obs), actions
    and 3 scalar flags."""
    per_step = 0
    for k in keys:
        space = obs_space[k]
        itemsize = 1 if np.issubdtype(space.dtype, np.uint8) else 4
        per_step += int(np.prod(space.shape)) * itemsize
    if store_next_obs:
        per_step *= 2
    per_step += (int(np.sum(actions_dim)) + 3) * 4
    return per_step * int(buffer_size) * int(n_envs)


def resolve_device_buffer(
    cfg: Any,
    fabric: Any,
    obs_space: Any,
    actions_dim: Sequence[int],
    buffer_size: int,
    n_envs: int,
    estimated_bytes: Optional[int] = None,
) -> bool:
    """Decide whether this run keeps replay in HBM.

    The ring has two placements: single-device, and sharded along the env
    axis of a pure data-parallel mesh. ``buffer.device=true`` forces HBM and
    raises when neither placement fits (multi-process runs, ``model_axis``
    meshes, or ``n_envs`` not divisible by the data-axis size); ``auto``
    picks HBM when a placement fits AND the backend is not CPU AND the
    estimated footprint stays under ``buffer.device_max_bytes`` (on a
    sharded ring that budget is per the whole mesh — each device holds
    ``1/data_parallel_size`` of it).
    """
    spec = cfg.buffer.get("device", "auto")
    unsupported_reason = None
    if fabric.num_processes != 1:
        unsupported_reason = (
            f"the ring cannot span processes (num_processes={fabric.num_processes})"
        )
    elif fabric.world_size > 1 and fabric.model_axis is not None:
        unsupported_reason = (
            f"the sharded ring needs a pure data-parallel mesh, but this run "
            f"shards params over model_axis={fabric.model_axis!r}"
        )
    elif fabric.world_size > 1 and n_envs % fabric.data_parallel_size:
        unsupported_reason = (
            f"the sharded ring splits env rows evenly across the data axis, but "
            f"n_envs={n_envs} does not divide by data_parallel_size={fabric.data_parallel_size}"
        )
    if spec in (True, "true", "True"):
        if unsupported_reason is not None:
            raise ValueError(f"buffer.device=true is impossible here: {unsupported_reason}")
        return True
    if spec in (False, "false", "False", None):
        return False
    if spec != "auto":
        raise ValueError(f"unknown buffer.device spec {spec!r}; use auto/true/false")
    if unsupported_reason is not None or jax.default_backend() == "cpu":
        return False
    est = (
        estimated_bytes
        if estimated_bytes is not None
        else estimate_ring_bytes(obs_space, actions_dim, buffer_size, n_envs)
    )
    return est <= int(cfg.buffer.get("device_max_bytes", 8_000_000_000))


def _mesh_kwargs(fabric: Any) -> Dict[str, Any]:
    """Constructor kwargs that place the ring on ``fabric``'s mesh: the
    env-axis sharding on a (>1 device) pure data-parallel mesh, single-device
    otherwise — :func:`resolve_device_buffer` has already rejected every
    topology the ring cannot serve."""
    if fabric.world_size > 1:
        return {"mesh": fabric.mesh, "data_axis": fabric.data_axis}
    return {}


def make_sequential_replay(
    cfg: Any,
    fabric: Any,
    obs_space: Any,
    actions_dim: Sequence[int],
    buffer_size: int,
    num_envs: int,
    obs_keys: Sequence[str],
    memmap_dir: Any,
    seed: Optional[int],
) -> Any:
    """Construct the sequential replay for a Dreamer-family loop: the HBM
    ring when :func:`resolve_device_buffer` allows it, else the stock
    host ``EnvIndependentReplayBuffer``."""
    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer

    if resolve_device_buffer(cfg, fabric, obs_space, actions_dim, buffer_size, num_envs):
        rb = DeviceReplayBuffer(
            buffer_size,
            n_envs=num_envs,
            obs_keys=obs_keys,
            seed=seed,
            **(_mesh_kwargs(fabric)),
        )
        assert ("sharded" in repr(rb)) == (fabric.world_size > 1), repr(rb)
        return rb
    return EnvIndependentReplayBuffer(
        buffer_size,
        n_envs=num_envs,
        obs_keys=obs_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=memmap_dir,
        buffer_cls=SequentialReplayBuffer,
        seed=seed,
    )


def make_transition_replay(
    cfg: Any,
    fabric: Any,
    obs_space: Any,
    stored_keys: Sequence[str],
    actions_dim: Sequence[int],
    buffer_size: int,
    num_envs: int,
    obs_keys: Sequence[str],
    memmap_dir: Any,
    seed: Optional[int],
    store_next_obs: bool,
) -> Any:
    """Construct the uniform-transition replay for a SAC-family loop: the HBM
    ring (sampled via :meth:`DeviceReplayBuffer.sample_transitions`) when
    :func:`resolve_device_buffer` allows it, else the stock host
    ``ReplayBuffer``. ``stored_keys`` are the observation-space keys the loop
    actually writes (for the footprint estimate); ``obs_keys`` the step-dict
    keys that get a ``next_`` twin under ``sample_next_obs``."""
    from sheeprl_tpu.data.buffers import ReplayBuffer

    est = estimate_transition_bytes(
        obs_space, stored_keys, actions_dim, buffer_size, num_envs, store_next_obs
    )
    if resolve_device_buffer(
        cfg, fabric, obs_space, actions_dim, buffer_size, num_envs, estimated_bytes=est
    ):
        rb = DeviceReplayBuffer(
            buffer_size,
            n_envs=num_envs,
            obs_keys=obs_keys,
            seed=seed,
            **(_mesh_kwargs(fabric)),
        )
        assert ("sharded" in repr(rb)) == (fabric.world_size > 1), repr(rb)
        return rb
    return ReplayBuffer(
        buffer_size,
        num_envs,
        obs_keys=obs_keys,
        memmap=cfg.buffer.memmap,
        memmap_dir=memmap_dir,
        seed=seed,
    )


def adapt_restored_buffer(
    rb: Any,
    want_device: bool,
    seed: Optional[int] = None,
    mode: str = "sequence",
    memmap: bool = False,
    memmap_dir: Any = None,
) -> Any:
    """Convert a checkpoint-restored replay buffer to this run's mode —
    checkpoints from either buffer mode resume into either. ``mode`` names
    the host layout: ``sequence`` (Dreamer family,
    ``EnvIndependentReplayBuffer``) or ``transition`` (SAC family, plain
    ``ReplayBuffer``). ``memmap``/``memmap_dir`` apply when a device
    checkpoint materializes as a host buffer — pass the run's
    ``cfg.buffer.memmap`` so a pixel ring does not land in host RAM that a
    fresh run of the same config would have memmapped."""
    from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, ReplayBuffer

    if isinstance(rb, DeviceReplayBuffer):
        if want_device:
            return rb.restore_to_device()
        if mode == "sequence":
            return rb.to_host_buffer(memmap=memmap, memmap_dir=memmap_dir)
        return rb.to_transition_host_buffer(memmap=memmap, memmap_dir=memmap_dir)
    if want_device and isinstance(rb, EnvIndependentReplayBuffer):
        return DeviceReplayBuffer.from_host_buffer(rb, seed=seed)
    if want_device and isinstance(rb, ReplayBuffer):
        return DeviceReplayBuffer.from_transition_host_buffer(rb, seed=seed)
    return rb
