"""Host→HBM double-buffered prefetch pipeline.

The TPU-specific piece of the data plane (SURVEY.md §2.2 note): the reference
moves each sampled batch host→device synchronously inside the gradient loop
(``rb.sample_tensors(..., device=fabric.device)``, dreamer_v3.py:659-666),
stalling the accelerator on PCIe. Here sampling runs on a background thread
and ``jax.device_put`` is issued one batch ahead, so the transfer of batch
``i+1`` overlaps the device computation on batch ``i`` (JAX transfers are
async: ``device_put`` returns immediately and XLA orders the copy before the
first op that consumes it).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Iterator, Optional, Sequence

import numpy as np

from sheeprl_tpu.data.buffers import to_device


def sampled_batches(
    rb: Any,
    batch_size: int,
    sequence_length: int,
    n_samples: int,
    cnn_keys: Sequence[str],
    fabric: Any,
    prefetch: int = 2,
) -> Iterator[Dict[str, Any]]:
    """Yield ``n_samples`` train-ready ``[T, B]`` sequence batches for the
    Dreamer-family gradient loops.

    With ``prefetch``, batches are sampled on a background thread and placed
    one step ahead (:class:`DevicePrefetcher`), so the host→HBM transfer of
    batch ``i+1`` overlaps the gradient step on batch ``i`` — the SURVEY §7
    stage-2 deliverable, replacing the synchronous per-step staging of the
    reference (``rb.sample_tensors(..., device=...)``, dreamer_v3.py:659-666).
    Multi-host runs prefetch too: each process's worker samples its local
    block and assembles the mesh-global array (``fabric.make_global`` is
    communication-free — local shards + sharding metadata — so it is safe
    off-thread; every process draws the same batch schedule, keeping the
    global arrays aligned). ``prefetch`` is the pipeline depth (0 disables;
    2 = double buffering).

    An HBM-resident ring (:class:`~sheeprl_tpu.data.device_buffer.DeviceReplayBuffer`)
    needs neither staging nor prefetch — sampling is an on-chip gather — so it
    short-circuits here and every Dreamer-family loop picks it up for free."""
    from sheeprl_tpu.data.device_buffer import DeviceReplayBuffer

    if isinstance(rb, DeviceReplayBuffer):
        yield from rb.sample_batches(batch_size, sequence_length, n_samples)
        return

    cnn_keys = set(cnn_keys)

    def stage(sample: Dict[str, np.ndarray], i: int) -> Dict[str, np.ndarray]:
        # pixels stay uint8 across PCIe; vectors go float32
        return {k: (v[i] if k in cnn_keys else v[i].astype(np.float32)) for k, v in sample.items()}

    if prefetch and n_samples > 0:
        def sample_one() -> Dict[str, np.ndarray]:
            d = rb.sample(batch_size, sequence_length=sequence_length, n_samples=1)
            return stage(d, 0)

        if getattr(fabric, "num_processes", 1) > 1:
            place = lambda host: fabric.make_global(host, (None, fabric.data_axis))  # noqa: E731
            yield from DevicePrefetcher(sample_one, n_samples, place=place, depth=int(prefetch))
            return
        # place batches pre-sharded over the data axis so the jitted step
        # consumes them without a resharding copy
        sharding = None
        if getattr(fabric, "world_size", 1) > 1:
            sharding = fabric.sharding(None, fabric.data_axis)
        yield from DevicePrefetcher(sample_one, n_samples, sharding=sharding, depth=int(prefetch))
        return

    local = rb.sample(batch_size, sequence_length=sequence_length, n_samples=n_samples)
    # the prefetch-off path honours the same placement contract as the
    # prefetcher: on a (single-process) mesh, batches go up pre-sharded over
    # the data axis instead of landing replicated and resharding inside jit
    sharding = None
    if getattr(fabric, "num_processes", 1) == 1 and getattr(fabric, "world_size", 1) > 1:
        sharding = fabric.sharding(None, fabric.data_axis)
    for i in range(n_samples):
        batch = stage(local, i)
        if getattr(fabric, "num_processes", 1) > 1:
            batch = fabric.make_global(batch, (None, fabric.data_axis))
        elif sharding is not None:
            batch = to_device(batch, sharding=sharding)
        yield batch


class DevicePrefetcher:
    """Iterate device-resident batches produced by ``sample_fn``.

    Supports early exit (``break`` / exception) without leaking the worker
    thread or the HBM batches it holds: leaving the iterator (or calling
    ``close()``) signals the worker to stop and drains the queue. Each
    iteration starts a fresh worker, so an instance is reusable.

    Args:
        sample_fn: zero-arg callable returning a dict of host numpy arrays
            (e.g. ``lambda: rb.sample(batch_size, ...)``).
        n_batches: total number of batches to yield (one gradient loop's worth).
        dtype: optional cast applied on host before transfer (e.g. staging
            images as uint8 and normalizing on device is cheaper than shipping
            fp32 — 4x less PCIe traffic).
        sharding: optional ``jax.sharding.Sharding`` for pre-sharded placement.
        place: optional host-batch → device-batch callable overriding the
            default ``to_device`` (e.g. ``fabric.make_global`` on multi-host,
            which builds the mesh-global array from this process's block).
        depth: queue depth; 2 = classic double buffering.
    """

    def __init__(
        self,
        sample_fn: Callable[[], Dict[str, np.ndarray]],
        n_batches: int,
        dtype: Any = None,
        sharding: Any = None,
        place: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, Any]]] = None,
        depth: int = 2,
    ) -> None:
        if n_batches < 0:
            raise ValueError(f"'n_batches' must be non-negative, got {n_batches}")
        self._sample_fn = sample_fn
        self._n_batches = n_batches
        self._dtype = dtype
        self._sharding = sharding
        self._place = place
        self._depth = max(1, depth)
        self._queue: Optional["queue.Queue[Any]"] = None
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        self._err: Optional[BaseException] = None

    def _worker(self, q: "queue.Queue[Any]", stop: threading.Event) -> None:
        try:
            for _ in range(self._n_batches):
                if stop.is_set():
                    return
                host = self._sample_fn()
                if self._place is not None:
                    dev = self._place(host)
                else:
                    dev = to_device(host, dtype=self._dtype, sharding=self._sharding)
                # bounded put that still observes the stop signal
                while not stop.is_set():
                    try:
                        q.put(dev, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except BaseException as e:  # surfaced on the consumer thread
            self._err = e
            # the error sentinel must not be dropped even when the queue is
            # full, or the consumer would block forever on get()
            while not stop.is_set():
                try:
                    q.put(None, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def _drain(self) -> None:
        if self._queue is None:
            return
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break

    def close(self) -> None:
        """Stop the worker and release queued device batches."""
        if self._stop is not None:
            self._stop.set()
        # first drain unblocks a worker parked in its bounded q.put (it only
        # re-checks the stop flag between put timeouts, so it may complete
        # one more put after the drain); the post-join drain then releases
        # that last batch deterministically — without it an HBM batch could
        # sit in the orphaned queue until the GC got around to it
        self._drain()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._drain()
        self._queue = None
        self._stop = None
        self._thread = None

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        self.close()  # reset any previous run
        self._err = None
        self._queue = queue.Queue(maxsize=self._depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._worker, args=(self._queue, self._stop), daemon=True)
        self._thread.start()
        try:
            for _ in range(self._n_batches):
                batch = self._queue.get()
                if batch is None:
                    raise RuntimeError("prefetch worker failed") from self._err
                yield batch
        finally:
            self.close()
