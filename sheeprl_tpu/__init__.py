"""sheeprl_tpu — a TPU-native (JAX/XLA/pjit/Pallas) reinforcement-learning framework.

A ground-up rebuild of the capabilities of SheepRL (reference: Eclectic-Sheep/sheeprl
v0.5.5 fork, PyTorch + Lightning Fabric) designed TPU-first:

- every numeric path is a jitted pure function (models are pytrees of params),
- sequential loops (RSSM, GAE, lambda-returns) are ``lax.scan``,
- data parallelism and cross-replica reductions are XLA collectives over a
  ``jax.sharding.Mesh`` (ICI within a slice, DCN across hosts) instead of NCCL,
- replay buffers are host-side numpy ring buffers with async device prefetch,
- compute is bf16 on the MXU with fp32 parameters/optimizer state.

Layer map mirrors the reference (see SURVEY.md): config/CLI -> registry ->
single-file algorithms -> models/ops/data/envs -> fabric (mesh runtime).
"""

from __future__ import annotations

__version__ = "0.1.0"

import importlib
import importlib.util
import os

# Algorithm modules register themselves via decorators at import time
# (same mechanism as the reference's sheeprl/__init__.py:18-46). Only modules
# that exist are imported so the package stays importable while algorithms are
# added incrementally; a present-but-broken algo module still raises.
_ALGO_MODULES = (
    "a2c",
    "dreamer_v1",
    "dreamer_v2",
    "dreamer_v3",
    "droq",
    "p2e_dv1",
    "p2e_dv2",
    "p2e_dv3",
    "ppo",
    "ppo_recurrent",
    "sac",
    "sac_ae",
)

if not os.environ.get("SHEEPRL_TPU_SKIP_ALGO_IMPORTS"):
    for _name in _ALGO_MODULES:
        if importlib.util.find_spec(f"sheeprl_tpu.algos.{_name}") is not None:
            importlib.import_module(f"sheeprl_tpu.algos.{_name}")
