"""Deterministic bridge-domain faults for the online-learning loop.

Same doctrine as the serve / actor_learner / rollout domains (see
``sheeprl_tpu/utils/faults.py``): faults are scheduled against monotone
counters owned by the component that executes them, so the drills replay
bit-identically. The bridge owns three counters:

- **publish attempts** (the learner's checkpoint commits) —
  ``poison_publish`` NaN-poisons the checkpoint payload *before* the
  manifest is written (a degraded producer committing garbage),
  ``torn_publish`` writes the payload but dies before the manifest (the
  classic torn commit the manifest discipline exists for), and
  ``learner_kill`` stops the learner dead mid-swap — after the checkpoint
  is on disk, before the gauntlet verdict lands.
- **feedback rows** (reward-hook invocations) — ``hook_exception`` raises
  inside the user hook, ``hook_hang`` stalls it for ``duration_s``; both
  must shed the affected experience (counted) without touching serving.
- **assembled slabs** — ``ring_full`` refuses ring writes for a
  ``for_slabs`` window, simulating a dead/slow consumer: the bridge must
  shed whole slabs (counted ``shed_experience``) and never block the
  request path.

Config shape (``online.fault_injection.faults``)::

    faults:
      - {kind: poison_publish, at_publish: 2}
      - {kind: torn_publish,   at_publish: 3}
      - {kind: learner_kill,   at_publish: 4}
      - {kind: hook_exception, at_row: 100}
      - {kind: hook_hang,      at_row: 200, duration_s: 2.0}
      - {kind: ring_full,      at_slab: 5, for_slabs: 3}
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Mapping, Optional, Sequence

from sheeprl_tpu.utils.faults import DeterministicSchedule, parse_fault_entries, register_fault_domain

PUBLISH_KINDS = ("poison_publish", "torn_publish", "learner_kill")
HOOK_KINDS = ("hook_exception", "hook_hang")
SLAB_KINDS = ("ring_full",)
_KINDS = PUBLISH_KINDS + HOOK_KINDS + SLAB_KINDS
register_fault_domain("online", _KINDS)


@dataclass(frozen=True)
class BridgeFaultSpec:
    """One scheduled bridge fault. Exactly one trigger counter applies per
    kind; the others stay at their defaults."""

    kind: str
    at_publish: int = 0  # 1-based publish attempt (publish-counter kinds)
    at_row: int = 0  # 0-based feedback-hook invocation (hook kinds)
    at_slab: int = 0  # 0-based assembled-slab index (ring_full)
    for_slabs: int = 1  # ring_full window length in slabs
    duration_s: float = 0.0  # hook_hang stall

    def __post_init__(self) -> None:
        object.__setattr__(self, "kind", str(self.kind).lower())
        if self.kind not in _KINDS:
            raise ValueError(f"unknown online fault kind {self.kind!r}; expected one of {_KINDS}")
        if self.kind in PUBLISH_KINDS and self.at_publish < 1:
            raise ValueError(f"{self.kind} needs at_publish >= 1, got {self.at_publish}")
        if self.kind in HOOK_KINDS and self.at_row < 0:
            raise ValueError(f"{self.kind} needs at_row >= 0, got {self.at_row}")
        if self.kind == "ring_full" and self.for_slabs < 1:
            raise ValueError(f"ring_full needs for_slabs >= 1, got {self.for_slabs}")
        if self.kind == "hook_hang" and self.duration_s <= 0:
            raise ValueError(f"hook_hang needs duration_s > 0, got {self.duration_s}")


def parse_bridge_faults(node: Optional[Sequence[Mapping[str, Any]]]) -> List[BridgeFaultSpec]:
    """``online.fault_injection.faults`` -> validated specs."""
    if not node:
        return []
    entries = parse_fault_entries(
        node,
        domain="online.fault_injection",
        required=("kind",),
        fields=(
            ("at_publish", int, 0),
            ("at_row", int, 0),
            ("at_slab", int, 0),
            ("for_slabs", int, 1),
            ("duration_s", float, 0.0),
        ),
    )
    return [BridgeFaultSpec(**e) for e in entries]


class BridgeFaultSchedule:
    """Three deterministic sub-schedules, one per counter owner. Thread-safe
    like the engine underneath: the collector thread queries hook/slab
    faults while the learner thread queries publish faults."""

    def __init__(self, faults: Sequence[BridgeFaultSpec]) -> None:
        self._publish = DeterministicSchedule(
            [f for f in faults if f.kind in PUBLISH_KINDS], at=lambda f: f.at_publish
        )
        self._hook = DeterministicSchedule(
            [f for f in faults if f.kind in HOOK_KINDS], at=lambda f: f.at_row
        )
        self._slab = DeterministicSchedule(
            [f for f in faults if f.kind in SLAB_KINDS],
            at=lambda f: f.at_slab,
            window=lambda f: f.for_slabs,
        )

    def publish_fault(self, attempt: int) -> Optional[BridgeFaultSpec]:
        """At most one publish fault fires per attempt (1-based), the same
        one-per-query semantics as the serve domain's ``poison_swap``."""
        return self._publish.pop_first(attempt)

    def hook_faults(self, row_index: int) -> List[BridgeFaultSpec]:
        """Hook faults due at feedback row ``row_index`` (0-based), with
        catch-up — a fault scheduled into a shed window still fires on the
        next surviving row."""
        return self._hook.pop_due(row_index)

    def ring_full_active(self, slab_index: int) -> bool:
        """True while a ``ring_full`` window covers assembled slab
        ``slab_index`` — the bridge treats the ring as having no free slot."""
        return bool(self._slab.pop_due(slab_index))
