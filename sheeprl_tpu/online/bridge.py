"""ExperienceBridge: served traffic → version-tagged slabs in the ring.

The serving side of the loop. ``observe()`` is the tap the ``ServeClient``
(or any router-path caller) invokes after a successful infer: it is a
bounded non-blocking enqueue — the request path can never stall on the
learning loop, full stop. A collector thread drains the queue, scores each
row through the :class:`~sheeprl_tpu.online.feedback.GuardedHook`, and
assembles rows into fixed-geometry slabs tagged with the policy version
that *produced* them (``Request.served_step`` mapped through the
:class:`~sheeprl_tpu.online.version.VersionAuthority`). Slabs are written
through the PR 11 writer protocol
(:class:`~sheeprl_tpu.net.transport.ActorTransport` — shm ring or TCP, the
learner cannot tell the difference), so torn-write detection, seqlock
commit and staleness admission all apply unchanged to served experience.

Shedding doctrine (drilled, counted, telemetered — never silent, never
blocking):

- **queue full** (collector behind, e.g. a hanging hook) — ``observe``
  drops the row, counts ``rows_shed_queue``;
- **hook failure** (exception/hang/timeout) — the guard returns None, the
  row is dropped, counted ``rows_shed_hook``;
- **ring full** (learner dead or slow) — ``try_begin_write`` finds no FREE
  slot, the whole assembled slab is dropped, counted ``slabs_shed_ring``.

``shed_experience`` is the row-level total across all three — the single
number the ring-full drill gates on. A version boundary flushes the partial
slab (``n_rows`` < geometry) so one slab never mixes policies.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from sheeprl_tpu.actor_learner.ring import SlabLayout
from sheeprl_tpu.obs.trace import new_trace_id, trace_event
from sheeprl_tpu.online.config import OnlineConfig
from sheeprl_tpu.online.fault_injection import BridgeFaultSchedule
from sheeprl_tpu.online.feedback import GuardedHook
from sheeprl_tpu.online.version import VersionAuthority


def build_experience_layout(
    obs_spec: Dict[str, Any], action_shape: Tuple[int, ...], rows: int
) -> SlabLayout:
    """The served-experience slab geometry: one field per observation leaf
    (``obs.<key>``), the served action, the hook's reward, and the optional
    feedback target with its validity mask."""
    fields: Dict[str, Tuple[Tuple[int, ...], str]] = {}
    for key in sorted(obs_spec):
        sds = obs_spec[key]
        fields[f"obs.{key}"] = ((rows,) + tuple(int(d) for d in sds.shape), np.dtype(sds.dtype).str)
    act = tuple(int(d) for d in action_shape)
    fields["action"] = ((rows,) + act, np.dtype(np.float32).str)
    fields["reward"] = ((rows,), np.dtype(np.float32).str)
    fields["target"] = ((rows,) + act, np.dtype(np.float32).str)
    fields["target_mask"] = ((rows,), np.dtype(np.float32).str)
    return SlabLayout(fields)


class _Row:
    __slots__ = ("obs", "action", "version", "trace_id", "t_enqueue")

    def __init__(self, obs: Any, action: Any, version: int, trace_id: int, t_enqueue: float) -> None:
        self.obs = obs
        self.action = action
        self.version = version
        self.trace_id = trace_id
        self.t_enqueue = t_enqueue


class ExperienceBridge:
    """Collector between the serving tap and the trajectory ring."""

    def __init__(
        self,
        *,
        layout: SlabLayout,
        transport: Any,  # ActorTransport writer protocol
        authority: VersionAuthority,
        hook: GuardedHook,
        cfg: OnlineConfig,
        schedule: Optional[BridgeFaultSchedule] = None,
        actor_id: int = 0,
        on_event: Optional[Callable[[str, Dict[str, Any]], None]] = None,
    ) -> None:
        self.layout = layout
        self.transport = transport
        self.authority = authority
        self.hook = hook
        self.cfg = cfg
        self._schedule = schedule
        self.actor_id = int(actor_id)
        self._on_event = on_event
        self.rows_per_slab = int(cfg.rows_per_slab)
        # derive per-row geometry from the layout (leading dim = rows)
        self._row_shapes = {k: (shape[1:], dtype) for k, (shape, dtype) in layout.fields.items()}

        self._lock = threading.Lock()
        self._queue: Deque[_Row] = deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        # counters: single-writer each (observe() callers under _lock for the
        # queue pair; the collector thread for the rest)
        self.rows_in = 0
        self.rows_collected = 0
        self.rows_shed_queue = 0
        self.rows_shed_hook = 0
        self.slabs_committed = 0
        self.slabs_assembled = 0
        self.slabs_shed_ring = 0
        self.rows_shed_ring = 0
        self._seq = 0
        # current accumulation buffer
        self._pending: List[Tuple[_Row, Any]] = []  # (row, feedback)
        self._pending_version: Optional[int] = None

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "ExperienceBridge":
        self._thread = threading.Thread(target=self._run, name="online-bridge", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(5.0)
        self.hook.close()

    def __enter__(self) -> "ExperienceBridge":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # ------------------------------------------------------------------ tap
    def observe(self, obs: Any, action: Any, step: Any, trace_id: int = 0) -> bool:
        """The ServeClient tap: bounded enqueue, never blocks. Returns False
        when the row was shed (queue full or bridge stopped)."""
        if self._stop.is_set():
            return False
        version = self.authority.version_for_step(step)
        with self._lock:
            if len(self._queue) >= self.cfg.queue_bound:
                self.rows_shed_queue += 1
                return False
            self._queue.append(_Row(obs, action, version, int(trace_id), time.monotonic()))
            self.rows_in += 1
        self._wake.set()
        return True

    @property
    def shed_experience(self) -> int:
        """Total experience rows lost to shedding, all causes."""
        return self.rows_shed_queue + self.rows_shed_hook + self.rows_shed_ring

    # ------------------------------------------------------------ collector
    def _run(self) -> None:
        while not self._stop.is_set():
            row = self._pop()
            if row is None:
                self._wake.wait(0.02)
                self._wake.clear()
                continue
            feedback = self.hook(row.obs, row.action)
            if feedback is None:
                self.rows_shed_hook += 1
                self._event("exp_row_shed", cause="hook", version=row.version)
                continue
            self.rows_collected += 1
            if self._pending and self._pending_version != row.version:
                # version boundary: flush the partial slab so one slab never
                # mixes policies (the staleness tag must be exact)
                self._flush()
            self._pending_version = row.version
            self._pending.append((row, feedback))
            if len(self._pending) >= self.rows_per_slab:
                self._flush()
        # drain on close: best-effort flush of the partial slab
        if self._pending:
            self._flush()

    def _pop(self) -> Optional[_Row]:
        with self._lock:
            return self._queue.popleft() if self._queue else None

    def _flush(self) -> None:
        rows = self._pending
        version = self._pending_version or 0
        self._pending = []
        self._pending_version = None
        if not rows:
            return
        slab_index = self.slabs_assembled
        self.slabs_assembled += 1
        ring_full_injected = (
            self._schedule is not None and self._schedule.ring_full_active(slab_index)
        )
        if ring_full_injected or not self.transport.try_begin_write():
            # ring full (real or drilled): shed the WHOLE slab, counted —
            # the alternative (blocking) would backpressure into serving
            self.slabs_shed_ring += 1
            self.rows_shed_ring += len(rows)
            self._event(
                "exp_slab_shed",
                cause="ring_full_injected" if ring_full_injected else "ring_full",
                rows=len(rows),
                version=version,
                shed_experience=self.shed_experience,
            )
            trace_event("exp_slab_shed", 0, rows=len(rows), version=version)
            return
        tid = new_trace_id()
        t0 = rows[0][0].t_enqueue
        data = self._pack(rows)
        self.layout.pack_into(self.transport.payload_view(), data)
        self.transport.write_meta(
            seq=self._seq,
            param_version=version,
            actor_id=self.actor_id,
            n_rows=len(rows),
            collect_us=int((time.monotonic() - t0) * 1e6),
            env_steps=len(rows),
            trace_id=tid,
            commit_t_us=int(time.monotonic() * 1e6),
        )
        self.transport.commit()
        self._seq += 1
        self.slabs_committed += 1
        # the causal join request → slab: the slab's trace event carries the
        # first few request trace ids collected into it
        request_ids = [r.trace_id for r, _ in rows if r.trace_id][:8]
        trace_event("exp_slab", tid, version=version, rows=len(rows), requests=request_ids)
        self._event("exp_slab", rows=len(rows), version=version)

    def _pack(self, rows: List[Tuple[_Row, Any]]) -> Dict[str, np.ndarray]:
        n = self.rows_per_slab
        data: Dict[str, np.ndarray] = {}
        for key, (shape, dtype) in self._row_shapes.items():
            data[key] = np.zeros((n,) + shape, dtype=dtype)
        for i, (row, fb) in enumerate(rows):
            for obs_key, value in row.obs.items():
                data[f"obs.{obs_key}"][i] = np.asarray(value)
            data["action"][i] = np.asarray(row.action, dtype=np.float32)
            data["reward"][i] = float(fb.reward)
            if fb.target is not None:
                data["target"][i] = np.asarray(fb.target, dtype=np.float32)
                data["target_mask"][i] = 1.0
        return data

    # ------------------------------------------------------------ reporting
    def _event(self, kind: str, **fields: Any) -> None:
        try:
            from sheeprl_tpu.obs.telemetry import telemetry_serve_event

            telemetry_serve_event(f"online_{kind}", **fields)
        except Exception:
            pass
        if self._on_event is not None:
            try:
                self._on_event(kind, fields)
            except Exception:
                pass

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            depth = len(self._queue)
        return {
            "rows_in": self.rows_in,
            "rows_collected": self.rows_collected,
            "rows_shed_queue": self.rows_shed_queue,
            "rows_shed_hook": self.rows_shed_hook,
            "rows_shed_ring": self.rows_shed_ring,
            "slabs_committed": self.slabs_committed,
            "slabs_shed_ring": self.slabs_shed_ring,
            "shed_experience": self.shed_experience,
            "queue_depth": depth,
            **self.hook.snapshot(),
        }
