"""OnlineLearner: continuous training from the experience ring.

The learner half of the bridge. A background thread polls the PR 11 learner
transport (shm ring or TCP — :class:`~sheeprl_tpu.net.transport.LearnerTransport`)
for committed experience slabs, applies the existing staleness-bounded
admission (:func:`~sheeprl_tpu.actor_learner.config.admit` against the
version authority's latest *published* version), and folds each admitted
slab into the params with a pluggable ``train_step``. Every
``publish_every`` updates the params go to the
:class:`~sheeprl_tpu.online.publisher.CheckpointPublisher`, which commits a
manifested checkpoint and pushes it through the hot-swap gauntlet.

Robustness posture (drilled in ``tests/test_online``):

- a non-finite update is **rolled back** (the previous params stand, the
  rejection is counted + trace-evented) — the learner never publishes NaNs
  it can see itself; the gauntlet is the independent second line;
- a stale slab is dropped with ``telemetry_slab(admitted=False)`` and a
  ``slab_drop_stale`` trace event carrying the slab's trace id — the same
  accounting the actor–learner plane uses;
- the learner dying (crash or the drilled ``learner_kill`` publish fault)
  just stops consumption: the ring fills, the bridge sheds (counted), and
  the fleet keeps serving the last validated version indefinitely.

``linear_feedback_train_step`` is the built-in step for the linear policy:
masked regression of ``obs @ w + b`` toward the hook's corrected-action
targets — host-side numpy on purpose (the policy is tiny; no compile, no
device round-trip on the learning path of a CPU drill).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from sheeprl_tpu.actor_learner.config import admit
from sheeprl_tpu.actor_learner.ring import SlabLayout, SlabMeta
from sheeprl_tpu.obs.trace import trace_event
from sheeprl_tpu.online.config import OnlineConfig
from sheeprl_tpu.online.version import VersionAuthority

# train_step(params, batch) -> (new_params, metrics)
TrainStep = Callable[[Any, Dict[str, np.ndarray]], Tuple[Any, Dict[str, float]]]


def linear_feedback_train_step(lr: float = 0.1) -> TrainStep:
    """Gradient step for the linear policy on feedback labels: pull
    ``obs @ w + b`` toward each labelled row's ``target`` (rows without a
    target — ``target_mask == 0`` — contribute nothing)."""

    def step(params: Dict[str, np.ndarray], batch: Dict[str, np.ndarray]) -> Tuple[Dict[str, np.ndarray], Dict[str, float]]:
        x = np.asarray(batch["obs.vector"], dtype=np.float32)
        target = np.asarray(batch["target"], dtype=np.float32)
        mask = np.asarray(batch["target_mask"], dtype=np.float32)
        n_labeled = float(mask.sum())
        w = np.asarray(params["w"], dtype=np.float32)
        b = np.asarray(params["b"], dtype=np.float32)
        if n_labeled < 1.0:
            return {"w": w, "b": b}, {"loss": 0.0, "n_labeled": 0.0}
        pred = x @ w + b
        err = (pred - target) * mask[:, None]
        grad_w = x.T @ err / n_labeled
        grad_b = err.sum(axis=0) / n_labeled
        new = {"w": w - lr * grad_w, "b": b - lr * grad_b}
        loss = float((err**2).sum() / n_labeled)
        return new, {"loss": loss, "n_labeled": n_labeled}

    return step


def linear_state(params: Dict[str, np.ndarray], step: int) -> Dict[str, Any]:
    """Checkpointable state for the linear policy (the publisher's
    ``state_fn``): the agent tree plus the update counter the manifest and
    ``params_from_state`` expect."""
    return {
        "agent": {k: np.asarray(v) for k, v in params.items()},
        "update": int(step),
    }


class OnlineLearner:
    """Poll → admit → train → (periodically) publish, on a daemon thread."""

    def __init__(
        self,
        *,
        transport: Any,  # LearnerTransport reader protocol
        layout: SlabLayout,
        authority: VersionAuthority,
        cfg: OnlineConfig,
        params: Any,
        train_step: TrainStep,
        publisher: Optional[Any] = None,  # CheckpointPublisher
        on_event: Optional[Callable[[str, Dict[str, Any]], None]] = None,
    ) -> None:
        self.transport = transport
        self.layout = layout
        self.authority = authority
        self.cfg = cfg
        self.params = params
        self.train_step = train_step
        self.publisher = publisher
        self._on_event = on_event

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # guards params for concurrent readers

        self.updates = 0
        self.rows_trained = 0
        self.slabs_admitted = 0
        self.slabs_stale = 0
        self.updates_rejected = 0  # non-finite rollbacks
        self.publishes = 0
        self.killed = False  # learner_kill drill tripped
        self.last_loss: Optional[float] = None

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "OnlineLearner":
        self._thread = threading.Thread(target=self._run, name="online-learner", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)

    def __enter__(self) -> "OnlineLearner":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.close()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def current_params(self) -> Any:
        with self._lock:
            return self.params

    # ------------------------------------------------------------------ loop
    def _run(self) -> None:
        while not self._stop.is_set():
            meta = self.transport.poll()
            if meta is None:
                time.sleep(0.005)
                continue
            self._consume(meta)
            if self.killed:
                # the drilled mid-swap death: stop consuming, leave the ring
                # to fill — exactly what a real learner crash looks like to
                # the bridge (shed) and the fleet (keep serving)
                return

    def _consume(self, meta: SlabMeta) -> None:
        from sheeprl_tpu.obs.telemetry import telemetry_slab

        published = self.authority.published_version
        ok = admit(meta.param_version, published, self.cfg.max_staleness)
        try:
            telemetry_slab(
                staleness=published - int(meta.param_version),
                occupancy=self.transport.occupancy(),
                admitted=ok,
            )
        except Exception:
            pass
        if not ok:
            self.slabs_stale += 1
            trace_event(
                "slab_drop_stale",
                meta.trace_id,
                version=int(meta.param_version),
                published=published,
                max_staleness=self.cfg.max_staleness,
            )
            self.transport.release(meta)
            return
        data = self.layout.unpack(self.transport.payload(meta))
        self.transport.release(meta)  # unpack copies; the slot is free now
        n = max(0, min(int(meta.n_rows), self.cfg.rows_per_slab))
        batch = {k: v[:n] for k, v in data.items()}

        with self._lock:
            params = self.params
        new_params, metrics = self.train_step(params, batch)
        from sheeprl_tpu.resilience.sentinel import host_all_finite

        if not host_all_finite(new_params):
            # rollback: the previous params stand, nothing is published
            self.updates_rejected += 1
            trace_event(
                "online_update_rejected", meta.trace_id, cause="non_finite", update=self.updates
            )
            self._event("update_rejected", cause="non_finite", update=self.updates)
            return
        with self._lock:
            self.params = new_params
        self.updates += 1
        self.rows_trained += n
        self.slabs_admitted += 1
        self.last_loss = float(metrics.get("loss", 0.0))
        # the causal join slab → gradient window: the update event reuses the
        # slab's trace id so tools/trace.py can chain request → slab → update
        trace_event(
            "online_update",
            meta.trace_id,
            update=self.updates,
            version=int(meta.param_version),
            rows=n,
            loss=self.last_loss,
        )
        self._event("update", update=self.updates, rows=n, loss=self.last_loss)
        if self.publisher is not None and self.updates % self.cfg.publish_every == 0:
            self._publish()

    def _publish(self) -> None:
        with self._lock:
            params = self.params
        result = self.publisher.publish(params)
        self.publishes += 1
        self._event("publish", **{k: v for k, v in result.items() if not isinstance(v, (dict, list))})
        if result.get("killed"):
            self.killed = True
            self._stop.set()

    # ------------------------------------------------------------- reporting
    def _event(self, kind: str, **fields: Any) -> None:
        try:
            from sheeprl_tpu.obs.telemetry import telemetry_serve_event

            telemetry_serve_event(f"online_{kind}", **fields)
        except Exception:
            pass
        if self._on_event is not None:
            try:
                self._on_event(kind, fields)
            except Exception:
                pass

    def snapshot(self) -> Dict[str, Any]:
        snap = {
            "updates": self.updates,
            "rows_trained": self.rows_trained,
            "slabs_admitted": self.slabs_admitted,
            "slabs_stale": self.slabs_stale,
            "updates_rejected": self.updates_rejected,
            "publishes": self.publishes,
            "killed": self.killed,
            "last_loss": self.last_loss,
        }
        if self.publisher is not None:
            snap.update(self.publisher.snapshot())
        return snap
