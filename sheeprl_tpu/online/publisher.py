"""CheckpointPublisher: learner params → committed checkpoint → hot swap.

The learn → serve half of the loop. ``publish()`` turns the learner's
current params into a *committed* checkpoint (payload staged, manifest last
— the same crash-atomic discipline training checkpoints use), mints the
next monotonic version from the shared
:class:`~sheeprl_tpu.online.version.VersionAuthority`, optionally pushes
the flat param bytes down the PR 11 param lane under that same version,
and then asks every attached server to ``request_swap`` the new path —
which runs the full PR 6 validation gauntlet (digest, structure,
finiteness, smoke inference, prewarm) before any replica flips.

A rejected swap is the *success* of the design, not a failure of the call:
``SwapRejected`` is caught, counted, trace-evented, and the fleet keeps
serving the previous validated version. The drilled publish faults
(``sheeprl_tpu.online.fault_injection``) exercise exactly that seam:

- ``poison_publish`` — a NaN is planted in the state before the manifest is
  built, so the checkpoint *commits* (manifest digest matches the poisoned
  payload) and the gauntlet's finiteness gate must catch it;
- ``torn_publish`` — the payload lands without a manifest (a crash between
  stage and commit); discovery never sees it and no version is minted;
- ``learner_kill`` — ``publish`` returns ``{"killed": True}`` after the
  commit but before any swap push, modelling the learner dying mid-publish.

Boot-step resume goes through the shared discovery helper
(:func:`~sheeprl_tpu.resilience.discovery.newest_committed`): a publisher
pointed at a warm checkpoint dir continues the step sequence instead of
colliding with existing commits.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from sheeprl_tpu.obs.trace import trace_event
from sheeprl_tpu.online.fault_injection import BridgeFaultSchedule
from sheeprl_tpu.online.version import VersionAuthority

# state_fn(params, step) -> checkpointable state tree (e.g. learner.linear_state)
StateFn = Callable[[Any, int], Dict[str, Any]]
# flat_fn(params) -> uint8 bytes for the param lane (same version as the commit)
FlatFn = Callable[[Any], np.ndarray]


class CheckpointPublisher:
    """Commit manifested checkpoints and push them through the gauntlet."""

    def __init__(
        self,
        *,
        ckpt_dir: str,
        authority: VersionAuthority,
        state_fn: StateFn,
        servers: Sequence[Any] = (),
        transport: Optional[Any] = None,  # LearnerTransport (param lane)
        flat_fn: Optional[FlatFn] = None,
        schedule: Optional[BridgeFaultSchedule] = None,
        boot_step: Optional[int] = None,
        on_event: Optional[Callable[[str, Dict[str, Any]], None]] = None,
    ) -> None:
        self.ckpt_dir = str(ckpt_dir)
        self.authority = authority
        self.state_fn = state_fn
        self.servers = list(servers)
        self.transport = transport
        self.flat_fn = flat_fn
        self._schedule = schedule
        self._on_event = on_event
        os.makedirs(self.ckpt_dir, exist_ok=True)
        if boot_step is None:
            # resume the step sequence from the newest committed checkpoint
            # already in the dir (shared discovery helper — satellite 1)
            from sheeprl_tpu.resilience.discovery import newest_committed

            newest = newest_committed(self.ckpt_dir)
            boot_step = newest.step if newest is not None else 0
        self._step = int(boot_step)

        self.attempts = 0
        self.committed = 0
        self.torn = 0
        self.poisoned = 0
        self.swaps_ok = 0
        self.swap_rejects = 0
        self.poisoned_steps: List[int] = []
        self.reject_reasons: List[str] = []

    @property
    def step(self) -> int:
        """The last step a publish attempt used."""
        return self._step

    def publish(self, params: Any, *, step: Optional[int] = None) -> Dict[str, Any]:
        """One publish attempt. Returns a result dict; never raises on a
        rejected swap (that is the gauntlet doing its job)."""
        self.attempts += 1
        fault = self._schedule.publish_fault(self.attempts) if self._schedule is not None else None
        kind = fault.kind if fault is not None else None
        self._step = int(step) if step is not None else self._step + 1
        this_step = self._step
        state = self.state_fn(params, this_step)

        if kind == "poison_publish":
            # poison BEFORE the manifest: the digest matches the poisoned
            # payload, so the checkpoint commits cleanly and only the
            # gauntlet's finiteness gate stands between it and the fleet
            self.poisoned += 1
            self.poisoned_steps.append(this_step)
            state = _poison_first_leaf(state)

        from sheeprl_tpu.resilience.manifest import build_manifest
        from sheeprl_tpu.utils.checkpoint import save_checkpoint

        path = os.path.join(self.ckpt_dir, f"ckpt_{this_step}_0.ckpt")
        if kind == "torn_publish":
            # payload without manifest: the crash-between-stage-and-commit
            # shape. Discovery skips it; no version is minted.
            self.torn += 1
            save_checkpoint(path, state, backend="pickle", manifest=None)
            trace_event("param_publish_torn", ckpt_step=this_step)
            self._event("publish_torn", step=this_step)
            return {"step": this_step, "version": None, "torn": True}

        man = build_manifest(step=this_step, backend="pickle", world_size=1, state=state)
        save_checkpoint(path, state, backend="pickle", manifest=man)
        version = self.authority.publish(this_step)
        self.committed += 1
        if self.transport is not None and self.flat_fn is not None:
            try:
                self.transport.publish_params(self.flat_fn(params), version)
            except Exception:
                pass  # the lane is advisory here; the checkpoint is the commit
        trace_event(
            "param_publish",
            version=version,
            ckpt_step=this_step,
            poisoned=kind == "poison_publish",
        )
        self._event("publish_committed", step=this_step, version=version)

        if kind == "learner_kill":
            # died after commit, before the swap push: the fleet never hears
            # about this checkpoint from us (its own swap watcher might)
            return {"step": this_step, "version": version, "killed": True}

        rejected: List[str] = []
        swapped = 0
        from sheeprl_tpu.serve.errors import SwapRejected

        for server in self.servers:
            try:
                server.request_swap(path)
                swapped += 1
                self.swaps_ok += 1
            except SwapRejected as err:
                self.swap_rejects += 1
                rejected.append(str(err))
                self.reject_reasons.append(str(err))
                trace_event("swap_rejected", version=version, ckpt_step=this_step, reason=str(err)[:200])
                self._event("swap_rejected", step=this_step, version=version)
        return {
            "step": this_step,
            "version": version,
            "path": path,
            "swapped": swapped,
            "rejected": len(rejected),
            "reject_reasons": rejected,
        }

    # ------------------------------------------------------------- reporting
    def _event(self, kind: str, **fields: Any) -> None:
        try:
            from sheeprl_tpu.obs.telemetry import telemetry_serve_event

            telemetry_serve_event(f"online_{kind}", **fields)
        except Exception:
            pass
        if self._on_event is not None:
            try:
                self._on_event(kind, fields)
            except Exception:
                pass

    def snapshot(self) -> Dict[str, Any]:
        return {
            "publish_attempts": self.attempts,
            "publish_committed": self.committed,
            "publish_torn": self.torn,
            "publish_poisoned": self.poisoned,
            "swaps_ok": self.swaps_ok,
            "swap_rejects": self.swap_rejects,
            "published_version": self.authority.published_version,
            "confirmed_version": self.authority.confirmed_version,
        }


def _poison_first_leaf(state: Dict[str, Any]) -> Dict[str, Any]:
    """Deep-copy ``state`` with one NaN planted in its first float leaf."""
    import copy

    poisoned = copy.deepcopy(state)
    stack: List[Any] = [poisoned]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            for key in sorted(node):
                value = node[key]
                if isinstance(value, np.ndarray) and np.issubdtype(value.dtype, np.floating):
                    arr = np.array(value)
                    arr.flat[0] = np.nan
                    node[key] = arr
                    return poisoned
                if isinstance(value, dict):
                    stack.append(value)
    return poisoned
