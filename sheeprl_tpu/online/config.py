"""Bridge configuration: the ``online`` config node.

Kept deliberately small — the bridge composes existing subsystems (serve,
actor_learner, net, resilience) and most behaviour lives in *their* config
nodes. What belongs here is only the glue the loop itself owns: slab
geometry, the client-side queue bound (the never-block-serving knob), the
staleness window for admission, the publish cadence, the hook budget, and
the bridge fault schedule.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Mapping

from sheeprl_tpu.online.fault_injection import BridgeFaultSpec, parse_bridge_faults


@dataclass(frozen=True)
class OnlineConfig:
    enabled: bool = False
    # slab geometry: rows of (obs, action, reward, target) per committed slab
    rows_per_slab: int = 64
    # trajectory-ring slots the bridge may write (the experience ring depth)
    ring_slots: int = 4
    # bounded client-side row queue between ServeClient taps and the
    # collector thread: when full, observe() sheds (counted) — the request
    # path NEVER blocks on the learning loop
    queue_bound: int = 512
    # staleness-bounded admission: a slab collected under version v is
    # admitted while published_version - v <= max_staleness (PR 11 doctrine)
    max_staleness: int = 2
    # learner updates between checkpoint publishes
    publish_every: int = 4
    # reward-hook wall budget; a call past it counts as a hang and sheds
    hook_timeout_s: float = 0.5
    # learner step size for the built-in feedback-regression train step
    lr: float = 0.1
    faults: List[BridgeFaultSpec] = field(default_factory=list)

    def __post_init__(self) -> None:
        if self.rows_per_slab < 1:
            raise ValueError(f"online.rows_per_slab must be >= 1, got {self.rows_per_slab}")
        if self.ring_slots < 1:
            raise ValueError(f"online.ring_slots must be >= 1, got {self.ring_slots}")
        if self.queue_bound < 1:
            raise ValueError(f"online.queue_bound must be >= 1, got {self.queue_bound}")
        if self.max_staleness < 0:
            raise ValueError(f"online.max_staleness must be >= 0, got {self.max_staleness}")
        if self.publish_every < 1:
            raise ValueError(f"online.publish_every must be >= 1, got {self.publish_every}")
        if self.hook_timeout_s <= 0:
            raise ValueError(f"online.hook_timeout_s must be > 0, got {self.hook_timeout_s}")


def online_config_from_cfg(cfg: Mapping[str, Any]) -> OnlineConfig:
    """Parse the ``online`` node out of a composed run config."""
    node = cfg.get("online") or {}
    if not hasattr(node, "get"):
        raise ValueError(f"online config node must be a mapping, got {node!r}")
    fault_node = (node.get("fault_injection") or {}).get("faults") if node.get("fault_injection") else None
    return OnlineConfig(
        enabled=bool(node.get("enabled", False)),
        rows_per_slab=int(node.get("rows_per_slab", 64)),
        ring_slots=int(node.get("ring_slots", 4)),
        queue_bound=int(node.get("queue_bound", 512)),
        max_staleness=int(node.get("max_staleness", 2)),
        publish_every=int(node.get("publish_every", 4)),
        hook_timeout_s=float(node.get("hook_timeout_s", 0.5)),
        lr=float(node.get("lr", 0.1)),
        faults=parse_bridge_faults(fault_node),
    )
