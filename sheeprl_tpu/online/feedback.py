"""The pluggable reward/feedback hook, guarded so it can never hurt serving.

The hook is user code — the one component of the loop the repo does not
control. It scores a served (obs, action) pair: a scalar reward, optionally
with a feedback *target* (the corrected action — the "user clicked the right
thing" label online systems actually learn from). User code fails in two
ways a drill must cover: it raises, and it hangs. :class:`GuardedHook`
contains both:

- the hook runs on a dedicated worker thread, never on the request path
  (the bridge collector calls the guard; ``ServeClient`` taps are a bounded
  enqueue and nothing more);
- every call carries a wall budget (``timeout_s``). A call past budget is
  counted as a hang, its experience row is shed, the stuck worker is
  abandoned (it exits on its own once the stall clears — generation-checked,
  so an abandoned worker can never deliver a stale result into a new call)
  and a fresh worker takes over;
- an exception inside the hook is counted and sheds that row; the guard
  itself never raises.

Scheduled ``hook_exception`` / ``hook_hang`` faults (the ``online`` fault
domain) are injected *around* the user hook inside the worker, so the drills
exercise the exact production guard path.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, NamedTuple, Optional

import numpy as np

from sheeprl_tpu.online.fault_injection import BridgeFaultSchedule


class Feedback(NamedTuple):
    """One scored experience row. ``target`` is the optional corrected
    action the learner regresses toward; reward-only hooks leave it None."""

    reward: float
    target: Optional[np.ndarray] = None


def _normalize(result: Any) -> Feedback:
    if isinstance(result, Feedback):
        return result
    if isinstance(result, tuple) and len(result) == 2:
        return Feedback(float(result[0]), None if result[1] is None else np.asarray(result[1]))
    return Feedback(float(result), None)


class HookError(RuntimeError):
    """A scheduled ``hook_exception`` fault firing (distinguishable in logs
    from an organic hook failure)."""


class GuardedHook:
    """Budgeted, fault-drilled wrapper around a user reward hook.

    Single-caller by design (the bridge collector thread); the counters are
    plain attributes under that contract. ``__call__`` returns the
    normalized :class:`Feedback` or ``None`` when the row must be shed
    (error, hang, or shutdown)."""

    def __init__(
        self,
        hook: Callable[[Any, Any], Any],
        *,
        timeout_s: float = 0.5,
        schedule: Optional[BridgeFaultSchedule] = None,
        on_event: Optional[Callable[[str, dict], None]] = None,
    ) -> None:
        self._hook = hook
        self.timeout_s = float(timeout_s)
        self._schedule = schedule
        self._on_event = on_event
        self.calls = 0
        self.errors = 0
        self.hangs = 0
        self._generation = 0
        self._inbox: Optional[queue.Queue] = None
        self._closed = False

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        self._closed = True
        self._generation += 1  # any in-flight worker exits after its item
        if self._inbox is not None:
            try:
                self._inbox.put_nowait(None)
            except queue.Full:
                pass
            self._inbox = None

    # ----------------------------------------------------------------- call
    def __call__(self, obs: Any, action: Any) -> Optional[Feedback]:
        if self._closed:
            return None
        row = self.calls
        self.calls += 1
        faults = self._schedule.hook_faults(row) if self._schedule is not None else []
        if self._inbox is None:
            self._spawn()
        out_q: "queue.Queue" = queue.Queue(maxsize=1)
        self._inbox.put((obs, action, faults, out_q))
        try:
            status, payload = out_q.get(timeout=self.timeout_s)
        except queue.Empty:
            # hang: abandon this worker (generation bump makes it exit once
            # the stall clears) and shed the row
            self.hangs += 1
            self._generation += 1
            self._inbox = None
            self._event("hook_hang", row=row, timeout_s=self.timeout_s)
            return None
        if status == "error":
            self.errors += 1
            self._event("hook_error", row=row, error=repr(payload))
            return None
        return payload

    # ------------------------------------------------------------- internal
    def _spawn(self) -> None:
        self._generation += 1
        gen = self._generation
        inbox: "queue.Queue" = queue.Queue()
        self._inbox = inbox

        def run() -> None:
            while self._generation == gen:
                try:
                    item = inbox.get(timeout=0.2)
                except queue.Empty:
                    continue
                if item is None:
                    return
                obs, action, faults, out_q = item
                try:
                    for fault in faults:
                        if fault.kind == "hook_hang":
                            time.sleep(fault.duration_s)
                        elif fault.kind == "hook_exception":
                            raise HookError(f"scheduled hook_exception at row {self.calls - 1}")
                    result = ("ok", _normalize(self._hook(obs, action)))
                except Exception as err:
                    result = ("error", err)
                try:
                    # an abandoned worker's result goes nowhere: the caller
                    # timed out and will never read out_q (bounded, size 1)
                    out_q.put_nowait(result)
                except queue.Full:
                    pass

        threading.Thread(target=run, name="online-hook", daemon=True).start()

    def _event(self, kind: str, **fields: Any) -> None:
        if self._on_event is not None:
            try:
                self._on_event(kind, fields)
            except Exception:
                pass

    def snapshot(self) -> dict:
        return {"hook_calls": self.calls, "hook_errors": self.errors, "hook_hangs": self.hangs}
