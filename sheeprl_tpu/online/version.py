"""The single monotonic policy-version authority for the closed loop.

Before the bridge, two counters described "which policy": the param lane's
``version`` (bumped per learner update, PR 11) and the checkpoint ``step``
the serving gauntlet promotes (PR 6). A trajectory tagged with one and a
server reporting the other cannot be joined — exactly the ambiguity an
online loop cannot afford, because staleness-bounded admission compares the
version a slab was *collected under* against the version the learner has
*published*.

:class:`VersionAuthority` collapses both into one monotone counter:

- ``publish(step)`` — the learner committed checkpoint ``step``; mints the
  next version and records the ``step → version`` mapping. The same version
  number goes onto the param lane (``publish_params(..., version)``) and
  into the publish trace event.
- ``version_for_step(step)`` — what the bridge stamps into slab metadata:
  requests carry the checkpoint step their replica served under
  (``Request.served_step``), and this maps it back to the lane's counter.
- ``confirm(step)`` — ``ModelStore.try_swap`` promoted ``step`` into the
  serving flip; the authority tracks the last *validated* version so drills
  can assert "the fleet serves the last validated version indefinitely"
  after a learner death or a rejected publish.

Thread-safe: the learner thread publishes while replica threads stamp and
swap watchers confirm.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


class VersionAuthority:
    """Monotonic policy-version counter shared by the param lane and the
    hot-swap gauntlet. ``boot_step`` registers the checkpoint the fleet is
    serving at construction as version 0 (already validated: it booted)."""

    def __init__(self, *, boot_step: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._version = 0
        self._step_to_version: Dict[int, int] = {}
        self._version_to_step: Dict[int, int] = {}
        self._confirmed_version = 0
        self._confirmed_step = boot_step
        if boot_step is not None:
            self._step_to_version[int(boot_step)] = 0
            self._version_to_step[0] = int(boot_step)

    # ------------------------------------------------------------- publish ----
    def publish(self, step: int) -> int:
        """Mint the next version for checkpoint ``step`` (the learner's
        commit path). Idempotent per step: republishing a step returns its
        existing version instead of burning a new one."""
        step = int(step)
        with self._lock:
            existing = self._step_to_version.get(step)
            if existing is not None:
                return existing
            self._version += 1
            self._step_to_version[step] = self._version
            self._version_to_step[self._version] = step
            return self._version

    def confirm(self, step: int) -> Optional[int]:
        """A swap promoted checkpoint ``step`` into serving. Returns the
        confirmed version (``None`` for a step this authority never minted —
        a foreign checkpoint, recorded as confirmed step only)."""
        step = int(step)
        with self._lock:
            version = self._step_to_version.get(step)
            if version is not None and version > self._confirmed_version:
                self._confirmed_version = version
            self._confirmed_step = step
            return version

    # -------------------------------------------------------------- lookup ----
    def version_for_step(self, step: Any) -> int:
        """The version whose checkpoint is ``step`` (what produced a served
        action). Unknown steps map to 0 — the boot policy — so a request
        served before the authority saw its step is stamped conservatively
        old rather than invented new."""
        try:
            step = int(step)
        except (TypeError, ValueError):
            return 0
        with self._lock:
            return self._step_to_version.get(step, 0)

    def step_for_version(self, version: int) -> Optional[int]:
        with self._lock:
            return self._version_to_step.get(int(version))

    @property
    def published_version(self) -> int:
        """Newest version the learner has published (the admission bound)."""
        with self._lock:
            return self._version

    @property
    def confirmed_version(self) -> int:
        """Newest version validated into serving by the gauntlet."""
        with self._lock:
            return self._confirmed_version

    @property
    def confirmed_step(self) -> Optional[int]:
        with self._lock:
            return self._confirmed_step

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "published_version": self._version,
                "confirmed_version": self._confirmed_version,
                "confirmed_step": self._confirmed_step,
                "known_steps": len(self._step_to_version),
            }
