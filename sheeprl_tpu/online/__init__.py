"""Online-learning bridge: the serving fleet as a data source, the learner
as a checkpoint publisher (ROADMAP open item 2 — the closed production loop).

The package fuses the two halves the repo already has:

- **serve → learn** — :class:`~sheeprl_tpu.online.bridge.ExperienceBridge`
  assembles served requests (obs, action, the exact policy version that
  produced them, and a reward/feedback label from a pluggable hook) into
  version-tagged experience slabs and writes them through the PR 11
  trajectory-ring writer protocol (shm or TCP — any
  :class:`~sheeprl_tpu.net.transport.ActorTransport`).
- **learn → serve** — :class:`~sheeprl_tpu.online.learner.OnlineLearner`
  trains continuously under the existing staleness-bounded admission and
  :class:`~sheeprl_tpu.online.publisher.CheckpointPublisher` commits
  manifested checkpoints and pushes them through the PR 6 hot-swap
  validation gauntlet into every replica.
- **one version authority** —
  :class:`~sheeprl_tpu.online.version.VersionAuthority` is the single
  monotonic counter shared by the param lane and ``ModelStore.try_swap``,
  so each trajectory records exactly which policy produced it.

The robustness doctrine (howto/online_learning.md): every fault on the
learning side — degraded checkpoint publish, reward-hook exception/hang,
ring-full backpressure, learner death — degrades the *learning* loop
(counted, telemetered shedding) while the serving SLO never blips.
"""

from sheeprl_tpu.online.bridge import ExperienceBridge, build_experience_layout
from sheeprl_tpu.online.config import OnlineConfig, online_config_from_cfg
from sheeprl_tpu.online.fault_injection import BridgeFaultSchedule, BridgeFaultSpec, parse_bridge_faults
from sheeprl_tpu.online.feedback import Feedback, GuardedHook
from sheeprl_tpu.online.learner import OnlineLearner, linear_feedback_train_step
from sheeprl_tpu.online.publisher import CheckpointPublisher
from sheeprl_tpu.online.version import VersionAuthority

__all__ = [
    "BridgeFaultSchedule",
    "BridgeFaultSpec",
    "CheckpointPublisher",
    "ExperienceBridge",
    "Feedback",
    "GuardedHook",
    "OnlineConfig",
    "OnlineLearner",
    "VersionAuthority",
    "build_experience_layout",
    "linear_feedback_train_step",
    "online_config_from_cfg",
    "parse_bridge_faults",
]
