"""Continuous batching: a per-replica slot pool replacing the gather window.

The PR 6 micro-batcher coalesced requests with a time window: the first
waiting request opened a ``gather_window_s`` gate and the batch closed when
the window elapsed. That buys batch occupancy with latency — every request
pays up to one window of dead air even on an idle server.

The slot pool is the vLLM-style alternative, trivial for single-step
policies because there is no per-request generation state to keep resident:
the replica's in-flight batch is a window of ``capacity`` *slots* (capacity
= the top AOT ladder rung), and a request is admitted into any free slot at
any time — including while the previous dispatch is still running on device.
The replica loop runs back-to-back dispatches over whatever slots are
occupied; a lone request rides the very next dispatch with zero gather
latency, and a saturated replica runs full rungs continuously. Requests past
the slot window wait in a bounded FIFO *backlog* that refills slots as
dispatches free them.

Two properties the fleet's robustness contract leans on:

- **admission order is dispatch order** — slots and backlog are FIFO, every
  occupied slot rides the next dispatch, and ``offer(front=True)`` (the
  crash re-route path) inserts ahead of the backlog, so an admitted request
  is never reordered behind later admissions (asserted by the ordering
  property test).
- **expiry only by a request's own deadline** — a request is completed
  exceptionally when *its* deadline passes (at dispatch assembly, exactly
  like the micro-batcher), never because a crash elsewhere re-routed it.

Observation staging is slot-resident: each pool preallocates buffer rows
per observation leaf (2x the slot window — the occupied slots and the
in-flight batch hold rows at the same time) and admission writes the
request's obs into its row immediately — batch assembly on the dispatch
path is one vectorized row-gather instead of a per-request stacking loop.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.obs.trace import trace_event
from sheeprl_tpu.serve.batching import Request
from sheeprl_tpu.serve.errors import ServerClosed


def safe_complete(req: Request, out: Any) -> bool:
    """Set ``req``'s result unless something else (a hedge twin, an expiry)
    beat us to it. Returns True when this call delivered the result."""
    if req.future.done():
        return False
    try:
        req.future.set_result(out)
        return True
    except Exception:  # InvalidStateError: lost the race to the hedge twin
        return False


class SlotPool:
    """One replica's continuous-batching window: ``capacity`` slots fed by a
    bounded FIFO backlog.

    ``on_expired(request)`` fires (outside the lock) for every request this
    pool completes exceptionally at dispatch assembly.
    """

    def __init__(
        self,
        *,
        capacity: int,
        backlog_bound: int,
        obs_spec: Any = None,
        clock: Callable[[], float] = time.monotonic,
        on_expired: Optional[Callable[[Request], None]] = None,
    ) -> None:
        if capacity < 1:
            raise ValueError(f"slot pool capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.backlog_bound = int(backlog_bound)
        self._clock = clock
        self._on_expired = on_expired
        self._cond = threading.Condition()
        self._waiting: Deque[Request] = deque()  # occupied slots, admission order
        self._backlog: Deque[Request] = deque()
        # rid -> request, insertion-ordered. Keyed (not a plain list) so
        # release is ownership-checked per dispatch: a stale replica
        # incarnation whose window was already drained/re-routed releases
        # nothing, and can never clobber the live incarnation's tracking.
        self._inflight: Dict[int, Request] = {}
        self._closed = False
        # slot-resident obs staging. Rows must cover the occupied slot window
        # AND the in-flight batch at once — continuous batching admits into
        # slots while the previous dispatch still holds its rows — so the
        # buffer carries 2 * capacity rows (waiting <= capacity, in-flight
        # <= capacity, nothing else stages).
        self._spec = obs_spec
        self._buffers: Optional[List[np.ndarray]] = None
        self._leaf_paths: Optional[List[Any]] = None
        self._rows: Dict[int, int] = {}  # rid -> staged slot row
        self._free_rows: List[int] = list(range(2 * self.capacity))
        if obs_spec is not None:
            import jax

            leaves = jax.tree.leaves(obs_spec)
            self._buffers = [
                np.zeros((2 * self.capacity,) + tuple(s.shape), dtype=s.dtype) for s in leaves
            ]

    # ------------------------------------------------------------- admission
    def offer(self, req: Request, *, front: bool = False) -> bool:
        """Place ``req`` into a free slot (else the backlog). Returns False
        when slots and backlog are both full — the caller (router) owns the
        fleet-wide admission decision, this is per-replica capacity only.
        ``front=True`` is the re-route path: the request was admitted before
        anything now waiting here, so it goes ahead of the backlog (or into
        the head of the slot window when one is free)."""
        with self._cond:
            if self._closed:
                raise ServerClosed("slot pool is shut down")
            if len(self._waiting) < self.capacity:
                self._stage(req)
                if front:
                    self._waiting.appendleft(req)
                else:
                    self._waiting.append(req)
                self._cond.notify()
                return True
            if len(self._backlog) >= self.backlog_bound:
                return False
            if front:
                self._backlog.appendleft(req)
            else:
                self._backlog.append(req)
            return True

    # -------------------------------------------------------------- dispatch
    def take_batch(self, wait_timeout_s: float) -> List[Request]:
        """Block up to ``wait_timeout_s`` for at least one occupied slot,
        then take the whole occupied window (admission order) as the next
        dispatch, refilling slots from the backlog. ``[]`` on timeout/close
        so replica loops can heartbeat. Expired requests are completed
        exceptionally here — by their own deadline — and never dispatched."""
        expired: List[Request] = []
        batch: List[Request] = []
        dropped: List[Request] = []
        with self._cond:
            deadline = self._clock() + wait_timeout_s
            while not self._waiting and not self._closed:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)
            if self._closed and not self._waiting:
                return []
            now = self._clock()
            while self._waiting:
                req = self._waiting.popleft()
                if req.future.done():  # hedge twin won, or already expired
                    self._unstage(req)
                    dropped.append(req)
                    continue
                (expired if req.expired(now) else batch).append(req)
            for req in expired:
                self._unstage(req)
            for req in batch:
                self._inflight[req.rid] = req
                # first-dispatch stamp for the critical-path decomposition
                # (queue wait = enqueue → here); a hedge twin keeps the
                # winner's first stamp, shared via the request object
                if req.t_dispatch is None:
                    req.t_dispatch = now
            self._refill_locked()
        now = self._clock()
        for req in dropped:
            # a done-skipped request here is a cancelled hedge loser (an
            # expired one was completed by its winner/expiry path already):
            # mark the loser's copy on the timeline, outside the lock
            if req.trace_id and not req.future.exception():
                trace_event("request_hedge_drop", req.trace_id, rid=req.rid)
        for req in expired:
            req.fail_expired(now)
            if self._on_expired is not None:
                try:
                    self._on_expired(req)
                except Exception:
                    pass
        return batch

    def complete_batch(self, batch: Sequence[Request]) -> None:
        """Release ``batch``'s slice of the in-flight window (called by the
        replica after the dispatch's futures are settled) and free its staged
        rows. Only requests this pool still tracks in-flight are released: a
        stale incarnation — declared hung, its window drained and re-routed,
        then waking late — releases nothing that now belongs to the live
        incarnation."""
        with self._cond:
            for req in batch:
                if self._inflight.pop(req.rid, None) is not None:
                    self._unstage(req)
            self._refill_locked()

    def staged_batch(self, batch: Sequence[Request], rung: int) -> Any:
        """Assemble the padded obs batch for ``batch`` at ladder rung
        ``rung`` from the slot-resident staging rows (one vectorized gather
        per leaf); falls back to request-held obs when staging is off."""
        if self._buffers is None or self._spec is None:
            from sheeprl_tpu.serve.model import stack_obs

            return stack_obs(self._spec, [r.obs for r in batch], rung)
        import jax

        with self._cond:
            # stage-on-demand backstop: a request can only be row-less here if
            # the 2x-capacity invariant was violated; never fail a dispatch
            # over it (the request still holds its obs).
            for req in batch:
                if req.rid not in self._rows:
                    self._stage(req)
            rows = [self._rows.get(r.rid) for r in batch]
        leaves = []
        for li, buf in enumerate(self._buffers):
            out = np.zeros((rung,) + buf.shape[1:], dtype=buf.dtype)
            if None not in rows:
                out[: len(rows)] = buf[rows]
            else:
                for i, (req, row) in enumerate(zip(batch, rows)):
                    if row is not None:
                        out[i] = buf[row]
                    else:
                        out[i] = np.asarray(jax.tree.leaves(req.obs)[li], dtype=buf.dtype)
            leaves.append(out)
        treedef = jax.tree.structure(self._spec)
        return jax.tree.unflatten(treedef, leaves)

    # ------------------------------------------------------------ re-routing
    def offer_front(self, reqs: Sequence[Request]) -> None:
        """Plant an ordered block of already-admitted requests AHEAD of this
        pool's backlog (the re-route-at-front path). Bypasses the backlog
        bound for the same reason the micro-batcher's ``requeue`` bypassed
        admission: these requests were admitted once — a fleet event they
        didn't cause must not shed them. Relative order is preserved; they
        ride the next dispatches as slots free up."""
        with self._cond:
            if self._closed:
                raise ServerClosed("slot pool is shut down")
            for req in reversed(reqs):
                if not req.future.done():
                    self._backlog.appendleft(req)
            self._refill_locked()

    def requeue_failed(self, batch: Sequence[Request]) -> None:
        """Hand a failed dispatch back to this pool at the front (the
        single-replica inference-failure retry; the batch has waited
        longest). Releases the batch's in-flight slice, so call INSTEAD of
        ``complete_batch``. Same ownership check: requests a drain already
        re-routed are not requeued here — they ride their sibling."""
        with self._cond:
            owned = [r for r in batch if self._inflight.pop(r.rid, None) is not None]
            for req in owned:
                self._unstage(req)
            if not self._closed:
                for req in reversed(owned):
                    if not req.future.done():
                        self._backlog.appendleft(req)
            self._refill_locked()

    def drain(self, *, inflight: str = "all") -> List[Request]:
        """Pull every request this pool still owes work for — the in-flight
        window first (it has waited longest), then occupied slots, then the
        backlog, preserving admission order within each — so a dead replica's
        work can be re-routed at the FRONT of a sibling. The pool stays open
        (a restarted incarnation reuses it).

        ``inflight`` scopes the window when the replica thread may still be
        executing it: ``"all"`` (the replica is confirmed dead — nothing else
        will ever complete these), ``"idempotent"`` (hung but alive: re-home
        only what is safe to run twice, first completion wins exactly like a
        hedge; non-idempotent requests stay with their original executor),
        ``"none"`` (healthy and retiring: it finishes its own window)."""
        with self._cond:
            drained: List[Request] = []
            if inflight != "none":
                for req in list(self._inflight.values()):
                    if inflight == "idempotent" and not getattr(req, "idempotent", True):
                        continue
                    del self._inflight[req.rid]
                    self._unstage(req)
                    if not req.future.done():
                        drained.append(req)
            drained += [r for r in self._waiting if not r.future.done()]
            drained += [r for r in self._backlog if not r.future.done()]
            for req in list(self._waiting):
                self._unstage(req)
            self._waiting.clear()
            self._backlog.clear()
        return drained

    # ------------------------------------------------------------ inspection
    def depth(self) -> int:
        """Queued work (occupied slots + backlog), the autoscale signal."""
        with self._cond:
            return len(self._waiting) + len(self._backlog)

    def outstanding(self) -> int:
        """Everything this pool owes an answer for (queued + in flight), the
        router's load score."""
        with self._cond:
            return len(self._waiting) + len(self._backlog) + len(self._inflight)

    @property
    def closed(self) -> bool:
        return self._closed

    # -------------------------------------------------------------- lifecycle
    def close(self) -> None:
        """Stop admitting; fail everything still queued with ServerClosed."""
        with self._cond:
            self._closed = True
            pending = list(self._waiting) + list(self._backlog)
            self._waiting.clear()
            self._backlog.clear()
            self._rows.clear()
            self._free_rows = list(range(2 * self.capacity))
            self._cond.notify_all()
        for req in pending:
            if not req.future.done():
                try:
                    req.future.set_exception(ServerClosed("slot pool is shut down"))
                except Exception:
                    pass

    # -------------------------------------------------------------- internal
    def _refill_locked(self) -> None:
        while self._backlog and len(self._waiting) < self.capacity:
            req = self._backlog.popleft()
            if req.future.done():
                continue
            self._stage(req)
            self._waiting.append(req)
        if self._waiting:
            self._cond.notify()

    def _stage(self, req: Request) -> None:
        if self._buffers is None:
            return
        if req.rid in self._rows or not self._free_rows:
            return
        import jax

        row = self._free_rows.pop()
        self._rows[req.rid] = row
        for buf, leaf in zip(self._buffers, jax.tree.leaves(req.obs)):
            buf[row] = np.asarray(leaf, dtype=buf.dtype)

    def _unstage(self, req: Request) -> None:
        if self._buffers is None:
            return
        row = self._rows.pop(req.rid, None)
        if row is not None:
            self._free_rows.append(row)
