"""The policy server: AOT warmup, replica set, stats, hot-swap watcher.

:class:`PolicyServer` composes the serving tier:

- **warmup-before-traffic** — ``start()`` AOT-compiles every ladder rung
  (:class:`~sheeprl_tpu.serve.model.CompiledLadder`) *before* any replica is
  spawned; by the time ``infer`` can enqueue anything, every batch shape the
  server will ever run is already compiled. ``submit`` before ``start``
  raises :class:`ServerClosed`.
- **request path** — ``infer(obs)`` = admission-controlled enqueue + wait on
  the request's Future, bounded by the request deadline (an unserved request
  — e.g. every replica masked — fails as :class:`DeadlineExceeded`, never
  hangs).
- **stats** — one :class:`ServeStats` aggregates counters (submitted /
  completed / shed / failed / restarts / swaps) and an end-to-end latency
  reservoir for p50/p95, snapshotted by ``stats()`` for telemetry and bench.
- **hot swap** — with ``swap_poll_s > 0`` a watcher thread scans the
  checkpoint dir for newer *committed* manifests and promotes them through
  the :class:`~sheeprl_tpu.serve.model.ModelStore` validation gauntlet;
  ``request_swap`` does the same on demand and raises on rejection.
"""

from __future__ import annotations

import math
import threading
import time
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Any, Callable, Dict, List, Optional

from sheeprl_tpu.resilience.manifest import CommittedCheckpoint, read_manifest
from sheeprl_tpu.serve.batching import MicroBatcher, Request
from sheeprl_tpu.serve.config import ServeConfig
from sheeprl_tpu.serve.errors import DeadlineExceeded, ServerClosed, SwapRejected
from sheeprl_tpu.serve.fault_injection import ServeFaultSchedule
from sheeprl_tpu.serve.model import CompiledLadder, ModelStore, ModelVersion, ServedPolicy
from sheeprl_tpu.serve.supervisor import ReplicaSet


class ServeStats:
    """Thread-safe serving counters + a bounded end-to-end latency reservoir."""

    RESERVOIR = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.started_t: Optional[float] = None
        self.submitted = 0
        self.completed = 0
        self.shed_overloaded = 0
        self.shed_expired = 0
        self.failed = 0
        self.batches = 0
        self.batched_requests = 0
        self._latencies: List[float] = []  # ring buffer of end-to-end seconds
        self._lat_pos = 0
        self.events: Dict[str, int] = {}  # supervision/swap event counts by kind

    def mark_started(self) -> None:
        with self._lock:
            self.started_t = time.monotonic()

    def record_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def record_complete(self, latency_s: float) -> None:
        with self._lock:
            self.completed += 1
            if len(self._latencies) < self.RESERVOIR:
                self._latencies.append(latency_s)
            else:
                self._latencies[self._lat_pos] = latency_s
                self._lat_pos = (self._lat_pos + 1) % self.RESERVOIR
    def record_failed(self) -> None:
        with self._lock:
            self.failed += 1

    def record_shed(self, kind: str) -> None:
        with self._lock:
            if kind == "overloaded":
                self.shed_overloaded += 1
            else:
                self.shed_expired += 1

    def record_batch(self, size: int, latency_s: float) -> None:
        with self._lock:
            self.batches += 1
            self.batched_requests += size

    def record_event(self, kind: str) -> None:
        with self._lock:
            self.events[kind] = self.events.get(kind, 0) + 1

    def percentile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._latencies:
                return None
            ordered = sorted(self._latencies)
        idx = min(len(ordered) - 1, max(0, math.ceil(q * len(ordered)) - 1))
        return ordered[idx]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            elapsed = time.monotonic() - self.started_t if self.started_t is not None else 0.0
            snap: Dict[str, Any] = {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed_overloaded": self.shed_overloaded,
                "shed_expired": self.shed_expired,
                "failed": self.failed,
                "batches": self.batches,
                "mean_batch": (self.batched_requests / self.batches) if self.batches else 0.0,
                "uptime_s": elapsed,
                "qps": (self.completed / elapsed) if elapsed > 0 else 0.0,
                "events": dict(self.events),
            }
        for name, q in (("p50_ms", 0.50), ("p95_ms", 0.95)):
            p = self.percentile(q)
            snap[name] = (p * 1e3) if p is not None else None
        return snap


class PolicyServer:
    """The serving facade the CLI, tests and load generator talk to."""

    def __init__(
        self,
        policy: ServedPolicy,
        config: ServeConfig,
        *,
        step: int,
        path: str,
        ckpt_dir: Optional[str] = None,
        on_event: Optional[Callable[[str, Dict[str, Any]], None]] = None,
    ) -> None:
        self.config = config
        self.policy = policy
        self.step = int(step)
        self.path = str(path)
        self.ckpt_dir = ckpt_dir
        self._on_event = on_event
        self.stats = ServeStats()
        self.fault_schedule = ServeFaultSchedule(config.faults) if config.faults else None
        self.batcher = MicroBatcher(
            max_queue=config.max_queue,
            gather_window_s=config.gather_window_s,
            on_shed=self.stats.record_shed,
        )
        self.ladder: Optional[CompiledLadder] = None
        self.store: Optional[ModelStore] = None
        self.replicas: Optional[ReplicaSet] = None
        self.aot_cache: Optional[Any] = None
        self._swap_thread: Optional[threading.Thread] = None
        self._closing = threading.Event()
        self._started = False
        self.warmup_s: Dict[int, float] = {}

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "PolicyServer":
        """AOT-warm the ladder, then open for traffic. Blocking: when this
        returns every rung is compiled and all replicas are pulling."""
        if self._started:
            return self
        from sheeprl_tpu.obs import telemetry_deliberate_compiles

        if self.config.aot_cache_dir:
            from sheeprl_tpu.ops.aotcache import AotCache

            self.aot_cache = AotCache(self.config.aot_cache_dir)
        # the batch-ladder AOT warmup IS compilation — allowlist it so a
        # serve session that configured telemetry (and is already warm from
        # a shared-process drill) doesn't spray RecompileWarnings; with an
        # executable cache, hits never lower and the window stays idle
        with telemetry_deliberate_compiles("serve_batch_ladder"):
            self.ladder = CompiledLadder(self.policy, self.config.batch_ladder, aot_cache=self.aot_cache)
        self.warmup_s = dict(self.ladder.compile_s)
        self.store = ModelStore(
            self.policy,
            self.ladder,
            step=self.step,
            path=self.path,
            fault_schedule=self.fault_schedule,
            on_event=self._event,
        )
        self.replicas = ReplicaSet(
            self.config,
            batcher=self.batcher,
            store=self.store,
            fault_schedule=self.fault_schedule,
            on_event=self._event,
            on_batch=self.stats.record_batch,
        )
        self.replicas.start()
        if self.config.swap_poll_s > 0 and self.ckpt_dir:
            self._swap_thread = threading.Thread(
                target=self._swap_watch, name="serve-swap-watch", daemon=True
            )
            self._swap_thread.start()
        self.stats.mark_started()
        self._started = True
        return self

    def close(self) -> None:
        self._closing.set()
        self.batcher.close()
        if self.replicas is not None:
            self.replicas.close()
        if self._swap_thread is not None:
            self._swap_thread.join(1.0)
        if self.aot_cache is not None:
            # drains queued executable stores (writer thread joins) so the
            # next boot of this cache dir sees everything this one compiled
            self.aot_cache.close()

    def __enter__(self) -> "PolicyServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # ------------------------------------------------------------ request path
    def submit(self, obs: Any, deadline_s: Optional[float] = None) -> Request:
        """Admit a request (or raise Overloaded/ServerClosed immediately)."""
        if not self._started:
            raise ServerClosed("server not started: warmup has not run")
        self.stats.record_submit()
        try:
            return self.batcher.submit(obs, deadline_s or self.config.default_deadline_s)
        except Exception:
            self.stats.record_failed()
            raise

    def infer(self, obs: Any, deadline_s: Optional[float] = None) -> Any:
        """Blocking single-request inference, bounded by the deadline."""
        deadline_s = deadline_s or self.config.default_deadline_s
        req = self.submit(obs, deadline_s)
        return self.wait(req)

    def wait(self, req: Request) -> Any:
        """Wait out a submitted request. Bounded: even with zero live
        replicas this fails by the request's own deadline."""
        budget = max(0.0, req.deadline_t - time.monotonic()) + 0.25
        try:
            out = req.future.result(timeout=budget)
        except DeadlineExceeded:
            self.stats.record_failed()
            raise
        except (TimeoutError, FutureTimeout):
            self.stats.record_failed()
            now = time.monotonic()
            raise DeadlineExceeded(now - req.enqueue_t, req.deadline_t - req.enqueue_t) from None
        except Exception:
            self.stats.record_failed()
            raise
        self.stats.record_complete(time.monotonic() - req.enqueue_t)
        return out

    # ------------------------------------------------------------------- swap
    def request_swap(self, ckpt_path: str) -> ModelVersion:
        """Promote ``ckpt_path`` now; raises :class:`SwapRejected` if it does
        not survive validation (torn/uncommitted, digest mismatch, structure
        change, poisoned weights)."""
        if self.store is None:
            raise ServerClosed("server not started")
        man = read_manifest(ckpt_path)
        if man is None:
            raise SwapRejected(f"checkpoint {ckpt_path} has no commit manifest (torn or foreign write)")
        return self.store.request_swap(CommittedCheckpoint(int(man["step"]), ckpt_path, man))

    def maybe_swap(self) -> Optional[ModelVersion]:
        """One scan-and-maybe-promote pass over ``ckpt_dir`` (what the
        watcher thread runs on its poll cadence)."""
        if self.store is None or not self.ckpt_dir:
            return None
        return self.store.maybe_swap_newest(self.ckpt_dir)

    def _swap_watch(self) -> None:
        while not self._closing.wait(self.config.swap_poll_s):
            try:
                self.maybe_swap()
            except Exception:
                pass  # the watcher must outlive any one bad scan

    # ------------------------------------------------------------------ stats
    def snapshot(self) -> Dict[str, Any]:
        snap = self.stats.snapshot()
        snap["queue_depth"] = self.batcher.depth()
        snap["slo_ms"] = self.config.slo_ms
        snap["batch_ladder"] = list(self.config.batch_ladder)
        snap["warmup_s"] = dict(self.warmup_s)
        if self.ladder is not None and self.aot_cache is not None:
            snap["ladder_from_cache"] = dict(self.ladder.from_cache)
            snap["aot_cache"] = self.aot_cache.stats()
        if self.replicas is not None:
            snap["replicas_alive"] = self.replicas.alive_count
            snap["replicas_masked"] = self.replicas.masked_count
            snap["restarts"] = self.replicas.total_restarts
            snap["degraded"] = self.replicas.degraded
        if self.store is not None:
            snap["serving_step"] = self.store.current.step
            snap["swaps"] = self.store.swaps
            snap["swap_rejects"] = self.store.swap_rejects
            snap["rollbacks"] = self.store.rollbacks
        return snap

    def _event(self, kind: str, info: Dict[str, Any]) -> None:
        self.stats.record_event(kind)
        if self._on_event is not None:
            try:
                self._on_event(kind, info)
            except Exception:
                pass
