"""Supervision of the replica set: restart-with-budget, masking, degraded mode.

Same supervision doctrine as the rollout pool (``rollout/supervisor.py``),
re-instantiated for threads instead of processes:

- **detect** — a replica is *dead* when its thread has exited (crash fault,
  circuit breaker, organic exception) and *hung* when its heartbeat is older
  than ``replica_timeout_s``. Hung threads cannot be killed in Python; they
  are abandoned (stop-flagged so they exit if they ever wake) and replaced,
  which is the same observable outcome.
- **restart under budget** — each slot owns a
  :class:`~sheeprl_tpu.rollout.supervisor.RestartBudget` (max_restarts with
  healthy-window refunds), restarts are scheduled with exponential backoff
  and executed by the monitor loop without blocking it.
- **mask, don't crash** — a slot whose budget is exhausted is masked: the
  server keeps serving on N-1 (degraded mode, visible in stats) rather than
  dying because one replica is beyond saving. With ALL slots masked the
  server stays up and requests fail by their own deadlines — the typed
  failure a client can reason about.

The monitor is one thread with a short interval; every decision it makes is
also re-derivable from the slot state it records (restarts, masks, reasons),
which is what the fault-drill tests assert against.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from sheeprl_tpu.rollout.supervisor import RestartBudget
from sheeprl_tpu.serve.batching import MicroBatcher
from sheeprl_tpu.serve.config import ServeConfig
from sheeprl_tpu.serve.fault_injection import ServeFaultSchedule
from sheeprl_tpu.serve.model import ModelStore
from sheeprl_tpu.serve.replica import Replica, ReplicaStats


class ReplicaSlot:
    """One supervised serving slot. The slot (not the thread) owns the batch
    counter and the restart budget so both survive replica incarnations."""

    def __init__(self, index: int, config: ServeConfig) -> None:
        self.index = index
        self.batch_counter = itertools.count()
        self.budget = RestartBudget(config.max_restarts, config.restart_refund_s)
        self.thread: Optional[Replica] = None
        self.stats: Optional[ReplicaStats] = None
        self.restarts = 0  # lifetime total (telemetry; budget may refund)
        self.masked = False
        self.mask_reason: Optional[str] = None
        self.restart_at: Optional[float] = None  # pending backoff-scheduled restart
        self.total_requests = 0
        self.total_failures = 0

    @property
    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def fold_stats(self) -> None:
        """Accumulate the dying incarnation's counters into slot totals."""
        if self.stats is not None:
            self.total_requests += self.stats.requests
            self.total_failures += self.stats.failures


class ReplicaSet:
    """The supervised pool of serving replicas over one shared queue/model."""

    def __init__(
        self,
        config: ServeConfig,
        *,
        batcher: MicroBatcher,
        store: ModelStore,
        fault_schedule: Optional[ServeFaultSchedule] = None,
        on_event: Optional[Callable[[str, Dict[str, Any]], None]] = None,
        on_batch: Optional[Callable[[int, float], None]] = None,
    ) -> None:
        self.config = config
        self.batcher = batcher
        self.store = store
        self._faults = fault_schedule
        self._on_event = on_event
        self._on_batch = on_batch
        self.slots: List[ReplicaSlot] = [ReplicaSlot(i, config) for i in range(config.num_replicas)]
        self._monitor_thread: Optional[threading.Thread] = None
        self._closing = threading.Event()

    # -------------------------------------------------------------- lifecycle
    def start(self) -> None:
        for slot in self.slots:
            self._spawn(slot)
        self._monitor_thread = threading.Thread(
            target=self._monitor, name="serve-monitor", daemon=True
        )
        self._monitor_thread.start()

    def close(self, timeout_s: float = 2.0) -> None:
        self._closing.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout_s)
        for slot in self.slots:
            if slot.thread is not None:
                slot.thread.request_stop()
        deadline = time.monotonic() + timeout_s
        for slot in self.slots:
            if slot.thread is not None:
                slot.thread.join(max(0.0, deadline - time.monotonic()))
            slot.fold_stats()

    # -------------------------------------------------------------- inspection
    @property
    def alive_count(self) -> int:
        return sum(1 for s in self.slots if s.alive)

    @property
    def masked_count(self) -> int:
        return sum(1 for s in self.slots if s.masked)

    @property
    def degraded(self) -> bool:
        return self.masked_count > 0

    @property
    def all_masked(self) -> bool:
        return self.masked_count == len(self.slots)

    @property
    def total_restarts(self) -> int:
        return sum(s.restarts for s in self.slots)

    # ---------------------------------------------------------------- monitor
    def _monitor(self) -> None:
        interval = self.config.monitor_interval_s
        while not self._closing.is_set() and not self.batcher.closed:
            now = time.monotonic()
            for slot in self.slots:
                if slot.masked:
                    continue
                if slot.restart_at is not None:
                    if now >= slot.restart_at:
                        slot.restart_at = None
                        self._spawn(slot)
                    continue
                if not slot.alive:
                    reason = (
                        slot.thread.exit_reason if slot.thread is not None else None
                    ) or "thread exited"
                    self._handle_fault(slot, reason)
                elif (
                    slot.stats is not None
                    and now - slot.stats.heartbeat > self.config.replica_timeout_s
                ):
                    # hung, not dead: abandon the thread, replace the slot
                    age = now - slot.stats.heartbeat
                    slot.thread.request_stop()
                    self._emit("replica_hung", {"replica": slot.index, "heartbeat_age_s": age})
                    self._handle_fault(slot, f"hung (heartbeat {age:.1f}s stale)")
            self._closing.wait(interval)

    def _handle_fault(self, slot: ReplicaSlot, reason: str) -> None:
        slot.fold_stats()
        if slot.budget.exhausted:
            slot.masked = True
            slot.mask_reason = reason
            slot.thread = None
            slot.stats = None
            self._emit(
                "replica_masked",
                {
                    "replica": slot.index,
                    "reason": reason,
                    "restarts": slot.restarts,
                    "alive": self.alive_count,
                    "degraded": True,
                },
            )
            return
        charge = slot.budget.charge()
        slot.restarts += 1
        backoff = self.config.backoff_s(charge)
        slot.restart_at = time.monotonic() + backoff
        self._emit(
            "replica_restart",
            {
                "replica": slot.index,
                "reason": reason,
                "restarts": slot.restarts,
                "backoff_s": backoff,
            },
        )

    def _spawn(self, slot: ReplicaSlot) -> None:
        slot.stats = ReplicaStats()
        slot.thread = Replica(
            slot.index,
            batcher=self.batcher,
            store=self.store,
            stats=slot.stats,
            batch_counter=slot.batch_counter,
            max_batch=self.config.max_batch,
            breaker_threshold=self.config.breaker_threshold,
            fault_schedule=self._faults,
            on_batch=self._on_batch,
        )
        slot.thread.start()

    def _emit(self, kind: str, info: Dict[str, Any]) -> None:
        if self._on_event is not None:
            try:
                self._on_event(kind, info)
            except Exception:
                pass
