"""Retry-aware client wrapper over :class:`PolicyServer`.

The server's failure contract is typed, so the client's policy is a small
decision table instead of string matching:

- :class:`Overloaded` — *retryable*: the server shed the request at
  admission, nothing was enqueued. Sleep the server's ``retry_after_s`` hint
  scaled by jittered exponential growth, then retry, up to ``max_retries``
  and never past the caller's own deadline.
- :class:`DeadlineExceeded` — *not retryable here*: the latency budget is
  already spent; surfacing it beats returning a stale action late.
- :class:`ServerClosed` — *not retryable*: shutdown is not a transient.

The jitter is deterministic per-client (seeded ``random.Random``) so load
drills are reproducible.
"""

from __future__ import annotations

import random
import time
from typing import Any, Optional

from sheeprl_tpu.serve.errors import Overloaded
from sheeprl_tpu.serve.server import PolicyServer


class ServeClient:
    """One logical caller. Counts its retries so drills can assert that
    shedding produced *backoff* (client-side), not just rejections.

    ``experience_sink`` is the online-learning tap
    (:meth:`~sheeprl_tpu.online.bridge.ExperienceBridge.observe` or anything
    with its signature): after a successful infer the client offers
    ``(obs, action, served_step, trace_id)`` to the sink. The offer is
    non-blocking by the sink's contract and exceptions are swallowed — the
    learning loop must never be able to fail a request that already
    succeeded.
    """

    def __init__(
        self,
        server: PolicyServer,
        *,
        max_retries: int = 3,
        timeout_s: Optional[float] = None,
        backoff_multiplier: float = 2.0,
        seed: int = 0,
        experience_sink: Optional[Any] = None,
    ) -> None:
        self.server = server
        self.max_retries = int(max_retries)
        self.timeout_s = timeout_s
        self.backoff_multiplier = float(backoff_multiplier)
        self._rng = random.Random(seed)
        self.retries = 0
        self.rejected = 0
        self.experience_sink = experience_sink
        self.experience_offered = 0

    def infer(self, obs: Any, timeout_s: Optional[float] = None) -> Any:
        """One request with admission-retry. Raises the final Overloaded when
        the budget (retries or time) is exhausted."""
        timeout_s = timeout_s if timeout_s is not None else self.timeout_s
        deadline = (time.monotonic() + timeout_s) if timeout_s is not None else None
        attempt = 0
        # submit/wait exposes the request object (served_step, trace_id) for
        # the experience tap; the client stays duck-typed over infer-only
        # servers, which can't feed the tap but serve identically.
        two_phase = hasattr(self.server, "submit") and hasattr(self.server, "wait")
        while True:
            try:
                deadline_s = (
                    max(1e-3, deadline - time.monotonic()) if deadline is not None else None
                )
                if two_phase:
                    req = self.server.submit(obs, deadline_s=deadline_s)
                    out = self.server.wait(req)
                else:
                    req = None
                    out = self.server.infer(obs, deadline_s=deadline_s)
                if self.experience_sink is not None:
                    try:
                        self.experience_sink(
                            obs, out, getattr(req, "served_step", -1), getattr(req, "trace_id", 0)
                        )
                        self.experience_offered += 1
                    except Exception:
                        pass
                return out
            except Overloaded as err:
                self.rejected += 1
                attempt += 1
                if attempt > self.max_retries:
                    raise
                pause = err.retry_after_s * (self.backoff_multiplier ** (attempt - 1))
                pause *= 0.5 + self._rng.random()  # jitter in [0.5x, 1.5x)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= pause:
                        raise  # can't absorb the backoff inside the deadline
                self.retries += 1
                time.sleep(pause)
