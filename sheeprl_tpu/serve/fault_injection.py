"""Deterministic fault injection for the serving tier.

Same philosophy as ``rollout.fault_injection`` — and since PR 12 literally
the same engine (:mod:`sheeprl_tpu.utils.faults`): the recovery paths are
exercised by scheduled drills in CI, not discovered in production. Faults are
owned by the *schedule* (parent-side state), not by the replica that executes
them, so a crashed-and-restarted replica cannot lose the record of which
faults already fired. This module keeps the serve-flavored config keys
(``replica``/``at_batch``/``at_swap``/``at_request``) as aliases into the
shared parser.

Config shape (``serve.fault_injection`` in the composed config)::

    serve:
      fault_injection:
        enabled: true
        faults:
          - {kind: replica_crash,  replica: 0, at_batch: 5}
          - {kind: slow_inference, replica: 0, at_batch: 2, duration_s: 0.2, for_batches: 20}
          - {kind: poison_swap, at_swap: 1}
          - {kind: router_blackhole, at_request: 10, duration_s: 0.2}

``kind``:

- ``replica_crash`` — replica ``replica`` raises before processing its
  ``at_batch``-th batch (the batch is re-queued first, so no request is
  dropped); the supervisor sees the dead thread and restarts it under the
  restart budget.
- ``slow_inference`` — replica ``replica`` sleeps ``duration_s`` before each
  of ``for_batches`` consecutive batches starting at ``at_batch``; drives the
  queue toward its bound so admission control sheds.
- ``poison_swap`` — the ``at_swap``-th swap *attempt* (1-based) has its
  freshly loaded weights NaN-poisoned after the load, so the promotion
  validation must reject it and keep serving the previous executable.
- ``router_blackhole`` — the fleet front door (:mod:`sheeprl_tpu.serve.
  router`) swallows assignments for ``duration_s`` starting at its
  ``at_request``-th routed request: the chosen replica never receives the
  work, so the hedge/deadline machinery must rescue every admitted request.
  Ignored by the single-server tier (there is no router to blackhole).

``at_batch`` counts batches *processed by that replica* (a monotone
per-replica counter that survives restarts); ``at_request`` counts requests
*routed by the fleet router*. Each fault fires exactly once
(``slow_inference`` covers its window, then expires).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Mapping, Sequence

from sheeprl_tpu.utils.faults import DeterministicSchedule, parse_fault_entries, register_fault_domain

_KINDS = ("replica_crash", "slow_inference", "poison_swap", "router_blackhole")
register_fault_domain("serve", _KINDS)


@dataclass
class ServeFaultSpec:
    kind: str
    replica: int = 0
    at_batch: int = 0
    at_swap: int = 1
    at_request: int = 0
    duration_s: float = 0.0
    for_batches: int = 1

    def __post_init__(self) -> None:
        self.kind = str(self.kind).lower()
        if self.kind not in _KINDS:
            raise ValueError(f"unknown serve fault kind {self.kind!r}; expected one of {_KINDS}")
        self.replica = int(self.replica)
        self.at_batch = int(self.at_batch)
        self.at_swap = int(self.at_swap)
        self.at_request = int(self.at_request)
        self.duration_s = float(self.duration_s)
        self.for_batches = int(self.for_batches)
        if self.replica < 0:
            raise ValueError(f"serve fault replica index must be >= 0, got {self.replica}")
        if self.at_batch < 0:
            raise ValueError(f"serve fault at_batch must be >= 0, got {self.at_batch}")
        if self.at_request < 0:
            raise ValueError(f"serve fault at_request must be >= 0, got {self.at_request}")
        if self.kind == "poison_swap" and self.at_swap < 1:
            raise ValueError(f"serve fault at_swap is 1-based, got {self.at_swap}")
        if self.for_batches < 1:
            raise ValueError(f"serve fault for_batches must be >= 1, got {self.for_batches}")


def parse_serve_faults(node: Sequence[Mapping[str, Any]]) -> List[ServeFaultSpec]:
    entries = parse_fault_entries(
        node,
        domain="serve.fault_injection",
        required=("kind",),
        fields=(
            ("replica", int, 0),
            ("at_batch", int, 0),
            ("at_swap", int, 1),
            ("at_request", int, 0),
            ("duration_s", float, 0.0),
            ("for_batches", int, 1),
        ),
    )
    return [ServeFaultSpec(**e) for e in entries]


class ServeFaultSchedule:
    """Thread-safe: replicas, the router and the swap watcher query
    concurrently (each counter family gets its own pending set)."""

    def __init__(self, faults: Sequence[ServeFaultSpec]) -> None:
        self._batches = DeterministicSchedule(
            [f for f in faults if f.kind in ("replica_crash", "slow_inference")],
            at=lambda f: f.at_batch,
            index=lambda f: f.replica,
            window=lambda f: f.for_batches if f.kind == "slow_inference" else 1,
        )
        self._swaps = DeterministicSchedule(
            [f for f in faults if f.kind == "poison_swap"], at=lambda f: f.at_swap
        )
        self._router = DeterministicSchedule(
            [f for f in faults if f.kind == "router_blackhole"], at=lambda f: f.at_request
        )

    def __bool__(self) -> bool:
        return bool(self._batches) or bool(self._swaps) or bool(self._router)

    def batch_faults(self, replica: int, batch_index: int) -> List[ServeFaultSpec]:
        """Faults due for ``replica``'s ``batch_index``-th batch. A
        ``replica_crash`` whose step the replica already passed (scheduled
        while it was restarting) fires on the next batch, mirroring the
        rollout schedule's nothing-silently-dropped rule."""
        return self._batches.pop_due(batch_index, index=replica)

    def poison_swap(self, attempt: int) -> bool:
        """True when the ``attempt``-th swap attempt (1-based) must have its
        loaded weights poisoned before validation."""
        return self._swaps.pop_first(attempt) is not None

    def router_faults(self, request_index: int) -> List[ServeFaultSpec]:
        """``router_blackhole`` faults due at the router's ``request_index``-th
        routed request, marked fired (the router holds each blackhole open
        for its ``duration_s``)."""
        return self._router.pop_due(request_index)
