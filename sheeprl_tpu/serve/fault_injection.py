"""Deterministic fault injection for the serving tier.

Same philosophy as ``rollout.fault_injection``: the recovery paths are
exercised by scheduled drills in CI, not discovered in production. Faults are
owned by the *schedule* (parent-side state), not by the replica that executes
them, so a crashed-and-restarted replica cannot lose the record of which
faults already fired.

Config shape (``serve.fault_injection`` in the composed config)::

    serve:
      fault_injection:
        enabled: true
        faults:
          - {kind: replica_crash,  replica: 0, at_batch: 5}
          - {kind: slow_inference, replica: 0, at_batch: 2, duration_s: 0.2, for_batches: 20}
          - {kind: poison_swap, at_swap: 1}

``kind``:

- ``replica_crash`` — replica ``replica`` raises before processing its
  ``at_batch``-th batch (the batch is re-queued first, so no request is
  dropped); the supervisor sees the dead thread and restarts it under the
  restart budget.
- ``slow_inference`` — replica ``replica`` sleeps ``duration_s`` before each
  of ``for_batches`` consecutive batches starting at ``at_batch``; drives the
  queue toward its bound so admission control sheds.
- ``poison_swap`` — the ``at_swap``-th swap *attempt* (1-based) has its
  freshly loaded weights NaN-poisoned after the load, so the promotion
  validation must reject it and keep serving the previous executable.

``at_batch`` counts batches *processed by that replica* (a monotone
per-replica counter that survives restarts). Each fault fires exactly once
(``slow_inference`` covers its window, then expires).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, List, Mapping, Sequence

_KINDS = ("replica_crash", "slow_inference", "poison_swap")


@dataclass
class ServeFaultSpec:
    kind: str
    replica: int = 0
    at_batch: int = 0
    at_swap: int = 1
    duration_s: float = 0.0
    for_batches: int = 1

    def __post_init__(self) -> None:
        self.kind = str(self.kind).lower()
        if self.kind not in _KINDS:
            raise ValueError(f"unknown serve fault kind {self.kind!r}; expected one of {_KINDS}")
        self.replica = int(self.replica)
        self.at_batch = int(self.at_batch)
        self.at_swap = int(self.at_swap)
        self.duration_s = float(self.duration_s)
        self.for_batches = int(self.for_batches)
        if self.replica < 0:
            raise ValueError(f"serve fault replica index must be >= 0, got {self.replica}")
        if self.at_batch < 0:
            raise ValueError(f"serve fault at_batch must be >= 0, got {self.at_batch}")
        if self.kind == "poison_swap" and self.at_swap < 1:
            raise ValueError(f"serve fault at_swap is 1-based, got {self.at_swap}")
        if self.for_batches < 1:
            raise ValueError(f"serve fault for_batches must be >= 1, got {self.for_batches}")


def parse_serve_faults(node: Sequence[Mapping[str, Any]]) -> List[ServeFaultSpec]:
    faults = []
    for i, entry in enumerate(node):
        if not hasattr(entry, "get"):
            raise ValueError(f"serve.fault_injection.faults[{i}] must be a mapping, got {entry!r}")
        if "kind" not in entry:
            raise ValueError(f"serve.fault_injection.faults[{i}] needs a kind, got {dict(entry)!r}")
        faults.append(
            ServeFaultSpec(
                kind=entry["kind"],
                replica=int(entry.get("replica", 0)),
                at_batch=int(entry.get("at_batch", 0)),
                at_swap=int(entry.get("at_swap", 1)),
                duration_s=float(entry.get("duration_s", 0.0) or 0.0),
                for_batches=int(entry.get("for_batches", 1)),
            )
        )
    return faults


class ServeFaultSchedule:
    """Thread-safe: replicas and the swap watcher query concurrently."""

    def __init__(self, faults: Sequence[ServeFaultSpec]) -> None:
        self._lock = threading.Lock()
        self._batch_faults = [f for f in faults if f.kind in ("replica_crash", "slow_inference")]
        self._swap_faults = [f for f in faults if f.kind == "poison_swap"]

    def __bool__(self) -> bool:
        with self._lock:
            return bool(self._batch_faults or self._swap_faults)

    def batch_faults(self, replica: int, batch_index: int) -> List[ServeFaultSpec]:
        """Faults due for ``replica``'s ``batch_index``-th batch. A
        ``replica_crash`` whose step the replica already passed (scheduled
        while it was restarting) fires on the next batch, mirroring the
        rollout schedule's nothing-silently-dropped rule."""
        due: List[ServeFaultSpec] = []
        with self._lock:
            remaining = []
            for f in self._batch_faults:
                if f.replica != replica:
                    remaining.append(f)
                elif f.kind == "replica_crash" and f.at_batch <= batch_index:
                    due.append(f)
                elif f.kind == "slow_inference" and f.at_batch <= batch_index < f.at_batch + f.for_batches:
                    due.append(f)
                    remaining.append(f)  # stays scheduled for its whole window
                elif f.kind == "slow_inference" and batch_index >= f.at_batch + f.for_batches:
                    pass  # window over: expire
                else:
                    remaining.append(f)
            self._batch_faults = remaining
        return due

    def poison_swap(self, attempt: int) -> bool:
        """True when the ``attempt``-th swap attempt (1-based) must have its
        loaded weights poisoned before validation."""
        with self._lock:
            for f in list(self._swap_faults):
                if f.at_swap <= attempt:
                    self._swap_faults.remove(f)
                    return True
        return False
