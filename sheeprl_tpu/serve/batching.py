"""SLO-bounded micro-batching queue with admission control.

The inference-server pattern: concurrent single-request callers are coalesced
into one batched forward. Two robustness rules make it production-shaped
rather than a demo:

- **bounded queue + explicit shedding** — ``submit`` REJECTS with a typed
  :class:`~sheeprl_tpu.serve.errors.Overloaded` the moment the pending count
  hits ``max_queue``. Backlog is never unbounded, so p95 latency is bounded
  by construction: at most ``max_queue / throughput`` of queueing can
  accumulate, and the caller (not the server) decides whether to retry.
- **per-request deadlines** — every request carries an absolute deadline;
  expired requests are completed exceptionally (:class:`DeadlineExceeded`)
  at the next batch assembly instead of being served dead work.

Batch assembly is latency-SLO-bounded: the first waiting request opens a
gather window (``gather_window_s``, derived from the SLO); the batch closes
when the window elapses or the ladder's top rung fills, whichever is first.
A lone request therefore pays at most one gather window of queueing, and a
saturated server runs full rungs back to back.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Callable, Deque, Dict, List, Optional

from sheeprl_tpu.serve.errors import DeadlineExceeded, Overloaded, ServerClosed

_REQUEST_IDS = itertools.count()


class Request:
    """One in-flight inference request: observation + deadline + Future."""

    __slots__ = (
        "obs", "enqueue_t", "deadline_t", "future", "rid", "attempts", "trace_id",
        "t_dispatch", "served_step",
    )

    def __init__(self, obs: Any, enqueue_t: float, deadline_t: float) -> None:
        self.obs = obs
        self.enqueue_t = enqueue_t
        self.deadline_t = deadline_t
        self.future: Future = Future()
        self.rid = next(_REQUEST_IDS)
        self.attempts = 0  # inference attempts (re-queues after replica failures)
        # trace-plane context (sheeprl_tpu.obs.trace): the cross-process
        # causal id minted at router admission (0 = untraced) and the
        # monotonic first-dispatch stamp — they live on the SHARED request
        # object, which is what lets one causal chain survive hedging,
        # re-route-at-front and requeue (every copy is the same object)
        self.trace_id = 0
        self.t_dispatch: Optional[float] = None
        # checkpoint step of the params that served this request (stamped by
        # the replica that completes it) — the online bridge maps it through
        # the version authority so every experience row records the exact
        # policy that produced it, swaps included
        self.served_step: int = -1

    def expired(self, now: float) -> bool:
        return now >= self.deadline_t

    def fail_expired(self, now: float) -> None:
        if not self.future.done():
            self.future.set_exception(
                DeadlineExceeded(now - self.enqueue_t, self.deadline_t - self.enqueue_t)
            )


class MicroBatcher:
    """The shared request queue between the submit path and the replicas.

    ``on_shed(kind)`` is the stats hook (``kind`` in ``overloaded`` /
    ``expired``); it fires outside the lock.
    """

    def __init__(
        self,
        *,
        max_queue: int,
        gather_window_s: float,
        clock: Callable[[], float] = time.monotonic,
        on_shed: Optional[Callable[[str], None]] = None,
    ) -> None:
        self.max_queue = int(max_queue)
        self.gather_window_s = float(gather_window_s)
        self._clock = clock
        self._on_shed = on_shed
        self._pending: Deque[Request] = deque()
        self._cond = threading.Condition()
        self._closed = False

    # ------------------------------------------------------------ submit side
    def submit(self, obs: Any, deadline_s: float) -> Request:
        """Admit ``obs`` or raise. Never blocks: admission control is a
        depth check under the lock, shedding is immediate and typed."""
        now = self._clock()
        with self._cond:
            if self._closed:
                raise ServerClosed("policy server is shut down")
            depth = len(self._pending)
            if depth < self.max_queue:
                req = Request(obs, now, now + float(deadline_s))
                self._pending.append(req)
                self._cond.notify()
                return req
        # shed path: the stats hook is user code — never run it under the lock
        self._shed("overloaded")
        raise Overloaded(depth, self.max_queue, self.gather_window_s)

    def depth(self) -> int:
        with self._cond:
            return len(self._pending)

    # ----------------------------------------------------------- replica side
    def next_batch(self, max_batch: int, wait_timeout_s: float) -> List[Request]:
        """Block up to ``wait_timeout_s`` for work; then coalesce up to
        ``max_batch`` requests within one gather window. Returns ``[]`` on
        timeout/closed so replica loops can heartbeat. Expired requests are
        completed exceptionally here and never reach the model."""
        batch: List[Request] = []
        expired: List[Request] = []
        with self._cond:
            deadline = self._clock() + wait_timeout_s
            while not self._pending and not self._closed:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return []
                self._cond.wait(remaining)
            if self._closed and not self._pending:
                return []
            gather_until = self._clock() + self.gather_window_s
            while len(batch) < max_batch:
                while self._pending:
                    req = self._pending.popleft()
                    (expired if req.expired(self._clock()) else batch).append(req)
                    if len(batch) >= max_batch:
                        break
                if len(batch) >= max_batch or self._closed:
                    break
                remaining = gather_until - self._clock()
                if remaining <= 0 or not batch:
                    # window over — or everything popped so far was expired:
                    # don't hold dead air waiting to pad a batch of nothing
                    break
                self._cond.wait(remaining)
        now = self._clock()
        for req in expired:
            req.fail_expired(now)
            self._shed("expired")
        return batch

    def requeue(self, requests: List[Request]) -> None:
        """Put a failed batch's still-viable requests back at the FRONT of
        the queue (they have already waited longest). Requests past their
        deadline are completed exceptionally instead. Bypasses admission
        control: an in-flight request was already admitted once — re-queueing
        it must not be sheddable, or a replica crash would drop work."""
        now = self._clock()
        viable = [r for r in requests if not r.future.done()]
        dead = [r for r in viable if r.expired(now)]
        keep = [r for r in viable if not r.expired(now)]
        for r in dead:
            r.fail_expired(now)
            self._shed("expired")
        if not keep:
            return
        failed: List[Request] = []
        with self._cond:
            if self._closed:
                # completing a Future wakes its waiter — do that after release
                failed = keep
            else:
                for r in reversed(keep):
                    r.attempts += 1
                    self._pending.appendleft(r)
                self._cond.notify_all()
        for r in failed:
            if not r.future.done():
                r.future.set_exception(ServerClosed("policy server is shut down"))

    # -------------------------------------------------------------- lifecycle
    def close(self) -> None:
        """Stop admitting; fail everything still pending with ServerClosed."""
        with self._cond:
            self._closed = True
            pending = list(self._pending)
            self._pending.clear()
            self._cond.notify_all()
        for req in pending:
            if not req.future.done():
                req.future.set_exception(ServerClosed("policy server is shut down"))

    @property
    def closed(self) -> bool:
        return self._closed

    def _shed(self, kind: str) -> None:
        if self._on_shed is not None:
            try:
                self._on_shed(kind)
            except Exception:
                pass
