"""Fault-tolerant policy-serving tier (``python -m sheeprl_tpu serve``).

Turns a committed training checkpoint into an inference service with the
robustness properties howto/serving.md documents: AOT-compiled batch ladder
(no request pays a JIT), SLO-bounded micro-batching, bounded queue with
typed load shedding, supervised replicas with budgeted restarts and
degraded N-1 mode, circuit breaking, and validated hot checkpoint swap
with rollback.

Import layering mirrors ``rollout``: this package root re-exports the
jax-free surface eagerly; :mod:`~sheeprl_tpu.serve.model` /
:mod:`~sheeprl_tpu.serve.server` (which import jax) are re-exported lazily
so ``bench.py``-style parents can read configs and errors without touching
an accelerator runtime.
"""

from __future__ import annotations

from typing import Any

from sheeprl_tpu.serve.batching import MicroBatcher, Request
from sheeprl_tpu.serve.config import FleetConfig, LoadConfig, ServeConfig, serve_config_from_cfg
from sheeprl_tpu.serve.errors import (
    DeadlineExceeded,
    InferenceFailed,
    Overloaded,
    ServeError,
    ServerClosed,
    SwapRejected,
)
from sheeprl_tpu.serve.fault_injection import (
    ServeFaultSchedule,
    ServeFaultSpec,
    parse_serve_faults,
)

_LAZY = {
    "CompiledLadder": "sheeprl_tpu.serve.model",
    "ModelStore": "sheeprl_tpu.serve.model",
    "ModelVersion": "sheeprl_tpu.serve.model",
    "ServedPolicy": "sheeprl_tpu.serve.model",
    "newest_committed": "sheeprl_tpu.serve.model",
    "PolicyServer": "sheeprl_tpu.serve.server",
    "ServeStats": "sheeprl_tpu.serve.server",
    "Replica": "sheeprl_tpu.serve.replica",
    "ReplicaStats": "sheeprl_tpu.serve.replica",
    "ReplicaSet": "sheeprl_tpu.serve.supervisor",
    "ReplicaSlot": "sheeprl_tpu.serve.supervisor",
    "ServeClient": "sheeprl_tpu.serve.client",
    "run_load": "sheeprl_tpu.serve.loadgen",
    "run_ramp": "sheeprl_tpu.serve.loadgen",
    "ramp_rates": "sheeprl_tpu.serve.loadgen",
    "SlotPool": "sheeprl_tpu.serve.slots",
    "safe_complete": "sheeprl_tpu.serve.slots",
    "Router": "sheeprl_tpu.serve.router",
    "RoutedRequest": "sheeprl_tpu.serve.router",
    "RouteTarget": "sheeprl_tpu.serve.router",
    "FleetServer": "sheeprl_tpu.serve.fleet",
    "FleetReplica": "sheeprl_tpu.serve.fleet",
    "FleetSlot": "sheeprl_tpu.serve.fleet",
    "POLICY_BUILDERS": "sheeprl_tpu.serve.policy",
    "build_served_policy": "sheeprl_tpu.serve.policy",
    "make_linear_state": "sheeprl_tpu.serve.policy",
    "register_policy_builder": "sheeprl_tpu.serve.policy",
}


def __getattr__(name: str) -> Any:
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)


__all__ = [
    "DeadlineExceeded",
    "FleetConfig",
    "InferenceFailed",
    "LoadConfig",
    "MicroBatcher",
    "Overloaded",
    "Request",
    "ServeConfig",
    "ServeError",
    "ServeFaultSchedule",
    "ServeFaultSpec",
    "ServerClosed",
    "SwapRejected",
    "parse_serve_faults",
    "serve_config_from_cfg",
    *sorted(_LAZY),
]
