"""The fleet's front door: admission, health-scored routing, hedged retries.

One :class:`Router` sits in front of N replica slot pools
(:mod:`sheeprl_tpu.serve.fleet`) and owns every fleet-wide request-path
decision, so the per-replica machinery can stay dumb:

- **admission** — one fleet-wide pending bound (``serve.fleet`` scales the
  single-server ``max_queue``); past it, ``submit`` sheds with the same typed
  :class:`~sheeprl_tpu.serve.errors.Overloaded` contract as the single
  server. Re-routed and hedged placements of already-admitted requests bypass
  admission — an admitted request is never shed by a fleet event it didn't
  cause.
- **routing** — consistent health-weighted least-loaded choice: each live
  replica gets a health score in ``(0, 1]`` decayed from its heartbeat age
  (fed by the fleet supervisor) and the router picks the lowest
  ``outstanding / health``. A sick-but-alive replica therefore sees traffic
  taper before the supervisor declares it dead, and routing is a pure
  function of observable state (no RNG) so drills replay exactly.
- **hedged retries** — a scan thread watches in-flight requests; one that has
  waited past the fleet's rolling latency quantile
  (``hedge_quantile``, floored by ``hedge_floor_ms``) is duplicated to a
  different replica. Only *idempotent* requests hedge (single-step policy
  calls are; anything submitted with ``idempotent=False`` never is), the
  first completion wins the request's Future, and the loser's copy is
  dropped at its pool's next dispatch assembly (``future.done()``), i.e. the
  losing twin is cancelled rather than served dead.
- **re-route-at-front** — when the fleet declares a replica dead, the
  router drains that replica's pool (in-flight window first, admission order
  preserved) and plants the work at the FRONT of the healthiest sibling:
  the single-server crash-requeue-at-front contract, promoted across
  replicas. Zero admitted requests are dropped by a crash; each still
  expires only by its own deadline. The in-flight window re-homes in full
  only when the replica thread is confirmed dead — a hung-but-alive thread
  may still finish its dispatch, so only its idempotent requests are
  duplicated (hedge semantics) and a healthy retiring thread keeps its
  whole window.
- **blackhole drill** — a scheduled ``router_blackhole`` fault makes the
  router swallow assignments for ``duration_s``: requests are admitted but
  reach no replica, and the hedge scan must rescue every one of them. This
  is the front door's own failure mode, drilled like every other.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence

from sheeprl_tpu.obs.trace import new_trace_id, trace_event, tracing_active
from sheeprl_tpu.serve.batching import Request
from sheeprl_tpu.serve.errors import Overloaded, ServerClosed
from sheeprl_tpu.serve.fault_injection import ServeFaultSchedule
from sheeprl_tpu.serve.slots import SlotPool

INTERACTIVE = "interactive"
BATCH = "batch"  # eval / loadgen traffic, spillable to CPU replicas


class RoutedRequest(Request):
    """A fleet request: a :class:`Request` plus routing state the hedge scan
    and the drills read. ``placements`` is the ordered list of replica
    indices this request was offered to (first = primary route)."""

    __slots__ = ("idempotent", "priority", "placements", "hedges", "rerouted")

    def __init__(
        self,
        obs: Any,
        enqueue_t: float,
        deadline_t: float,
        *,
        idempotent: bool = True,
        priority: str = INTERACTIVE,
    ) -> None:
        super().__init__(obs, enqueue_t, deadline_t)
        self.idempotent = bool(idempotent)
        self.priority = str(priority)
        self.placements: List[int] = []
        self.hedges = 0
        self.rerouted = 0


class RouteTarget(NamedTuple):
    """One routable replica as the fleet advertises it to the router."""

    index: int
    pool: SlotPool
    health: float  # (0, 1]; <= 0 means unroutable (masked/dead/retiring)
    kind: str  # "device" | "cpu_spill"


class Router:
    """Fleet front door. ``targets()`` is the fleet's live routing table —
    re-read on every decision so replica death/scale events take effect
    immediately; the router holds no replica state of its own."""

    LATENCY_RESERVOIR = 2048
    MIN_HEDGE_SAMPLES = 16

    def __init__(
        self,
        *,
        targets: Callable[[], List[RouteTarget]],
        max_pending: int,
        slo_s: float,
        hedge_quantile: float = 0.95,
        hedge_floor_s: float = 0.0,
        hedge_max: int = 1,
        hedge_scan_s: float = 0.005,
        spill_depth: int = 4,
        fault_schedule: Optional[ServeFaultSchedule] = None,
        on_event: Optional[Callable[[str, Dict[str, Any]], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._targets = targets
        self.max_pending = int(max_pending)
        self._slo_s = float(slo_s)
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_floor_s = float(hedge_floor_s)
        self.hedge_max = int(hedge_max)
        self._hedge_scan_s = float(hedge_scan_s)
        self.spill_depth = int(spill_depth)
        self._faults = fault_schedule
        self._on_event = on_event
        self._clock = clock
        self._lock = threading.Lock()
        self._inflight: Dict[int, RoutedRequest] = {}
        self._latencies: List[float] = []
        self._lat_pos = 0
        self._route_seq = 0
        self._blackhole_until = 0.0
        self._closing = threading.Event()
        self._scan_thread: Optional[threading.Thread] = None
        # counters (drills and the fleet snapshot read these)
        self.routed = 0
        self.shed = 0
        self.hedged = 0
        self.hedged_won = 0  # completions that had at least one hedge twin
        self.rerouted_requests = 0
        self.blackholed = 0
        self.spilled = 0
        self.expired = 0  # backstop expiries of unplaced requests

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "Router":
        if self._scan_thread is None:
            self._scan_thread = threading.Thread(
                target=self._scan, name="fleet-router-hedge", daemon=True
            )
            self._scan_thread.start()
        return self

    def close(self) -> None:
        self._closing.set()
        if self._scan_thread is not None:
            self._scan_thread.join(1.0)

    # ---------------------------------------------------------------- routing
    def submit(
        self,
        obs: Any,
        deadline_s: float,
        *,
        idempotent: bool = True,
        priority: str = INTERACTIVE,
    ) -> RoutedRequest:
        """Admit + route one request. Raises :class:`Overloaded` at the
        fleet-wide bound, :class:`ServerClosed` when no replica exists at
        all (fleet shut down)."""
        if self._closing.is_set():
            raise ServerClosed("fleet router is shut down")
        now = self._clock()
        # queued depth alone misses admitted-but-unplaced requests (blackhole
        # window, every pool full): they occupy no pool, so they must count
        # here or a blackhole makes the fleet-wide bound unenforceable
        depth = self.pending_depth() + self.unplaced_inflight()
        if depth >= self.max_pending:
            self.shed += 1
            raise Overloaded(depth, self.max_pending, self._slo_s / 5.0)
        req = RoutedRequest(
            obs, now, now + float(deadline_s), idempotent=idempotent, priority=priority
        )
        if tracing_active():
            # the request id is per-process; the trace id is the cross-process
            # causal handle — minted once here, it rides the shared request
            # object through every hedge/re-route/requeue placement. Minted
            # BEFORE the request enters _inflight: the hedge scan can place
            # an inflight-but-unplaced request from its own thread, and a
            # replica may dispatch and deliver that copy immediately — if the
            # mint raced that window the delivery would see trace_id == 0 and
            # the chain would dangle without its request_done
            req.trace_id = new_trace_id()
            trace_event(
                "request_admit",
                req.trace_id,
                rid=req.rid,
                priority=req.priority,
                idempotent=req.idempotent,
                deadline_ms=float(deadline_s) * 1e3,
            )
        with self._lock:
            seq = self._route_seq
            self._route_seq += 1
            self._inflight[req.rid] = req
        self.routed += 1
        self._consume_router_faults(seq, now)
        if now < self._blackhole_until:
            # blackholed: admitted, tracked, but the assignment is swallowed;
            # the hedge scan is the rescue path for every one of these
            self.blackholed += 1
            if req.trace_id:
                trace_event("request_blackholed", req.trace_id, rid=req.rid)
            return req
        self._place(req, now)
        return req

    def _place(self, req: RoutedRequest, now: float) -> bool:
        """Offer ``req`` to the best target it hasn't been placed on yet."""
        for target in self._ranked_targets(req):
            try:
                if target.pool.offer(req):
                    req.placements.append(target.index)
                    if target.kind == "cpu_spill":
                        self.spilled += 1
                    if req.trace_id:
                        trace_event(
                            "request_route",
                            req.trace_id,
                            rid=req.rid,
                            replica=target.index,
                            attempt=len(req.placements),
                            target_kind=target.kind,
                        )
                    return True
            except ServerClosed:
                continue
        return False  # every pool full/closed: the hedge scan retries

    def _ranked_targets(self, req: RoutedRequest) -> List[RouteTarget]:
        """Routable targets, best first: health-weighted least-loaded.
        ``batch`` traffic spills to CPU replicas once the device replicas are
        queueing past ``spill_depth`` each; interactive traffic only ever
        lands on a spill replica when no device replica is routable."""
        live = [t for t in self._targets() if t.health > 0 and not t.pool.closed]
        fresh = [t for t in live if t.index not in req.placements]
        device = [t for t in fresh if t.kind != "cpu_spill"]
        spill = [t for t in fresh if t.kind == "cpu_spill"]

        def score(t: RouteTarget) -> float:
            return t.pool.outstanding() / max(t.health, 1e-6)

        device.sort(key=score)
        spill.sort(key=score)
        if req.priority == BATCH and spill:
            saturated = device and all(
                t.pool.depth() >= self.spill_depth for t in device
            )
            if saturated or not device:
                return spill + device
        return device + spill

    # ---------------------------------------------------------------- hedging
    def hedge_threshold_s(self) -> float:
        """How long a request may wait before it is hedged: the rolling
        ``hedge_quantile`` of completed fleet latencies, floored by
        ``hedge_floor_s``; one SLO until enough samples exist."""
        with self._lock:
            lats = sorted(self._latencies)
        if len(lats) < self.MIN_HEDGE_SAMPLES:
            return max(self.hedge_floor_s, self._slo_s)
        idx = min(len(lats) - 1, max(0, math.ceil(self.hedge_quantile * len(lats)) - 1))
        return max(self.hedge_floor_s, lats[idx])

    def record_latency(self, latency_s: float) -> None:
        """Feed one completed end-to-end latency into the hedge quantile."""
        with self._lock:
            if len(self._latencies) < self.LATENCY_RESERVOIR:
                self._latencies.append(latency_s)
            else:
                self._latencies[self._lat_pos] = latency_s
                self._lat_pos = (self._lat_pos + 1) % self.LATENCY_RESERVOIR

    def _scan(self) -> None:
        while not self._closing.wait(self._hedge_scan_s):
            try:
                self._scan_once()
            except Exception:
                pass  # the rescue path must outlive any one bad pass

    def _scan_once(self) -> None:
        now = self._clock()
        threshold = self.hedge_threshold_s()
        with self._lock:
            inflight = list(self._inflight.values())
        for req in inflight:
            if req.future.done():
                with self._lock:
                    self._inflight.pop(req.rid, None)
                if req.hedges and not req.future.exception():
                    self.hedged_won += 1
                continue
            if now >= req.deadline_t:
                # backstop expiry: a placed request is normally expired by
                # its pool at dispatch assembly, but an unplaced one (black-
                # holed, every pool full, re-route with no live sibling) is
                # in NO pool — without this it would leak in-flight forever
                # and its consumer would hang on a raw future
                req.fail_expired(now)
                with self._lock:
                    self._inflight.pop(req.rid, None)
                self.expired += 1
                if req.trace_id:
                    trace_event(
                        "request_expired",
                        req.trace_id,
                        rid=req.rid,
                        waited_ms=(now - req.enqueue_t) * 1e3,
                    )
                continue
            if not req.placements and now >= self._blackhole_until:
                # swallowed by a blackhole (or every pool was full): rescue
                if self._place(req, now):
                    self._emit("router_rescue", {"rid": req.rid})
                continue
            if (
                req.idempotent
                and req.hedges < self.hedge_max
                and now - req.enqueue_t >= threshold
            ):
                if self._place(req, now):
                    req.hedges += 1
                    self.hedged += 1
                    self._emit(
                        "hedge",
                        {
                            "rid": req.rid,
                            "waited_ms": (now - req.enqueue_t) * 1e3,
                            "threshold_ms": threshold * 1e3,
                            "placements": list(req.placements),
                        },
                    )
                    if req.trace_id:
                        trace_event(
                            "request_hedge",
                            req.trace_id,
                            rid=req.rid,
                            replica=req.placements[-1],
                            waited_ms=(now - req.enqueue_t) * 1e3,
                            threshold_ms=threshold * 1e3,
                        )

    # -------------------------------------------------------------- re-routing
    def reroute(self, index: int, pool: SlotPool, reason: str, *, inflight: str = "all") -> int:
        """Drain a dead/retiring replica's pool and plant the work — in
        admission order — at the FRONT of the healthiest surviving sibling.
        Returns how many requests were re-homed. Requests with no live
        sibling stay tracked in-flight; the hedge scan keeps retrying them
        until a replica returns or their own deadline expires.

        ``inflight`` (see :meth:`SlotPool.drain`) scopes the in-flight
        window: ``"all"`` only when the replica thread is confirmed dead —
        re-homing a live thread's window would run non-idempotent requests
        twice. A hung-but-alive replica uses ``"idempotent"`` (duplication
        there is hedging: first completion wins), a healthy retiring one
        ``"none"``."""
        drained = pool.drain(inflight=inflight)
        if not drained:
            return 0
        moved = 0
        for req in drained:
            if isinstance(req, RoutedRequest):
                req.rerouted += 1
        targets = [
            t
            for t in self._ranked_targets_any()
            if t.index != index and t.health > 0 and not t.pool.closed
        ]
        for target in targets:
            try:
                target.pool.offer_front(drained)
            except ServerClosed:
                continue  # closed between the ranking and the offer: next one
            for req in drained:
                if isinstance(req, RoutedRequest):
                    req.placements.append(target.index)
            moved = len(drained)
            break
        if moved == 0:
            # nowhere to go right now: leave them in-flight; the scan retries
            for req in drained:
                if isinstance(req, RoutedRequest):
                    req.placements.clear()
        self.rerouted_requests += moved
        self._emit(
            "reroute",
            {"replica": index, "reason": reason, "requests": len(drained), "moved": moved},
        )
        # one batched trace event per reroute (not one per request): the
        # merger expands trace_ids so every victim's chain carries the
        # re-route attribution without a hot-path write per request
        tids = [r.trace_id for r in drained if getattr(r, "trace_id", 0)]
        if tids:
            trace_event(
                "request_reroute",
                replica=index,
                reason=reason,
                moved=moved,
                trace_ids=tids,
            )
        return moved

    def _ranked_targets_any(self) -> List[RouteTarget]:
        live = [t for t in self._targets() if t.health > 0 and not t.pool.closed]
        return sorted(live, key=lambda t: t.pool.outstanding() / max(t.health, 1e-6))

    # ------------------------------------------------------------------ stats
    def pending_depth(self) -> int:
        """Fleet-wide queued depth (the admission + autoscale signal)."""
        return sum(t.pool.depth() for t in self._targets())

    def inflight_count(self) -> int:
        with self._lock:
            return len(self._inflight)

    def unplaced_inflight(self) -> int:
        """Admitted requests currently in NO pool (blackholed, or every pool
        was full/closed at placement). Part of the admission signal."""
        with self._lock:
            return sum(
                1
                for r in self._inflight.values()
                if not r.placements and not r.future.done()
            )

    def snapshot(self) -> Dict[str, Any]:
        return {
            "routed": self.routed,
            "shed": self.shed,
            "hedged": self.hedged,
            "hedged_won": self.hedged_won,
            "rerouted_requests": self.rerouted_requests,
            "blackholed": self.blackholed,
            "spilled": self.spilled,
            "expired": self.expired,
            "inflight": self.inflight_count(),
            "unplaced_inflight": self.unplaced_inflight(),
            "pending_depth": self.pending_depth(),
            "hedge_threshold_ms": self.hedge_threshold_s() * 1e3,
        }

    # --------------------------------------------------------------- internal
    def _consume_router_faults(self, seq: int, now: float) -> None:
        if self._faults is None:
            return
        for fault in self._faults.router_faults(seq):
            self._blackhole_until = max(self._blackhole_until, now + fault.duration_s)
            self._emit(
                "router_blackhole",
                {"at_request": seq, "duration_s": fault.duration_s},
            )

    def _emit(self, kind: str, info: Dict[str, Any]) -> None:
        if self._on_event is not None:
            try:
                self._on_event(kind, info)
            except Exception:
                pass
