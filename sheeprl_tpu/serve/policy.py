"""Policy builders: checkpoint state -> a servable forward + obs spec.

A builder takes the run config stored beside the checkpoint plus the loaded
state and returns a :class:`~sheeprl_tpu.serve.model.ServedPolicy` — the
pure ``apply``, the initial params, the per-request observation spec and the
``params_from_state`` extractor hot swaps re-use. Registered per algorithm
name (the serve CLI dispatches on ``cfg.algo.name`` exactly like eval does);
``linear`` is the env-free synthetic policy the unit tests and drills serve
so the robustness machinery is testable without gymnasium or a real
checkpointed run.

Serving is greedy and stateless: the PPO forward takes the distribution
mode, so the PRNG key baked into the compiled executable is never consulted
and identical observations yield identical actions across replicas — which
is what lets a crashed replica's re-queued request be re-served anywhere.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import numpy as np

from sheeprl_tpu.serve.model import ServedPolicy

POLICY_BUILDERS: Dict[str, Callable[..., ServedPolicy]] = {}


def register_policy_builder(*names: str) -> Callable:
    def deco(fn: Callable[..., ServedPolicy]) -> Callable[..., ServedPolicy]:
        for name in names:
            POLICY_BUILDERS[name] = fn
        return fn

    return deco


def build_served_policy(cfg: Any, state: Dict[str, Any]) -> ServedPolicy:
    """Dispatch on ``cfg.algo.name``. Unsupported algorithms fail with the
    list of servable ones, mirroring the eval registry's error shape."""
    name = cfg["algo"]["name"]
    builder = POLICY_BUILDERS.get(name)
    if builder is None:
        raise ValueError(
            f"no policy builder registered for algorithm '{name}'; "
            f"servable algorithms: {sorted(POLICY_BUILDERS)}"
        )
    return builder(cfg, state)


@register_policy_builder("ppo", "ppo_decoupled")
def build_ppo_policy(cfg: Any, state: Dict[str, Any]) -> ServedPolicy:
    """Greedy PPO serving forward: ``obs -> env-ready actions`` (per-part
    integer indices for discrete spaces, raw vectors for continuous)."""
    import gymnasium as gym
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.ppo.agent import build_agent, real_actions_from_onehot, sample_actions
    from sheeprl_tpu.envs import make_env
    from sheeprl_tpu.parallel.fabric import Fabric

    # spaces come from one throwaway env exactly like evaluate() builds them
    env = make_env(cfg, cfg["seed"], 0, None, "serve", vector_env_idx=0)()
    observation_space = env.observation_space
    action_space = env.action_space
    env.close()
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"unexpected observation space for serving: {observation_space}")
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )

    fabric = Fabric(devices=1, precision=str(cfg["fabric"].get("precision", "fp32")))
    agent, params = build_agent(fabric, actions_dim, is_continuous, cfg, observation_space, state["agent"])

    # per-request obs spec: the post-`prepare_obs` layout (frame stack folded
    # into channels, pixels uint8, vectors float32), WITHOUT the batch axis
    spec: Dict[str, jax.ShapeDtypeStruct] = {}
    for k in agent.cnn_keys:
        shape = observation_space[k].shape
        if len(shape) == 4:  # [S,H,W,C] stacked -> [H,W,S*C]
            s, h, w, c = shape
            shape = (h, w, s * c)
        spec[k] = jax.ShapeDtypeStruct(tuple(shape), np.uint8)
    for k in agent.mlp_keys:
        spec[k] = jax.ShapeDtypeStruct(tuple(observation_space[k].shape), np.float32)

    greedy_key = jax.random.PRNGKey(0)  # never consulted: greedy takes the mode

    def apply(p: Any, obs: Dict[str, Any]) -> Any:
        actions, _, _ = sample_actions(agent, p, obs, greedy_key, greedy=True)
        return real_actions_from_onehot(agent.actions_dim, agent.is_continuous, actions)

    def params_from_state(new_state: Dict[str, Any]) -> Any:
        # same placement pipeline build_agent runs on a restore
        new = jax.tree.map(jnp.asarray, new_state["agent"])
        new = jax.tree.map(lambda x: x.astype(fabric.precision.param_dtype), new)
        return fabric.replicate(new)

    return ServedPolicy(
        name=cfg["algo"]["name"],
        apply=apply,
        params=params,
        obs_spec=spec,
        params_from_state=params_from_state,
    )


@register_policy_builder("linear")
def build_linear_policy(cfg: Any, state: Dict[str, Any]) -> ServedPolicy:
    """Synthetic env-free policy for tests and serving drills: a single
    linear layer over a flat observation. State layout matches the real
    algos (``state["agent"]`` holds the params pytree)."""
    import jax
    import jax.numpy as jnp

    params = jax.tree.map(jnp.asarray, state["agent"])
    in_dim = int(np.asarray(params["w"]).shape[0])

    def apply(p: Any, obs: Dict[str, Any]) -> Any:
        return obs["vector"] @ p["w"] + p["b"]

    return ServedPolicy(
        name="linear",
        apply=apply,
        params=params,
        obs_spec={"vector": jax.ShapeDtypeStruct((in_dim,), np.float32)},
        params_from_state=lambda s: jax.tree.map(jnp.asarray, s["agent"]),
    )


def make_linear_state(in_dim: int = 4, out_dim: int = 2, seed: int = 0) -> Dict[str, Any]:
    """A deterministic ``state`` dict servable by the ``linear`` builder —
    what the tests checkpoint, commit and hot-swap."""
    rng = np.random.default_rng(seed)
    return {
        "agent": {
            "w": rng.standard_normal((in_dim, out_dim)).astype(np.float32),
            "b": rng.standard_normal((out_dim,)).astype(np.float32),
        },
        "update": 0,
    }
