"""The model side of the serving tier: AOT-compiled batch ladder + hot swap.

**Batch ladder.** The policy forward is AOT-compiled once per rung of
``serve.batch_ladder`` via ``jax.jit(...).lower(...).compile()`` *before the
server accepts traffic*, so no request ever pays a JIT compile. At inference
a gathered micro-batch is zero-padded up to the nearest rung and the outputs
sliced back — a bounded ladder keeps the executable cache small while
padding waste stays under 2x with the default power-of-two rungs.

**Hot swap.** The AOT executables close over *shapes*, not weights: params
are a call argument. A newer committed checkpoint can therefore be promoted
atomically by replacing the params reference — no recompilation, no serving
gap. Promotion is validate-then-promote; a candidate must pass ALL of:

1. committed manifest present (torn writes are invisible by construction —
   the scan only sees :func:`committed_checkpoints`),
2. manifest ``tree_digest``/``leaf_count`` match the loaded state (detects a
   corrupted or foreign checkpoint behind a valid-looking manifest),
3. extracted params are structurally identical to the serving params (same
   treedef, leaf shapes and dtypes — the precondition for executable reuse),
4. all weights finite (a NaN-poisoned checkpoint must not reach traffic),
5. a smoke inference through the smallest rung returns finite outputs.

Any failure leaves the previous version serving (the "rollback" is that
promotion never happened); :meth:`ModelStore.rollback` additionally restores
the previous params if a promoted version misbehaves post-swap.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import numpy as np

from sheeprl_tpu.resilience.manifest import CommittedCheckpoint, committed_checkpoints, tree_digest
from sheeprl_tpu.resilience.sentinel import host_all_finite
from sheeprl_tpu.serve.errors import SwapRejected
from sheeprl_tpu.serve.fault_injection import ServeFaultSchedule
from sheeprl_tpu.utils.checkpoint import load_checkpoint


@dataclass
class ServedPolicy:
    """Everything the server needs to run one policy:

    - ``apply(params, obs_batch) -> action_batch`` — pure, jit-able; obs and
      action batches are pytrees whose leaves carry a leading batch dim,
    - ``params`` — the initial weights (from the checkpoint being served),
    - ``obs_spec`` — pytree of per-request ``jax.ShapeDtypeStruct`` (no batch
      dim) that requests must match,
    - ``params_from_state(state)`` — extract the params pytree from a raw
      loaded checkpoint state dict (used again at every hot swap).
    """

    name: str
    apply: Callable[[Any, Any], Any]
    params: Any
    obs_spec: Any
    params_from_state: Callable[[Dict[str, Any]], Any]


class ModelVersion(NamedTuple):
    step: int
    path: str
    params: Any


def _batched_spec(obs_spec: Any, batch: int) -> Any:
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct((batch,) + tuple(s.shape), s.dtype), obs_spec
    )


def stack_obs(obs_spec: Any, obs_list: Sequence[Any], batch: int) -> Any:
    """Stack per-request observations into one batch of size ``batch``
    (zero-padding past ``len(obs_list)``), coercing leaves to the spec dtype
    so they match what the executables were lowered against."""

    def build(spec: Any, *leaves: Any) -> np.ndarray:
        out = np.zeros((batch,) + tuple(spec.shape), dtype=spec.dtype)
        for i, leaf in enumerate(leaves):
            out[i] = np.asarray(leaf, dtype=spec.dtype)
        return out

    return jax.tree.map(build, obs_spec, *obs_list)


class CompiledLadder:
    """One AOT executable per batch rung, warmed eagerly at construction.

    With an :class:`~sheeprl_tpu.ops.aotcache.AotCache` each rung is first
    looked up as a serialized executable (keyed by the params *structure*,
    the rung's batched obs spec, and the topology — howto/aot_cache.md);
    only misses pay the compile, and those are stored for the next boot.
    ``device`` pins the key to a fleet replica's device: serialized
    executables bake in their device assignment, so replicas must never
    share entries across devices.
    """

    def __init__(
        self,
        policy: ServedPolicy,
        ladder: Sequence[int],
        *,
        aot_cache: Optional[Any] = None,
        device: Optional[Any] = None,
    ) -> None:
        self.policy = policy
        self.rungs = sorted({int(b) for b in ladder})
        self.compile_s: Dict[int, float] = {}
        self.from_cache: Dict[int, bool] = {}
        self._compiled: Dict[int, Any] = {}
        self._aot_cache = aot_cache
        self._keys: Dict[int, Any] = {}
        jitted = jax.jit(policy.apply)
        for b in self.rungs:
            t0 = time.perf_counter()
            spec = _batched_spec(policy.obs_spec, b)
            fn = None
            if aot_cache is not None:
                key = aot_cache.key(
                    tag=f"serve_ladder.{policy.name}",
                    avals=(policy.params, spec),
                    params=policy.params,
                    device=device,
                    extra={"rung": b},
                )
                self._keys[b] = key
                fn, hit = aot_cache.load_or_compile(
                    key, lambda: jitted.lower(policy.params, spec).compile()
                )
                self.from_cache[b] = hit
            else:
                fn = jitted.lower(policy.params, spec).compile()
                self.from_cache[b] = False
            self._compiled[b] = fn
            self.compile_s[b] = time.perf_counter() - t0

    def prewarm_cache(self) -> int:
        """Persist any rung whose cache entry is missing on disk (committed
        synchronously, so it is durable when this returns). Called by the
        hot-swap gauntlet just before the version flip: an accepted
        candidate is structurally identical to the serving params, so the
        incoming digest maps to these same entries — the next replica
        restart or scale-up deserializes instead of compiling. Returns the
        number of entries written; never raises (a failed store is a
        telemetry event and the swap proceeds)."""
        if self._aot_cache is None:
            return 0
        written = 0
        for b in self.rungs:
            key = self._keys.get(b)
            if key is None or self._aot_cache.has(key):
                continue
            self._aot_cache.store(key, self._compiled[b], sync=True)
            if self._aot_cache.has(key):
                written += 1
        return written

    @property
    def max_batch(self) -> int:
        return self.rungs[-1]

    def rung_for(self, n: int) -> int:
        for b in self.rungs:
            if b >= n:
                return b
        raise ValueError(f"batch of {n} exceeds top ladder rung {self.max_batch}")

    def run(self, params: Any, obs_list: Sequence[Any]) -> List[Any]:
        """Run ``len(obs_list)`` requests through the nearest rung; returns
        one host-side action pytree per request (padding sliced away)."""
        n = len(obs_list)
        rung = self.rung_for(n)
        batch = stack_obs(self.policy.obs_spec, obs_list, rung)
        return self.run_staged(params, batch, rung, n)

    def run_staged(self, params: Any, batch: Any, rung: int, n: int) -> List[Any]:
        """Run a pre-assembled (already rung-padded) batch — the slot-pool
        path, where obs were staged at admission — returning the first ``n``
        per-request host-side action pytrees."""
        out = jax.device_get(self._compiled[rung](params, batch))
        return [jax.tree.map(lambda leaf: leaf[i], out) for i in range(n)]


class ModelStore:
    """The atomically-swappable current model version.

    ``on_event(kind, info)`` (kinds ``swap`` / ``swap_rejected`` /
    ``rollback``) is the stats hook; exceptions from it are swallowed.
    """

    def __init__(
        self,
        policy: ServedPolicy,
        ladder: CompiledLadder,
        *,
        step: int,
        path: str,
        fault_schedule: Optional[ServeFaultSchedule] = None,
        on_event: Optional[Callable[[str, Dict[str, Any]], None]] = None,
    ) -> None:
        self.policy = policy
        self.ladder = ladder
        self._faults = fault_schedule
        self._on_event = on_event
        self._lock = threading.Lock()
        self._current = ModelVersion(int(step), str(path), policy.params)
        self._previous: Optional[ModelVersion] = None
        self.swap_attempts = 0
        self.swaps = 0
        self.swap_rejects = 0
        self.rollbacks = 0
        # online-learning hook: a VersionAuthority whose confirm() is called
        # with the promoted step AFTER each atomic flip — the gauntlet is the
        # only gate between a published version and a confirmed one
        self.version_authority: Optional[Any] = None

    # ---------------------------------------------------------------- serving
    @property
    def current(self) -> ModelVersion:
        return self._current  # reference read is atomic; swaps replace wholesale

    def infer(self, obs_list: Sequence[Any]) -> List[Any]:
        version = self._current
        return self.ladder.run(version.params, obs_list)

    # ------------------------------------------------------------------- swap
    def maybe_swap_newest(self, ckpt_dir: str) -> Optional[ModelVersion]:
        """Promote the newest committed checkpoint in ``ckpt_dir`` if it is
        strictly newer than the serving one. Returns the new version on
        promotion, ``None`` otherwise (including rejections, which are
        recorded, not raised — the watcher must keep serving)."""
        committed = committed_checkpoints(ckpt_dir)
        fresh = [c for c in committed if c.step > self._current.step]
        if not fresh:
            return None
        candidate = fresh[-1]
        ok, reason = self.try_swap(candidate)
        return self._current if ok else None

    def request_swap(self, candidate: CommittedCheckpoint) -> ModelVersion:
        """Explicit-swap API: promote or raise :class:`SwapRejected`."""
        ok, reason = self.try_swap(candidate)
        if not ok:
            raise SwapRejected(f"checkpoint {candidate.path} rejected: {reason}")
        return self._current

    def try_swap(self, candidate: CommittedCheckpoint) -> Tuple[bool, str]:
        """Validate-then-promote ``candidate``. Never raises on a bad
        checkpoint — returns ``(False, reason)`` and keeps serving."""
        self.swap_attempts += 1
        attempt = self.swap_attempts
        try:
            state = load_checkpoint(candidate.path)
        except Exception as err:
            return self._reject(candidate, f"load failed: {err!r}")

        man = candidate.manifest
        if man.get("tree_digest") is not None:
            leaf_count, digest = tree_digest(state)
            if (leaf_count, digest) != (man.get("leaf_count"), man.get("tree_digest")):
                return self._reject(
                    candidate,
                    f"state digest ({leaf_count}, {digest}) != manifest "
                    f"({man.get('leaf_count')}, {man.get('tree_digest')}) — torn or foreign checkpoint",
                )

        try:
            params = self.policy.params_from_state(state)
        except Exception as err:
            return self._reject(candidate, f"params extraction failed: {err!r}")

        mismatch = _structure_mismatch(self._current.params, params)
        if mismatch:
            return self._reject(candidate, f"params structure changed: {mismatch}")

        if self._faults is not None and self._faults.poison_swap(attempt):
            params = _poison(params)

        from sheeprl_tpu.obs import telemetry_deliberate_compiles

        # revalidation runs off the request path (watcher/replica threads)
        # and may trace fresh helpers (finite reduction, device_get trees) —
        # deliberate work, not a serving-path retrace
        with telemetry_deliberate_compiles("serve_swap_revalidation"):
            if not host_all_finite(jax.device_get(params)):
                return self._reject(candidate, "non-finite weights (poisoned checkpoint)")

            try:
                smoke = self.ladder.run(params, [_zero_obs(self.policy.obs_spec)])
                if not host_all_finite(smoke):
                    return self._reject(candidate, "smoke inference produced non-finite outputs")
            except Exception as err:
                return self._reject(candidate, f"smoke inference failed: {err!r}")

        # pre-populate executable-cache entries for the incoming digest
        # BEFORE the flip: the candidate passed the structure gauntlet, so
        # its executables are exactly the serving ones — after this, any
        # replica restart/scale-up under the new version boots from cache
        prewarmed = self.ladder.prewarm_cache()
        if prewarmed:
            from sheeprl_tpu.obs import telemetry_aot_cache

            telemetry_aot_cache(
                "prewarm", f"serve_ladder.{self.policy.name}", entries=prewarmed, step=candidate.step
            )

        with self._lock:
            self._previous = self._current
            self._current = ModelVersion(candidate.step, candidate.path, params)
            self.swaps += 1
        if self.version_authority is not None:
            try:
                self.version_authority.confirm(candidate.step)
            except Exception:
                pass
        from sheeprl_tpu.obs.trace import trace_event

        # the terminal link of the online-learning causal chain: request →
        # exp_slab → online_update → param_publish → model_swap
        trace_event("model_swap", ckpt_step=candidate.step, attempt=attempt)
        self._emit("swap", {"step": candidate.step, "path": candidate.path, "attempt": attempt})
        return True, "promoted"

    def rollback(self) -> Optional[ModelVersion]:
        """Restore the previous version (post-swap escape hatch). Returns the
        now-serving version, or ``None`` when there is nothing to roll back."""
        with self._lock:
            if self._previous is None:
                return None
            bad, self._current, self._previous = self._current, self._previous, None
            self.rollbacks += 1
        self._emit("rollback", {"from_step": bad.step, "to_step": self._current.step})
        return self._current

    # ------------------------------------------------------------------ misc
    def _reject(self, candidate: CommittedCheckpoint, reason: str) -> Tuple[bool, str]:
        self.swap_rejects += 1
        self._emit("swap_rejected", {"step": candidate.step, "path": candidate.path, "reason": reason})
        return False, reason

    def _emit(self, kind: str, info: Dict[str, Any]) -> None:
        if self._on_event is not None:
            try:
                self._on_event(kind, info)
            except Exception:
                pass


def _structure_mismatch(current: Any, new: Any) -> Optional[str]:
    """Human-readable first difference between two param trees (treedef,
    leaf shapes or dtypes), or ``None`` when they are executable-compatible."""
    cur_flat, cur_def = jax.tree.flatten(current)
    new_flat, new_def = jax.tree.flatten(new)
    if cur_def != new_def:
        return f"tree structure differs ({cur_def} vs {new_def})"
    for i, (a, b) in enumerate(zip(cur_flat, new_flat)):
        a_shape, b_shape = np.shape(a), np.shape(b)
        if a_shape != b_shape:
            return f"leaf {i} shape {b_shape} != serving {a_shape}"
        a_dtype = getattr(a, "dtype", np.asarray(a).dtype)
        b_dtype = getattr(b, "dtype", np.asarray(b).dtype)
        if a_dtype != b_dtype:
            return f"leaf {i} dtype {b_dtype} != serving {a_dtype}"
    return None


def _poison(params: Any) -> Any:
    """NaN-poison the first inexact leaf (fault injection: a checkpoint whose
    weights were corrupted after commit)."""
    flat, treedef = jax.tree.flatten(params)
    for i, leaf in enumerate(flat):
        arr = np.asarray(jax.device_get(leaf))
        if arr.dtype.kind == "f":
            bad = arr.copy()
            bad.flat[0] = np.nan
            flat[i] = bad
            break
    return jax.tree.unflatten(treedef, flat)


def _zero_obs(obs_spec: Any) -> Any:
    return jax.tree.map(lambda s: np.zeros(tuple(s.shape), dtype=s.dtype), obs_spec)


def newest_committed(ckpt_dir: str) -> Optional[CommittedCheckpoint]:
    from sheeprl_tpu.resilience.discovery import newest_committed as _newest_committed

    return _newest_committed(ckpt_dir)
