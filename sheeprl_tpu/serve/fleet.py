"""Replica-fleet serving: N data-parallel policy replicas behind one router.

The single :class:`~sheeprl_tpu.serve.server.PolicyServer` multiplexes
replica *threads* over one queue and one params reference. The fleet is the
next structural step: each :class:`FleetSlot` is a full serving unit — its
own continuous-batching :class:`~sheeprl_tpu.serve.slots.SlotPool`, its own
AOT ladder compiled for its *device*, its own device-resident copy of the
params (data-parallel placement, re-placed per hot-swap version) — and the
:class:`~sheeprl_tpu.serve.router.Router` in front owns every fleet-wide
decision. Composition:

- **supervision** — the single-server doctrine (detect dead/hung, restart
  under a :class:`~sheeprl_tpu.rollout.supervisor.RestartBudget` with
  exponential backoff, mask when the budget is spent, keep serving degraded
  on N-1) is re-instantiated per slot, with one fleet-shaping change: a dead
  replica's queued + in-flight work is *re-routed at the front of a sibling*
  (``router.reroute``) before the restart is even scheduled. The
  crash-requeue-at-front contract survives the jump from one queue to N.
- **elastic scaling** — the monitor doubles as the autoscaler: sustained
  queue depth per active replica above ``scale_up_depth`` activates a
  standby slot (its ladder is compiled *before* it takes traffic — warmup
  precedes routing, same as server start); sustained depth below
  ``scale_down_depth`` retires the newest active slot (router stops routing,
  its work re-homes, the thread drains out). ``min_replicas`` /
  ``max_replicas`` bound both directions.
- **CPU spill** — optional ``cpu_spill_replicas`` slots compiled for the
  host backend absorb ``batch``-priority traffic (eval / loadgen) when the
  device replicas are queueing past ``spill_depth``, keeping interactive
  latency flat while bulk traffic degrades gracefully instead of shedding.
- **chaos surface** — ``kill_replica(i)`` is the drill entry point: the
  replica dies *without completing its in-flight futures* (the worst legal
  crash), and the acceptance drill asserts zero admitted requests are
  dropped while the survivors hold the SLO.

:class:`FleetServer` keeps the exact :class:`PolicyServer` facade (``infer``
/ ``submit`` / ``wait`` / ``snapshot`` / ``request_swap``), so the client,
the load generator and the telemetry pipeline serve either tier unchanged.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from sheeprl_tpu.obs.telemetry import telemetry_request_path
from sheeprl_tpu.obs.trace import trace_event
from sheeprl_tpu.resilience.manifest import CommittedCheckpoint, read_manifest
from sheeprl_tpu.rollout.supervisor import RestartBudget
from sheeprl_tpu.serve.config import ServeConfig
from sheeprl_tpu.serve.errors import DeadlineExceeded, ServerClosed, SwapRejected
from sheeprl_tpu.serve.fault_injection import ServeFaultSchedule
from sheeprl_tpu.serve.model import CompiledLadder, ModelStore, ModelVersion, ServedPolicy
from sheeprl_tpu.serve.replica import InjectedCrash, ReplicaStats
from sheeprl_tpu.serve.router import INTERACTIVE, RoutedRequest, Router, RouteTarget
from sheeprl_tpu.serve.server import ServeStats
from sheeprl_tpu.serve.slots import SlotPool, safe_complete

DEVICE = "device"
CPU_SPILL = "cpu_spill"
REMOTE = "remote"  # per-host agent adopted over TCP (sheeprl_tpu.net.remote)


class FleetReplica(threading.Thread):
    """One serving incarnation bound to one slot's pool/ladder/device.

    Differences from the single-server replica are exactly the fleet
    contracts: work it cannot finish stays *in its pool* (in-flight window
    included) for the router to re-home, and ``kill()`` makes it die without
    completing futures — the crash shape the chaos drill injects.
    """

    def __init__(
        self,
        index: int,
        *,
        pool: SlotPool,
        ladder: CompiledLadder,
        store: ModelStore,
        device: Any,
        stats: ReplicaStats,
        batch_counter: Any,
        breaker_threshold: int,
        fault_schedule: Optional[ServeFaultSchedule] = None,
        poll_timeout_s: float = 0.05,
        on_batch: Optional[Callable[[int, float], None]] = None,
        on_shed: Optional[Callable[[str], None]] = None,
    ) -> None:
        super().__init__(name=f"fleet-replica-{index}", daemon=True)
        self.index = index
        self.pool = pool
        self.ladder = ladder
        self.store = store
        self.device = device
        self.stats = stats
        self._batch_counter = batch_counter
        self.breaker_threshold = int(breaker_threshold)
        self._faults = fault_schedule
        self._poll_timeout_s = float(poll_timeout_s)
        self._on_batch = on_batch
        self._on_shed = on_shed
        self._stop_evt = threading.Event()
        self._killed = threading.Event()
        self._params_step: Optional[int] = None
        self._params: Any = None
        self.exit_reason: Optional[str] = None

    def request_stop(self) -> None:
        self._stop_evt.set()

    def kill(self) -> None:
        """Chaos entry point: die at the next check WITHOUT completing
        in-flight futures. The work stays in the pool for re-routing."""
        self._killed.set()
        self._stop_evt.set()

    # ------------------------------------------------------------------- loop
    def run(self) -> None:  # pragma: no cover - exercised via the fleet tests
        try:
            self._loop()
        except InjectedCrash as err:
            self.exit_reason = f"injected crash: {err}"
        except Exception as err:
            self.exit_reason = f"crashed: {err!r}"
        else:
            self.exit_reason = (
                "killed" if self._killed.is_set() else self.exit_reason or "stopped"
            )

    def _loop(self) -> None:
        while not self._stop_evt.is_set() and not self.pool.closed:
            self.stats.beat()
            batch = self.pool.take_batch(self._poll_timeout_s)
            if self._killed.is_set():
                return  # batch (if any) stays in the in-flight window
            if not batch:
                continue
            self._serve_batch(batch)

    def _serve_batch(self, batch: List[Any]) -> None:
        batch_index = next(self._batch_counter)
        if self._faults is not None:
            for fault in self._faults.batch_faults(self.index, batch_index):
                if fault.kind == "slow_inference":
                    self._sleep_injected(fault.duration_s)
                elif fault.kind == "replica_crash":
                    # the batch stays in the pool's in-flight window; the
                    # fleet monitor re-routes it at the front of a sibling
                    raise InjectedCrash(f"scheduled replica_crash at batch {batch_index}")
        t0 = time.monotonic()
        try:
            params = self._params_for()
            rung = self.ladder.rung_for(len(batch))
            staged = self.pool.staged_batch(batch, rung)
            t_staged = time.monotonic()
            outputs = self.ladder.run_staged(params, staged, rung, len(batch))
            t_done = time.monotonic()
        except Exception as err:
            self.stats.failures += 1
            self.stats.consecutive_failures += 1
            self.pool.requeue_failed(batch)
            if self.stats.consecutive_failures >= self.breaker_threshold:
                raise RuntimeError(
                    f"circuit breaker open after {self.stats.consecutive_failures} "
                    f"consecutive inference failures"
                ) from err
            return
        if self._killed.is_set():
            return  # die before delivery: futures stay pending → re-routed
        latency_s = time.monotonic() - t0
        self.stats.consecutive_failures = 0
        self.stats.batches += 1
        self.stats.requests += len(batch)
        self.stats.beat()
        now = time.monotonic()
        for req, out in zip(batch, outputs):
            if req.future.done():
                continue  # hedge twin won
            if req.expired(now):
                req.fail_expired(now)
                if self._on_shed is not None:
                    try:
                        self._on_shed("expired")
                    except Exception:
                        pass
            else:
                # stamp the serving checkpoint step BEFORE completion: the
                # online bridge reads it off the request right after wait()
                req.served_step = self._params_step
                delivered = safe_complete(req, out)
                if delivered and req.trace_id:
                    # critical-path decomposition, measured at the replica
                    # that actually delivered the result: queue-wait is
                    # admission→this batch's start, assembly is the staging
                    # row-gather + params placement, compute is the dispatch
                    queue_wait_ms = (t0 - req.enqueue_t) * 1e3
                    assembly_ms = (t_staged - t0) * 1e3
                    compute_ms = (t_done - t_staged) * 1e3
                    hedged = len(getattr(req, "placements", ())) > 1
                    rerouted = getattr(req, "rerouted", 0) > 0
                    trace_event(
                        "request_done",
                        req.trace_id,
                        rid=req.rid,
                        replica=self.index,
                        batch=len(batch),
                        queue_wait_ms=queue_wait_ms,
                        assembly_ms=assembly_ms,
                        compute_ms=compute_ms,
                        hedged=hedged,
                        rerouted=rerouted,
                    )
                    telemetry_request_path(
                        queue_wait_ms=queue_wait_ms,
                        assembly_ms=assembly_ms,
                        compute_ms=compute_ms,
                        hedged=hedged,
                        rerouted=rerouted,
                    )
        self.pool.complete_batch(batch)
        if self._on_batch is not None:
            try:
                self._on_batch(len(batch), latency_s)
            except Exception:
                pass

    def _params_for(self) -> Any:
        """The serving version's params, placed on this replica's device
        (re-placed once per promoted version, not per batch)."""
        version = self.store.current
        if self._params_step != version.step:
            params = version.params
            if self.device is not None:
                import jax

                try:
                    params = jax.device_put(version.params, self.device)
                except Exception:
                    params = version.params
            self._params = params
            self._params_step = version.step
        return self._params

    def _sleep_injected(self, duration_s: float) -> None:
        end = time.monotonic() + duration_s
        while not self._stop_evt.is_set():
            remaining = end - time.monotonic()
            if remaining <= 0:
                return
            self.stats.beat()  # slow, not hung
            time.sleep(min(0.02, remaining))


class FleetSlot:
    """One supervised fleet position. The slot — not any thread incarnation —
    owns the pool, the batch counter, the restart budget, the device binding
    and the activation state, so all of them survive restarts."""

    def __init__(self, index: int, kind: str, config: ServeConfig, *, obs_spec: Any = None) -> None:
        import itertools

        self.index = index
        self.kind = kind
        self.device: Any = None
        self.remote_addr: Optional[str] = None  # REMOTE slots: agent host:port
        self.pool = SlotPool(
            capacity=config.max_batch,
            backlog_bound=config.fleet.backlog_per_replica,
            obs_spec=obs_spec,
        )
        self.batch_counter = itertools.count()
        self.budget = RestartBudget(config.max_restarts, config.restart_refund_s)
        self.thread: Optional[Any] = None  # FleetReplica | net.remote.RemoteReplica
        self.stats: Optional[ReplicaStats] = None
        self.ladder: Optional[CompiledLadder] = None
        self.active = False  # routable position (autoscaler toggles)
        self.retiring = False
        self.masked = False
        self.mask_reason: Optional[str] = None
        self.restart_at: Optional[float] = None
        self.restart_deferrals = 0  # restarts held back by a hung-alive thread
        self.restarts = 0
        self.total_requests = 0
        self.total_failures = 0

    @property
    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def health(self, now: float, timeout_s: float) -> float:
        """Routing weight in [0, 1]: 0 = unroutable, decaying with heartbeat
        age so a struggling replica sheds traffic before it is declared
        hung."""
        if not self.active or self.masked or self.retiring or not self.alive:
            return 0.0
        if self.restart_at is not None:
            # declared hung, awaiting restart: the thread may be alive (stuck
            # in a dispatch) but nothing will serve new work until respawn
            return 0.0
        if self.stats is None:
            return 0.0
        age = max(0.0, now - self.stats.heartbeat)
        return max(0.05, 1.0 - age / max(timeout_s, 1e-6))

    def fold_stats(self) -> None:
        if self.stats is not None:
            self.total_requests += self.stats.requests
            self.total_failures += self.stats.failures


class FleetServer:
    """N supervised replicas + router behind the ``PolicyServer`` facade."""

    def __init__(
        self,
        policy: ServedPolicy,
        config: ServeConfig,
        *,
        step: int,
        path: str,
        ckpt_dir: Optional[str] = None,
        on_event: Optional[Callable[[str, Dict[str, Any]], None]] = None,
    ) -> None:
        if not config.fleet.enabled:
            raise ValueError("FleetServer requires serve.fleet.enabled=true")
        self.config = config
        self.policy = policy
        self.step = int(step)
        self.path = str(path)
        self.ckpt_dir = ckpt_dir
        self._on_event = on_event
        self.stats = ServeStats()
        self.fault_schedule = ServeFaultSchedule(config.faults) if config.faults else None
        self.slots: List[FleetSlot] = []
        self.router: Optional[Router] = None
        self.store: Optional[ModelStore] = None
        self.aot_cache: Optional[Any] = None
        self._ladders: Dict[Any, CompiledLadder] = {}  # device -> compiled ladder
        self._monitor_thread: Optional[threading.Thread] = None
        self._swap_thread: Optional[threading.Thread] = None
        self._closing = threading.Event()
        self._started = False
        self._lock = threading.Lock()
        self.warmup_s: Dict[int, float] = {}
        self.scale_ups = 0
        self.scale_downs = 0
        self._pressure_streak = 0
        self._idle_streak = 0
        self._last_autoscale_t = 0.0

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "FleetServer":
        """Warm the initial replicas' ladders, place params, open the front
        door. When this returns every initially-active replica is compiled
        and pulling; standby slots compile at activation, before routing."""
        if self._started:
            return self
        import jax

        if self.config.aot_cache_dir:
            from sheeprl_tpu.ops.aotcache import AotCache

            # one cache shared by every per-device ladder (entries are keyed
            # by device, so replicas never load a sibling's executable)
            self.aot_cache = AotCache(self.config.aot_cache_dir)
        fleet = self.config.fleet
        devices = self._device_ring()
        spill_devices = self._spill_devices()
        for i in range(fleet.max_replicas):
            slot = FleetSlot(i, DEVICE, self.config, obs_spec=self.policy.obs_spec)
            slot.device = devices[i % len(devices)] if devices else None
            self.slots.append(slot)
        for j in range(fleet.cpu_spill_replicas):
            slot = FleetSlot(
                fleet.max_replicas + j, CPU_SPILL, self.config, obs_spec=self.policy.obs_spec
            )
            slot.device = spill_devices[j % len(spill_devices)] if spill_devices else None
            self.slots.append(slot)
        for k, addr in enumerate(fleet.remote_agents):
            # a per-host agent adopted as one slot: the pool/budget/counter
            # live HERE, so re-route-at-front and budgeted restarts (which
            # for this kind are reconnects) run on unchanged machinery
            slot = FleetSlot(
                fleet.max_replicas + fleet.cpu_spill_replicas + k,
                REMOTE,
                self.config,
                obs_spec=self.policy.obs_spec,
            )
            slot.remote_addr = str(addr)
            self.slots.append(slot)

        base_ladder = self._ladder_for(None)
        self.warmup_s = dict(base_ladder.compile_s)
        self.store = ModelStore(
            self.policy,
            base_ladder,
            step=self.step,
            path=self.path,
            fault_schedule=self.fault_schedule,
            on_event=self._event,
        )
        for slot in self.slots:
            if slot.kind == DEVICE and slot.index >= fleet.num_replicas:
                continue  # standby: warms at activation
            slot.active = True
            if slot.kind != REMOTE:  # remote compute lives agent-side
                slot.ladder = self._ladder_for(slot.device)
            self._spawn(slot)

        self.router = Router(
            targets=self._route_targets,
            max_pending=fleet.resolved_max_pending(self.config),
            slo_s=self.config.slo_ms / 1e3,
            hedge_quantile=fleet.hedge_quantile,
            hedge_floor_s=fleet.hedge_floor_ms / 1e3,
            hedge_max=fleet.hedge_max,
            hedge_scan_s=fleet.hedge_scan_ms / 1e3,
            spill_depth=fleet.spill_depth,
            fault_schedule=self.fault_schedule,
            on_event=self._event,
        ).start()

        self._monitor_thread = threading.Thread(
            target=self._monitor, name="fleet-monitor", daemon=True
        )
        self._monitor_thread.start()
        if self.config.swap_poll_s > 0 and self.ckpt_dir:
            self._swap_thread = threading.Thread(
                target=self._swap_watch, name="fleet-swap-watch", daemon=True
            )
            self._swap_thread.start()
        self.stats.mark_started()
        self._started = True
        return self

    def close(self) -> None:
        self._closing.set()
        if self.router is not None:
            self.router.close()
        if self._monitor_thread is not None:
            self._monitor_thread.join(2.0)
        for slot in self.slots:
            if slot.thread is not None:
                slot.thread.request_stop()
        deadline = time.monotonic() + 2.0
        for slot in self.slots:
            if slot.thread is not None:
                slot.thread.join(max(0.0, deadline - time.monotonic()))
            slot.fold_stats()
            slot.pool.close()
        if self._swap_thread is not None:
            self._swap_thread.join(1.0)
        if self.aot_cache is not None:
            # drain queued executable stores (writer thread joins) so the
            # next spawn against this cache dir boots from cache
            self.aot_cache.close()

    def __enter__(self) -> "FleetServer":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # ------------------------------------------------------------ request path
    def submit(
        self,
        obs: Any,
        deadline_s: Optional[float] = None,
        *,
        priority: str = INTERACTIVE,
        idempotent: bool = True,
    ) -> RoutedRequest:
        if not self._started or self.router is None:
            raise ServerClosed("fleet not started: warmup has not run")
        self.stats.record_submit()
        try:
            return self.router.submit(
                obs,
                deadline_s or self.config.default_deadline_s,
                idempotent=idempotent,
                priority=priority,
            )
        except Exception as err:
            from sheeprl_tpu.serve.errors import Overloaded

            if isinstance(err, Overloaded):
                self.stats.record_shed("overloaded")
            self.stats.record_failed()
            raise

    def infer(
        self,
        obs: Any,
        deadline_s: Optional[float] = None,
        *,
        priority: str = INTERACTIVE,
        idempotent: bool = True,
    ) -> Any:
        req = self.submit(obs, deadline_s, priority=priority, idempotent=idempotent)
        return self.wait(req)

    def wait(self, req: RoutedRequest) -> Any:
        from concurrent.futures import TimeoutError as FutureTimeout

        budget = max(0.0, req.deadline_t - time.monotonic()) + 0.25
        try:
            out = req.future.result(timeout=budget)
        except DeadlineExceeded:
            self.stats.record_failed()
            raise
        except (TimeoutError, FutureTimeout):
            self.stats.record_failed()
            now = time.monotonic()
            raise DeadlineExceeded(now - req.enqueue_t, req.deadline_t - req.enqueue_t) from None
        except Exception:
            self.stats.record_failed()
            raise
        latency = time.monotonic() - req.enqueue_t
        self.stats.record_complete(latency)
        if self.router is not None:
            self.router.record_latency(latency)
        return out

    # ------------------------------------------------------------------ chaos
    def kill_replica(self, index: int) -> bool:
        """Drill API: make replica ``index`` die without completing its
        in-flight futures. Returns False when it has no live thread."""
        slot = self.slots[index]
        if slot.thread is None or not slot.thread.is_alive():
            return False
        slot.thread.kill()
        self._event("replica_killed", {"replica": index})
        trace_event("replica_killed", replica=index)  # process-scoped (tid 0)
        return True

    # ------------------------------------------------------------------- swap
    def request_swap(self, ckpt_path: str) -> ModelVersion:
        if self.store is None:
            raise ServerClosed("fleet not started")
        man = read_manifest(ckpt_path)
        if man is None:
            raise SwapRejected(f"checkpoint {ckpt_path} has no commit manifest (torn or foreign write)")
        return self.store.request_swap(CommittedCheckpoint(int(man["step"]), ckpt_path, man))

    def maybe_swap(self) -> Optional[ModelVersion]:
        if self.store is None or not self.ckpt_dir:
            return None
        return self.store.maybe_swap_newest(self.ckpt_dir)

    def _swap_watch(self) -> None:
        while not self._closing.wait(self.config.swap_poll_s):
            try:
                self.maybe_swap()
            except Exception:
                pass

    # ------------------------------------------------------------------ stats
    def snapshot(self) -> Dict[str, Any]:
        snap = self.stats.snapshot()
        snap["slo_ms"] = self.config.slo_ms
        snap["batch_ladder"] = list(self.config.batch_ladder)
        snap["warmup_s"] = dict(self.warmup_s)
        if self.aot_cache is not None:
            snap["aot_cache"] = self.aot_cache.stats()
            with self._lock:
                ladders = dict(self._ladders)
            snap["ladder_from_cache"] = {
                str(dev): dict(ladder.from_cache) for dev, ladder in ladders.items()
            }
        snap["queue_depth"] = self.router.pending_depth() if self.router else 0
        routable = [s for s in self.slots if s.active and not s.masked]
        snap["replicas_alive"] = sum(1 for s in routable if s.alive)
        snap["replicas_masked"] = sum(1 for s in self.slots if s.masked)
        snap["restarts"] = sum(s.restarts for s in self.slots)
        snap["degraded"] = snap["replicas_masked"] > 0
        if self.store is not None:
            snap["serving_step"] = self.store.current.step
            snap["swaps"] = self.store.swaps
            snap["swap_rejects"] = self.store.swap_rejects
            snap["rollbacks"] = self.store.rollbacks
        now = time.monotonic()
        snap["fleet"] = {
            "active_device_replicas": sum(
                1 for s in self.slots if s.kind == DEVICE and s.active and not s.masked
            ),
            "cpu_spill_replicas": sum(1 for s in self.slots if s.kind == CPU_SPILL and s.active),
            "remote_replicas": sum(
                1 for s in self.slots if s.kind == REMOTE and s.active and not s.masked
            ),
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "router": self.router.snapshot() if self.router else {},
            "replicas": [
                {
                    "index": s.index,
                    "kind": s.kind,
                    "device": str(s.device) if s.device is not None else None,
                    "remote": s.remote_addr,
                    "active": s.active,
                    "alive": s.alive,
                    "masked": s.masked,
                    "retiring": s.retiring,
                    "restarts": s.restarts,
                    "health": round(s.health(now, self.config.replica_timeout_s), 3),
                    "depth": s.pool.depth(),
                    "outstanding": s.pool.outstanding(),
                    "requests": s.total_requests
                    + (s.stats.requests if s.stats is not None else 0),
                    "failures": s.total_failures
                    + (s.stats.failures if s.stats is not None else 0),
                }
                for s in self.slots
            ],
        }
        return snap

    # ---------------------------------------------------------------- monitor
    def _route_targets(self) -> List[RouteTarget]:
        now = time.monotonic()
        timeout = self.config.replica_timeout_s
        return [
            RouteTarget(s.index, s.pool, s.health(now, timeout), s.kind)
            for s in self.slots
            if s.active
        ]

    def _monitor(self) -> None:
        interval = self.config.monitor_interval_s
        fleet = self.config.fleet
        self._last_autoscale_t = time.monotonic()
        while not self._closing.is_set():
            now = time.monotonic()
            for slot in self.slots:
                try:
                    self._supervise_slot(slot, now)
                except Exception as err:
                    # one bad pass on one slot must not kill the fleet's only
                    # supervision thread (mirrors the hedge scan's loop)
                    self._event(
                        "monitor_error", {"replica": slot.index, "error": repr(err)}
                    )
            if now - self._last_autoscale_t >= fleet.autoscale_interval_s:
                self._last_autoscale_t = now
                try:
                    self._autoscale()
                except Exception as err:
                    self._event("monitor_error", {"replica": None, "error": repr(err)})
            self._closing.wait(interval)

    def _supervise_slot(self, slot: FleetSlot, now: float) -> None:
        if not slot.active or slot.masked:
            return
        if slot.restart_at is not None:
            if now < slot.restart_at:
                return
            prev = slot.thread
            if prev is not None and prev.is_alive():
                prev.join(0.05)
            if prev is not None and prev.is_alive():
                # the hung incarnation is still inside a dispatch on this
                # pool: a second thread on the same pool would race it, so
                # the restart waits until the old thread is confirmed dead
                # (its late complete/requeue is ownership-checked anyway)
                slot.restart_at = now + max(self.config.monitor_interval_s, 0.05)
                if slot.restart_deferrals == 0:
                    self._event("replica_restart_deferred", {"replica": slot.index})
                slot.restart_deferrals += 1
                return
            slot.restart_at = None
            slot.restart_deferrals = 0
            self._spawn(slot)
            return
        if not slot.alive:
            reason = (
                slot.thread.exit_reason if slot.thread is not None else None
            ) or "thread exited"
            self._handle_fault(slot, reason)
        elif (
            slot.stats is not None
            and now - slot.stats.heartbeat > self.config.replica_timeout_s
        ):
            age = now - slot.stats.heartbeat
            slot.thread.request_stop()
            self._event("replica_hung", {"replica": slot.index, "heartbeat_age_s": age})
            self._handle_fault(slot, f"hung (heartbeat {age:.1f}s stale)")

    def _handle_fault(self, slot: FleetSlot, reason: str) -> None:
        """Crash-requeue-at-front, fleet edition: the dead replica's work is
        re-routed to a sibling FIRST, then the restart/mask decision runs —
        recovery of the *work* never waits on recovery of the *worker*."""
        if self.router is not None:
            # a dead thread's in-flight window re-homes in full; a hung but
            # still-alive thread may yet finish its dispatch, so only its
            # idempotent requests are duplicated (hedge semantics) — the
            # rest complete when it wakes or expire by their own deadline
            alive = slot.thread is not None and slot.thread.is_alive()
            self.router.reroute(
                slot.index,
                slot.pool,
                reason,
                inflight="idempotent" if alive else "all",
            )
        slot.fold_stats()
        if slot.budget.exhausted:
            slot.masked = True
            slot.mask_reason = reason
            slot.thread = None
            slot.stats = None
            self._event(
                "replica_masked",
                {
                    "replica": slot.index,
                    "reason": reason,
                    "restarts": slot.restarts,
                    "alive": sum(1 for s in self.slots if s.alive),
                    "degraded": True,
                },
            )
            return
        charge = slot.budget.charge()
        slot.restarts += 1
        backoff = self.config.backoff_s(charge)
        slot.restart_at = time.monotonic() + backoff
        self._event(
            "replica_restart",
            {
                "replica": slot.index,
                "reason": reason,
                "restarts": slot.restarts,
                "backoff_s": backoff,
            },
        )

    def _autoscale(self) -> None:
        fleet = self.config.fleet

        def active_device() -> List[FleetSlot]:
            return [s for s in self.slots if s.kind == DEVICE and s.active and not s.masked]

        device_slots = active_device()
        # emergency floor, no patience: masking can drop the fleet below
        # min_replicas — even to zero, where no queue-depth signal could ever
        # fire again — so standby slots are re-activated immediately. The
        # hedge scan then re-places every stranded request on the recovered
        # capacity.
        if len(device_slots) < fleet.min_replicas:
            standby = [
                s for s in self.slots if s.kind == DEVICE and not s.active and not s.masked
            ]
            for slot in standby[: fleet.min_replicas - len(device_slots)]:
                slot.retiring = False
                slot.active = True
                self._spawn(slot)
                self.scale_ups += 1
                self._event(
                    "fleet_scale_up",
                    {"replica": slot.index, "reason": "below_min_replicas"},
                )
            device_slots = active_device()
        if not device_slots:
            return
        depth_per = sum(s.pool.depth() for s in device_slots) / len(device_slots)
        if depth_per >= fleet.scale_up_depth:
            self._pressure_streak += 1
            self._idle_streak = 0
        elif depth_per <= fleet.scale_down_depth:
            self._idle_streak += 1
            self._pressure_streak = 0
        else:
            self._pressure_streak = 0
            self._idle_streak = 0
        if self._pressure_streak >= fleet.scale_patience:
            self._pressure_streak = 0
            standby = [
                s
                for s in self.slots
                if s.kind == DEVICE and not s.active and not s.masked
            ]
            if standby:
                slot = standby[0]
                slot.retiring = False
                slot.active = True
                self._spawn(slot)  # compiles its ladder before it is routable
                self.scale_ups += 1
                self._event(
                    "fleet_scale_up",
                    {"replica": slot.index, "depth_per_replica": depth_per},
                )
        elif self._idle_streak >= fleet.scale_patience:
            self._idle_streak = 0
            if len(device_slots) > fleet.min_replicas:
                slot = device_slots[-1]
                slot.retiring = True  # router stops targeting it immediately
                if self.router is not None:
                    # a healthy retiring thread finishes its own in-flight
                    # dispatch (re-homing it would double-run non-idempotent
                    # requests); only its queued work moves to a sibling
                    alive = slot.thread is not None and slot.thread.is_alive()
                    self.router.reroute(
                        slot.index,
                        slot.pool,
                        "scale_down",
                        inflight="none" if alive else "all",
                    )
                if slot.thread is not None:
                    slot.thread.request_stop()
                slot.active = False
                slot.retiring = False
                self.scale_downs += 1
                self._event("fleet_scale_down", {"replica": slot.index})

    # --------------------------------------------------------------- internal
    def _spawn(self, slot: FleetSlot) -> None:
        prev = slot.thread
        if prev is not None and prev.is_alive():
            # never run two incarnations on one pool: stop the old thread and
            # give it a beat to exit; if it is still alive (hung mid-dispatch,
            # or a retired thread draining its window) arm a deferred restart
            # and let the monitor spawn once it is confirmed dead
            prev.request_stop()
            prev.join(0.05)
            if prev.is_alive():
                slot.restart_at = time.monotonic() + max(
                    self.config.monitor_interval_s, 0.05
                )
                return
        if slot.kind == REMOTE:
            from sheeprl_tpu.net.remote import RemoteReplica

            slot.stats = ReplicaStats()
            # generation rides the restart count: the agent's handshake trace
            # distinguishes a reconnect from a first attach, mirroring the
            # actor transport's generation bump
            slot.thread = RemoteReplica(
                slot.index,
                pool=slot.pool,
                addr=slot.remote_addr,
                stats=slot.stats,
                batch_counter=slot.batch_counter,
                breaker_threshold=self.config.breaker_threshold,
                timeout_s=self.config.fleet.remote_timeout_s,
                generation=slot.restarts,
                on_batch=self.stats.record_batch,
                on_shed=self.stats.record_shed,
            )
            slot.thread.start()
            return
        if slot.ladder is None:
            slot.ladder = self._ladder_for(slot.device)
        slot.stats = ReplicaStats()
        slot.thread = FleetReplica(
            slot.index,
            pool=slot.pool,
            ladder=slot.ladder,
            store=self.store,
            device=slot.device,
            stats=slot.stats,
            batch_counter=slot.batch_counter,
            breaker_threshold=self.config.breaker_threshold,
            fault_schedule=self.fault_schedule,
            on_batch=self.stats.record_batch,
            on_shed=self.stats.record_shed,
        )
        slot.thread.start()

    def _ladder_for(self, device: Any) -> CompiledLadder:
        """One AOT ladder per distinct device, compiled on first use (fleet
        start for initial replicas, activation for standbys)."""
        with self._lock:
            if device in self._ladders:
                return self._ladders[device]
        from sheeprl_tpu.obs import telemetry_deliberate_compiles

        import jax

        with telemetry_deliberate_compiles("serve_batch_ladder"):
            if device is None:
                ladder = CompiledLadder(
                    self.policy, self.config.batch_ladder, aot_cache=self.aot_cache
                )
            else:
                try:
                    with jax.default_device(device):
                        ladder = CompiledLadder(
                            self.policy,
                            self.config.batch_ladder,
                            aot_cache=self.aot_cache,
                            device=device,
                        )
                except Exception:
                    ladder = self._ladder_for(None)
        with self._lock:
            self._ladders.setdefault(device, ladder)
            return self._ladders[device]

    def _device_ring(self) -> List[Any]:
        import jax

        try:
            return list(jax.local_devices())
        except Exception:
            return []

    def _spill_devices(self) -> List[Any]:
        import jax

        try:
            cpus = list(jax.devices("cpu"))
            if cpus:
                return cpus
        except Exception:
            pass
        return self._device_ring()

    def _event(self, kind: str, info: Dict[str, Any]) -> None:
        self.stats.record_event(kind)
        if self._on_event is not None:
            try:
                self._on_event(kind, info)
            except Exception:
                pass
