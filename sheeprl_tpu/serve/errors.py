"""Typed request-path failures of the policy-serving tier.

Every way a request can fail is a distinct exception type so clients branch
on ``except`` clauses, not string matching — and so the load-shedding
contract is explicit: an overloaded server REJECTS (``Overloaded``, returned
immediately at admission) instead of queueing without bound and timing every
request out.
"""

from __future__ import annotations


class ServeError(RuntimeError):
    """Base class for policy-serving failures."""


class Overloaded(ServeError):
    """Admission control rejected the request: the queue is at its bound.

    ``retry_after_s`` is the server's backoff hint (one gather window — by
    then at least one batch has drained); :class:`~sheeprl_tpu.serve.client.
    ServeClient` sleeps it (with jittered exponential growth) before retrying.
    """

    def __init__(self, depth: int, bound: int, retry_after_s: float) -> None:
        super().__init__(f"serving queue at bound ({depth}/{bound}); retry after {retry_after_s:.3f}s")
        self.depth = depth
        self.bound = bound
        self.retry_after_s = retry_after_s


class DeadlineExceeded(ServeError):
    """The request's deadline elapsed before an inference completed it."""

    def __init__(self, waited_s: float, deadline_s: float) -> None:
        super().__init__(f"request deadline exceeded ({waited_s:.3f}s waited, deadline {deadline_s:.3f}s)")
        self.waited_s = waited_s
        self.deadline_s = deadline_s


class ServerClosed(ServeError):
    """The server is shutting down (or never started); nothing was enqueued."""


class InferenceFailed(ServeError):
    """The policy forward itself raised and the request's remaining deadline
    could not absorb a retry on another replica."""


class SwapRejected(ServeError):
    """A checkpoint promotion was refused (torn write, digest mismatch,
    structural change, or poisoned weights). The previous executable keeps
    serving — raised only by the *explicit* ``request_swap`` API; the
    background watcher just records the rejection."""
