"""Scripted load generator: the measurable proxy for production traffic.

Runs ``concurrency`` client threads against a started
:class:`~sheeprl_tpu.serve.server.PolicyServer` for ``duration_s``:

- **closed-loop** (default, ``rate_hz == 0``): each client fires its next
  request as soon as the previous one resolves — the classic
  concurrency-bounded load that finds the server's natural throughput.
- **open-loop** (``rate_hz > 0``): clients pace to an aggregate target rate,
  which can exceed capacity — the shape that drives shedding drills.

Each client is a :class:`~sheeprl_tpu.serve.client.ServeClient` (retry +
backoff on ``Overloaded``), observations are drawn per-request from a seeded
RNG, and the run report is a plain dict (ok/shed/expired counts, retries,
qps, p50/p95) that ``--serve-stats`` and the acceptance tests both consume —
the SLO claim in the docs is literally this report.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from sheeprl_tpu.serve.client import ServeClient
from sheeprl_tpu.serve.config import LoadConfig
from sheeprl_tpu.serve.errors import DeadlineExceeded, Overloaded, ServeError, ServerClosed
from sheeprl_tpu.serve.server import PolicyServer


def _default_obs_factory(server: PolicyServer) -> Callable[[np.random.Generator], Any]:
    """Random observations matching the policy's per-request spec."""
    import jax

    spec = server.policy.obs_spec

    def make(rng: np.random.Generator) -> Any:
        def leaf(s: Any) -> np.ndarray:
            if np.issubdtype(s.dtype, np.integer):
                return rng.integers(0, 255, size=tuple(s.shape)).astype(s.dtype)
            return rng.standard_normal(tuple(s.shape)).astype(s.dtype)

        return jax.tree.map(leaf, spec)

    return make


class _Worker(threading.Thread):
    def __init__(
        self,
        wid: int,
        server: PolicyServer,
        cfg: LoadConfig,
        stop: threading.Event,
        obs_factory: Callable[[np.random.Generator], Any],
        interval_s: float,
        experience_sink: Optional[Any] = None,
    ) -> None:
        super().__init__(name=f"loadgen-{wid}", daemon=True)
        self.client = ServeClient(
            server,
            max_retries=cfg.max_retries,
            timeout_s=(cfg.timeout_ms / 1e3) if cfg.timeout_ms else None,
            seed=cfg.seed * 10_000 + wid,
            experience_sink=experience_sink,
        )
        self._halt = stop
        self._obs_factory = obs_factory
        self._rng = np.random.default_rng(cfg.seed * 10_000 + wid)
        self._interval_s = interval_s  # 0: closed loop
        self.ok = 0
        self.shed = 0
        self.expired = 0
        self.errors = 0
        self.latencies: List[float] = []

    def run(self) -> None:
        next_t = time.monotonic()
        while not self._halt.is_set():
            if self._interval_s > 0:
                now = time.monotonic()
                if now < next_t:
                    if self._halt.wait(min(next_t - now, 0.05)):
                        break
                    continue
                next_t += self._interval_s
            obs = self._obs_factory(self._rng)
            t0 = time.monotonic()
            try:
                self.client.infer(obs)
            except Overloaded:
                self.shed += 1
            except DeadlineExceeded:
                self.expired += 1
            except ServerClosed:
                break
            except ServeError:
                self.errors += 1
            else:
                self.ok += 1
                self.latencies.append(time.monotonic() - t0)


def run_load(
    server: PolicyServer,
    cfg: LoadConfig,
    *,
    obs_factory: Optional[Callable[[np.random.Generator], Any]] = None,
    experience_sink: Optional[Any] = None,
) -> Dict[str, Any]:
    """Drive the load shape described by ``cfg``; returns the run report.

    ``experience_sink`` is handed to every worker's :class:`ServeClient` —
    the online-learning tap (``ExperienceBridge.observe``): the loadgen IS
    the served traffic the bridge learns from in the ``serve_train`` drills.
    """
    factory = obs_factory or _default_obs_factory(server)
    interval_s = cfg.concurrency / cfg.rate_hz if cfg.rate_hz > 0 else 0.0
    stop = threading.Event()
    workers = [
        _Worker(i, server, cfg, stop, factory, interval_s, experience_sink)
        for i in range(cfg.concurrency)
    ]
    t0 = time.monotonic()
    for w in workers:
        w.start()
    stop.wait(cfg.duration_s)
    stop.set()
    for w in workers:
        w.join(5.0)
    elapsed = time.monotonic() - t0

    lats = sorted(l for w in workers for l in w.latencies)

    def pct(q: float) -> Optional[float]:
        if not lats:
            return None
        idx = min(len(lats) - 1, max(0, int(np.ceil(q * len(lats))) - 1))
        return lats[idx] * 1e3

    ok = sum(w.ok for w in workers)
    report: Dict[str, Any] = {
        "duration_s": elapsed,
        "concurrency": cfg.concurrency,
        "mode": "open-loop" if cfg.rate_hz > 0 else "closed-loop",
        "target_rate_hz": cfg.rate_hz or None,
        "ok": ok,
        "shed": sum(w.shed for w in workers),
        "expired": sum(w.expired for w in workers),
        "errors": sum(w.errors for w in workers),
        "client_retries": sum(w.client.retries for w in workers),
        "client_rejections": sum(w.client.rejected for w in workers),
        "qps": ok / elapsed if elapsed > 0 else 0.0,
        "p50_ms": pct(0.50),
        "p95_ms": pct(0.95),
        "slo_ms": server.config.slo_ms,
    }
    p95 = report["p95_ms"]
    report["slo_met"] = bool(p95 is not None and p95 <= server.config.slo_ms)
    return report


def ramp_rates(start_hz: float, factor: float, steps: int) -> List[float]:
    """The stepped open-loop schedule: ``start_hz * factor**k`` per step."""
    if steps < 1:
        raise ValueError(f"ramp needs >= 1 step, got {steps}")
    if start_hz <= 0 or factor <= 1.0:
        raise ValueError(
            f"ramp needs start_hz > 0 and factor > 1, got start_hz={start_hz}, factor={factor}"
        )
    return [start_hz * (factor**k) for k in range(steps)]


def run_ramp(
    server: PolicyServer,
    cfg: LoadConfig,
    *,
    rates_hz: Optional[List[float]] = None,
    step_duration_s: Optional[float] = None,
    obs_factory: Optional[Callable[[np.random.Generator], Any]] = None,
    on_step: Optional[Callable[[int, float], None]] = None,
    experience_sink: Optional[Any] = None,
) -> Dict[str, Any]:
    """Stepped open-loop ramp that walks the offered rate up until the
    server stops meeting its SLO — the saturation-knee finder.

    Each step runs :func:`run_load` open-loop at one rate for
    ``step_duration_s`` (default: ``cfg.duration_s`` split across the
    steps). ``on_step(step_index, rate_hz)`` fires *before* each step — the
    chaos drills use it to kill a replica mid-ramp. The report's knee is the
    highest offered rate whose step still met the SLO with negligible
    shedding; ``max_good_qps`` is the throughput claim the regress cell
    gates (completed QPS while p95 <= SLO).
    """
    import dataclasses

    rates = rates_hz or ramp_rates(cfg.ramp_start_hz, cfg.ramp_factor, cfg.ramp_steps)
    per_step = step_duration_s if step_duration_s is not None else cfg.duration_s / len(rates)
    steps: List[Dict[str, Any]] = []
    knee_rate: Optional[float] = None
    max_good_qps = 0.0
    for k, rate in enumerate(rates):
        if on_step is not None:
            on_step(k, rate)
        step_cfg = dataclasses.replace(cfg, rate_hz=float(rate), duration_s=float(per_step))
        report = run_load(
            server, step_cfg, obs_factory=obs_factory, experience_sink=experience_sink
        )
        report["step"] = k
        report["offered_rate_hz"] = float(rate)
        attempts = report["ok"] + report["shed"] + report["expired"] + report["errors"]
        report["goodput_frac"] = (report["ok"] / attempts) if attempts else 0.0
        steps.append(report)
        if report["slo_met"] and report["goodput_frac"] >= 0.99:
            knee_rate = float(rate)
            max_good_qps = max(max_good_qps, float(report["qps"]))
    return {
        "mode": "ramp",
        "steps": steps,
        "offered_rates_hz": [float(r) for r in rates],
        "knee_rate_hz": knee_rate,
        "max_good_qps": max_good_qps,
        "saturated": bool(steps and not (steps[-1]["slo_met"] and steps[-1]["goodput_frac"] >= 0.99)),
        "slo_ms": server.config.slo_ms,
    }
